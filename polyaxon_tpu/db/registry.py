"""Durable run registry: the control plane's single source of truth.

Capability parity with the reference's Postgres models + Redis ephemeral
state:

- runs table        ~ ``db/models/experiments.py:48`` (``Experiment``) and the
                      other entity models (jobs, groups, pipelines) folded into
                      one polymorphic table keyed by ``kind``;
- statuses table    ~ per-entity ``*Status`` models
                      (``db/models/experiments.py:419``), with every write
                      gated by the lifecycle machine the way the reference
                      checks ``can_transition``
                      (``scheduler/tasks/experiments.py:72-77``);
- metrics table +   ~ ``ExperimentMetric`` rows + ``Experiment.set_metric``
  ``last_metric``     merging into JSONB (``db/models/experiments.py:294-298``);
- logs table        ~ the logs store written by ``logs_handlers/``;
- heartbeats        ~ ``db/redis/heartbeat.py`` (``RedisHeartBeat``);
- iterations        ~ ``ExperimentGroupIteration``
                      (``db/models/experiment_groups.py:414``);
- processes         ~ ``ExperimentJob`` rows (the replica unit,
                      ``db/models/experiment_jobs.py``) — here a gang's host
                      processes;
- activity table    ~ ``activitylogs/``;
- options table     ~ the DB-backed store of ``options/option.py:13-40``.

TPU-native differences: sqlite (WAL) instead of Postgres+Redis — the control
plane is a single service and workers report through run-dir files, so one
embedded, multi-process-safe database replaces both; statuses/metrics/logs
are ordinary rows so the streaming layer can tail them with a cursor.
"""

from __future__ import annotations

import functools
import json
import math
import sqlite3
import threading
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

from polyaxon_tpu.exceptions import PolyaxonTPUError
from polyaxon_tpu.lifecycles import StatusOptions as S, lifecycle_for_kind
from polyaxon_tpu.stats.metrics import labeled_key, split_labeled_key
from polyaxon_tpu.schemas.specifications import (
    BaseSpecification,
    specification_for_kind,
)


class RegistryError(PolyaxonTPUError):
    pass


_SCHEMA = """
CREATE TABLE IF NOT EXISTS runs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    uuid TEXT UNIQUE NOT NULL,
    kind TEXT NOT NULL,
    name TEXT,
    project TEXT NOT NULL DEFAULT 'default',
    spec TEXT NOT NULL,
    status TEXT NOT NULL,
    group_id INTEGER,
    pipeline_id INTEGER,
    original_id INTEGER,
    cloning_strategy TEXT,
    restarts INTEGER NOT NULL DEFAULT 0,
    tags TEXT NOT NULL DEFAULT '[]',
    last_metric TEXT NOT NULL DEFAULT '{}',
    outputs_path TEXT,
    code_ref TEXT,
    service_url TEXT,
    meta TEXT NOT NULL DEFAULT '{}',
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL,
    started_at REAL,
    finished_at REAL,
    archived_at REAL
);
CREATE INDEX IF NOT EXISTS ix_runs_kind ON runs (kind);
CREATE INDEX IF NOT EXISTS ix_runs_group ON runs (group_id);
CREATE INDEX IF NOT EXISTS ix_runs_status ON runs (status);

CREATE TABLE IF NOT EXISTS statuses (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    status TEXT NOT NULL,
    message TEXT,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_statuses_run ON statuses (run_id);

CREATE TABLE IF NOT EXISTS metrics (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    step INTEGER,
    vals TEXT NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_metrics_run ON metrics (run_id);

CREATE TABLE IF NOT EXISTS logs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    process_id INTEGER,
    line TEXT NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_logs_run ON logs (run_id);

CREATE TABLE IF NOT EXISTS spans (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    process_id INTEGER,
    trace_id TEXT,
    span_id TEXT,
    parent_id TEXT,
    name TEXT NOT NULL,
    thread TEXT,
    start REAL NOT NULL,
    duration REAL NOT NULL,
    attrs TEXT,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_spans_run ON spans (run_id);

CREATE TABLE IF NOT EXISTS heartbeats (
    run_id INTEGER PRIMARY KEY,
    last_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS progress (
    run_id INTEGER NOT NULL,
    process_id INTEGER NOT NULL,
    step INTEGER,
    epoch INTEGER,
    throughput REAL,
    at REAL NOT NULL,
    updated_at REAL NOT NULL,
    PRIMARY KEY (run_id, process_id)
);

CREATE TABLE IF NOT EXISTS anomalies (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    process_id INTEGER,
    kind TEXT NOT NULL,
    message TEXT,
    attrs TEXT,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_anomalies_run ON anomalies (run_id);

CREATE TABLE IF NOT EXISTS utilization (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    process_id INTEGER,
    seq INTEGER,
    source TEXT,
    wall_s REAL,
    buckets TEXT,
    steps INTEGER,
    tokens INTEGER,
    flops REAL,
    goodput REAL,
    mfu REAL,
    tokens_per_device_s REAL,
    compile_s REAL,
    compile_events INTEGER,
    hbm_peak_bytes REAL,
    devices INTEGER,
    device_kind TEXT,
    peak_flops_per_s REAL,
    final INTEGER NOT NULL DEFAULT 0,
    attrs TEXT,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_utilization_run ON utilization (run_id);

CREATE TABLE IF NOT EXISTS iterations (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    group_id INTEGER NOT NULL,
    number INTEGER NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL,
    UNIQUE (group_id, number)
);

CREATE TABLE IF NOT EXISTS processes (
    run_id INTEGER NOT NULL,
    process_id INTEGER NOT NULL,
    pid INTEGER,
    status TEXT NOT NULL,
    exit_code INTEGER,
    report_offset INTEGER NOT NULL DEFAULT 0,
    updated_at REAL NOT NULL,
    PRIMARY KEY (run_id, process_id)
);

CREATE TABLE IF NOT EXISTS activity (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    event_type TEXT NOT NULL,
    context TEXT NOT NULL,
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS options (
    key TEXT PRIMARY KEY,
    value TEXT NOT NULL
);

CREATE TABLE IF NOT EXISTS projects (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    description TEXT,
    owner TEXT,
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS project_collaborators (
    project_name TEXT NOT NULL,
    username TEXT NOT NULL,
    created_at REAL NOT NULL,
    PRIMARY KEY (project_name, username)
);

CREATE TABLE IF NOT EXISTS chart_views (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    name TEXT NOT NULL,
    charts TEXT NOT NULL,
    meta TEXT NOT NULL DEFAULT '{}',
    owner TEXT,
    created_at REAL NOT NULL,
    UNIQUE (run_id, name)
);

CREATE TABLE IF NOT EXISTS project_ci (
    project_name TEXT PRIMARY KEY,
    spec TEXT NOT NULL,
    last_code_ref TEXT,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS searches (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    query TEXT NOT NULL,
    owner TEXT,
    created_at REAL NOT NULL
);

CREATE TABLE IF NOT EXISTS bookmarks (
    run_id INTEGER NOT NULL,
    owner TEXT NOT NULL DEFAULT '',
    created_at REAL NOT NULL,
    PRIMARY KEY (run_id, owner)
);

CREATE TABLE IF NOT EXISTS users (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    username TEXT UNIQUE NOT NULL,
    token_hash TEXT UNIQUE NOT NULL,
    role TEXT NOT NULL DEFAULT 'user',
    created_at REAL NOT NULL,
    last_used_at REAL
);

CREATE TABLE IF NOT EXISTS devices (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT UNIQUE NOT NULL,
    accelerator TEXT NOT NULL,
    chips INTEGER NOT NULL,
    num_hosts INTEGER NOT NULL DEFAULT 1,
    run_id INTEGER,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_devices_family ON devices (accelerator);

CREATE TABLE IF NOT EXISTS device_claims (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    device_id INTEGER NOT NULL REFERENCES devices (id) ON DELETE CASCADE,
    run_id INTEGER NOT NULL,
    chips INTEGER NOT NULL,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_claims_device ON device_claims (device_id);
CREATE INDEX IF NOT EXISTS ix_claims_run ON device_claims (run_id);

CREATE TABLE IF NOT EXISTS commands (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    uuid TEXT UNIQUE NOT NULL,
    kind TEXT NOT NULL,
    process_id INTEGER,
    payload TEXT NOT NULL DEFAULT '{}',
    status TEXT NOT NULL,
    message TEXT,
    acks TEXT NOT NULL DEFAULT '{}',
    expected INTEGER NOT NULL DEFAULT 1,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_commands_run ON commands (run_id);

CREATE TABLE IF NOT EXISTS captures (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    capture_id TEXT NOT NULL,
    process_id INTEGER NOT NULL,
    status TEXT NOT NULL,
    start_step INTEGER,
    num_steps INTEGER,
    started_at REAL,
    finished_at REAL,
    artifacts TEXT NOT NULL DEFAULT '[]',
    message TEXT,
    attrs TEXT,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL,
    UNIQUE (run_id, capture_id, process_id)
);
CREATE INDEX IF NOT EXISTS ix_captures_run ON captures (run_id);

CREATE TABLE IF NOT EXISTS alerts (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    rule TEXT NOT NULL,
    state TEXT NOT NULL,
    severity TEXT NOT NULL,
    message TEXT,
    value REAL,
    for_s REAL,
    episodes INTEGER NOT NULL DEFAULT 0,
    pending_since REAL,
    fired_at REAL,
    resolved_at REAL,
    attrs TEXT,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL,
    UNIQUE (run_id, rule)
);
CREATE INDEX IF NOT EXISTS ix_alerts_run ON alerts (run_id);

CREATE TABLE IF NOT EXISTS remediations (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_id INTEGER NOT NULL,
    action TEXT NOT NULL,
    trigger TEXT,
    status TEXT NOT NULL,
    message TEXT,
    attrs TEXT,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_remediations_run ON remediations (run_id);

CREATE TABLE IF NOT EXISTS metric_samples (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    name TEXT NOT NULL,
    run_id INTEGER,
    at REAL NOT NULL,
    value REAL NOT NULL,
    agg TEXT NOT NULL DEFAULT 'raw',
    vmin REAL,
    vmax REAL,
    vsum REAL,
    vcount INTEGER,
    created_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS ix_metric_samples_name ON metric_samples (name, at);
CREATE INDEX IF NOT EXISTS ix_metric_samples_run ON metric_samples (run_id);

CREATE TABLE IF NOT EXISTS metric_baselines (
    project TEXT NOT NULL,
    kind TEXT NOT NULL,
    series TEXT NOT NULL,
    ewma REAL NOT NULL,
    ewvar REAL NOT NULL DEFAULT 0,
    count INTEGER NOT NULL DEFAULT 0,
    last_value REAL,
    last_run_id INTEGER,
    updated_at REAL NOT NULL,
    PRIMARY KEY (project, kind, series)
);
"""


class CommandStatus:
    """Lifecycle of a worker-directed command (the run command bus).

    PENDING (enqueued, mailbox files written) → ACKED (at least one worker
    picked it up) → COMPLETE/FAILED (every targeted worker reported a
    terminal per-process state).  EXPIRED is the control plane's own
    verdict: the run finished (or was already finished) before the gang
    honored the command — a typed answer instead of a hang.
    """

    PENDING = "pending"
    ACKED = "acked"
    COMPLETE = "complete"
    FAILED = "failed"
    EXPIRED = "expired"

    TERMINAL = (COMPLETE, FAILED, EXPIRED)


def command_ack_state(ack: Any) -> Optional[str]:
    """The per-process state of an ``acks`` value — plain string for
    attr-less acks, ``{"state":..., "attrs":...}`` dicts otherwise."""
    if isinstance(ack, dict):
        return ack.get("state")
    return ack


def command_ack_attrs(ack: Any) -> Dict[str, Any]:
    """Handler result attrs folded into an ``acks`` value ({} if none)."""
    if isinstance(ack, dict):
        return ack.get("attrs") or {}
    return {}


class AlertState:
    """Lifecycle of an alert-rule evaluation (Alertmanager-shaped).

    PENDING (predicate violated, inside the ``for_s`` hold-down) → FIRING
    (held long enough; notifications routed) → RESOLVED (predicate healthy
    again, or the run finished mid-episode).  A pending alert that recovers
    before the hold-down elapses is dropped silently — that is the flap
    suppression.  One row per (run, rule) holds the latest state; every
    state *transition* re-inserts the row with a fresh id so since_id
    pagers and the WS tail see transitions, not steady-state churn.
    """

    PENDING = "pending"
    FIRING = "firing"
    RESOLVED = "resolved"

    ACTIVE = (PENDING, FIRING)


class AlertSeverity:
    INFO = "info"
    WARNING = "warning"
    CRITICAL = "critical"

    ALL = (INFO, WARNING, CRITICAL)


class RemediationStatus:
    """Lifecycle of a remediation action (the detection→action loop).

    PENDING (decided, not yet acting) → IN_PROGRESS (command issued /
    process signalled) → SUCCEEDED / FAILED.  SKIPPED records a decision
    *not* to act (budget exhausted, topology not shrinkable) so the run's
    timeline explains inaction; EXPIRED is the control plane closing rows
    left open when the run reached a terminal state.
    """

    PENDING = "pending"
    IN_PROGRESS = "in_progress"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    SKIPPED = "skipped"
    EXPIRED = "expired"

    OPEN = (PENDING, IN_PROGRESS)
    TERMINAL = (SUCCEEDED, FAILED, SKIPPED, EXPIRED)


def accelerator_family(accelerator: str) -> str:
    """``v5e-16`` → ``v5e``; ``cpu``/``cpu-1`` → ``cpu`` — the platform
    generation a gang can actually run on (chips aren't fungible across
    generations the way the reference's ``NodeGPU`` count was)."""
    return accelerator.split("-", 1)[0]


@dataclass
class Run:
    """A registry row. ``spec`` is lazily parsed into a typed specification."""

    id: int
    uuid: str
    kind: str
    name: Optional[str]
    project: str
    status: str
    spec_data: Dict[str, Any]
    group_id: Optional[int] = None
    pipeline_id: Optional[int] = None
    original_id: Optional[int] = None
    cloning_strategy: Optional[str] = None
    restarts: int = 0
    tags: List[str] = field(default_factory=list)
    last_metric: Dict[str, Any] = field(default_factory=dict)
    outputs_path: Optional[str] = None
    code_ref: Optional[str] = None
    #: Reachable URL of a serving service gang (notebook/tensorboard kinds).
    service_url: Optional[str] = None
    #: Control-plane scratch attrs surviving restarts (e.g. the ``elastic``
    #: topology override recorded by straggler eviction).
    meta: Dict[str, Any] = field(default_factory=dict)
    created_at: float = 0.0
    updated_at: float = 0.0
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    #: Set = hidden from default listings, eligible for the retention
    #: purge cron (reference archived managers + ``crons/tasks/deletion.py``).
    archived_at: Optional[float] = None

    @property
    def spec(self) -> BaseSpecification:
        cls = specification_for_kind(self.kind)
        return cls.model_validate(self.spec_data)

    @property
    def lifecycle(self):
        return lifecycle_for_kind(self.kind)

    @property
    def is_done(self) -> bool:
        return self.lifecycle.is_done(self.status)


def _row_to_run(row: sqlite3.Row) -> Run:
    return Run(
        id=row["id"],
        uuid=row["uuid"],
        kind=row["kind"],
        name=row["name"],
        project=row["project"],
        status=row["status"],
        spec_data=json.loads(row["spec"]),
        group_id=row["group_id"],
        pipeline_id=row["pipeline_id"],
        original_id=row["original_id"],
        cloning_strategy=row["cloning_strategy"],
        restarts=row["restarts"],
        tags=json.loads(row["tags"]),
        last_metric=json.loads(row["last_metric"]),
        outputs_path=row["outputs_path"],
        code_ref=row["code_ref"],
        service_url=row["service_url"],
        meta=json.loads(row["meta"] or "{}"),
        created_at=row["created_at"],
        updated_at=row["updated_at"],
        started_at=row["started_at"],
        finished_at=row["finished_at"],
        archived_at=row["archived_at"],
    )


class _TimedLock:
    """``threading.Lock`` wrapper observing wait + hold time on a stats
    backend (``registry_lock_wait_s`` / ``registry_lock_hold_s``).

    Assigned to ``RunRegistry._lock`` so the ~60 ``with self._lock``
    write sites — and graft-lint GL003's lexical lock-discipline check —
    keep working unchanged.  With no stats attached the wrapper costs one
    attribute read per acquisition; ``_held_at`` is only touched by the
    holding thread, so it needs no extra synchronization.
    """

    __slots__ = ("_lock", "_owner", "_held_at")

    def __init__(self, owner: "RunRegistry") -> None:
        self._lock = threading.Lock()
        self._owner = owner
        self._held_at = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        stats = self._owner._stats
        if stats is None:
            return self._lock.acquire(blocking, timeout)
        t0 = time.perf_counter()
        got = self._lock.acquire(blocking, timeout)
        if got:
            stats.observe("registry_lock_wait_s", time.perf_counter() - t0)
            self._held_at = time.perf_counter()
        return got

    def release(self) -> None:
        stats = self._owner._stats
        if stats is not None and self._held_at:
            stats.observe(
                "registry_lock_hold_s", time.perf_counter() - self._held_at
            )
            self._held_at = 0.0
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "_TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


#: Operation families for ``registry_op_s{op=...}`` — a bounded label set
#: (GL007 checks label values stay bounded; raw method names would be ~100
#: series, these are 6).  Methods not named here classify by prefix.
_INGEST_OPS = frozenset({
    "add_metric", "add_log", "add_logs", "add_span", "add_utilization",
    "add_anomaly", "upsert_progress", "ping_heartbeat", "set_report_offset",
    "upsert_process", "upsert_capture", "record_activity",
    "add_metric_samples", "fold_metric_baseline",
})
_LIFECYCLE_OPS = frozenset({
    "create_run", "set_status", "update_run", "merge_run_meta",
    "archive_run", "restore_run", "delete_run",
})
_RETENTION_OPS = frozenset({
    "clean_old_rows", "expire_commands", "expire_remediations",
})
_READ_PREFIXES = (
    "get_", "list_", "last_", "count_", "has_", "project_", "free_",
    "queued_", "zombie_", "stale_", "archived_", "usage_", "advance_",
)


def _op_family(name: str) -> str:
    if name in _INGEST_OPS:
        return "ingest"
    if name in _LIFECYCLE_OPS:
        return "lifecycle"
    if name in _RETENTION_OPS:
        return "retention"
    if name in ("upsert_alert", "delete_alert"):
        return "alerts"
    if name.startswith(_READ_PREFIXES):
        return "read"
    return "write"


def _timed_op(name: str, fn: Any) -> Any:
    """Per-operation-family latency wrapper applied to every public
    ``RunRegistry`` method: with a stats backend attached each call lands
    in ``registry_op_s{op=<family>}``; without one the overhead is a
    single attribute check."""
    key = labeled_key("registry_op_s", op=_op_family(name))

    @functools.wraps(fn)
    def wrapper(self: "RunRegistry", *args: Any, **kwargs: Any) -> Any:
        stats = self._stats
        if stats is None:
            return fn(self, *args, **kwargs)
        t0 = time.perf_counter()
        try:
            return fn(self, *args, **kwargs)
        finally:
            stats.observe(key, time.perf_counter() - t0)

    wrapper.__wrapped_op__ = name
    return wrapper


class RunRegistry:
    """Sqlite-backed run registry, safe across threads and processes.

    Every status write passes the lifecycle gate; a rejected transition is
    reported (``False``) rather than raised, mirroring how the reference
    silently skips illegal writes after checking ``can_transition``.
    """

    #: Self-telemetry backend (None = uninstrumented).  A class attribute
    #: so the lock/op wrappers are safe during ``__init__`` too.
    _stats: Optional[Any] = None

    def __init__(self, path: Union[str, Path] = ":memory:") -> None:
        self.path = str(path)
        self._local = threading.local()
        self._lock = _TimedLock(self)
        with self._conn() as conn:
            conn.executescript(_SCHEMA)
            # In-place migration for registries created before the durable
            # report-offset column (CREATE IF NOT EXISTS won't add it).
            cols = {r[1] for r in conn.execute("PRAGMA table_info(processes)")}
            if "report_offset" not in cols:
                conn.execute(
                    "ALTER TABLE processes ADD COLUMN"
                    " report_offset INTEGER NOT NULL DEFAULT 0"
                )
            run_cols = {r[1] for r in conn.execute("PRAGMA table_info(runs)")}
            if "service_url" not in run_cols:
                conn.execute("ALTER TABLE runs ADD COLUMN service_url TEXT")
            proj_cols = {r[1] for r in conn.execute("PRAGMA table_info(projects)")}
            if "owner" not in proj_cols:
                # Pre-ownership projects stay ownerless (= open access).
                conn.execute("ALTER TABLE projects ADD COLUMN owner TEXT")
            user_cols = {r[1] for r in conn.execute("PRAGMA table_info(users)")}
            if "sso_provider" not in user_cols:
                # NULL = locally-created user; set = which SSO provider
                # owns this identity (no cross-takeover by name collision).
                conn.execute("ALTER TABLE users ADD COLUMN sso_provider TEXT")
            if "archived_at" not in run_cols:
                conn.execute("ALTER TABLE runs ADD COLUMN archived_at REAL")
            if "meta" not in run_cols:
                conn.execute(
                    "ALTER TABLE runs ADD COLUMN meta TEXT NOT NULL DEFAULT '{}'"
                )

    # -- connection management ------------------------------------------------
    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30.0, check_same_thread=False)
            conn.row_factory = sqlite3.Row
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA busy_timeout=30000")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    # -- self-telemetry --------------------------------------------------------
    def attach_stats(self, stats: Optional[Any]) -> None:
        """Turn on registry self-telemetry: per-operation-family latency
        (``registry_op_s{op=...}``) plus write-lock wait/hold histograms
        (``registry_lock_wait_s`` / ``registry_lock_hold_s``) on ``stats``.
        The orchestrator calls this once its stats backend exists (the
        registry is constructed first — it *stores* the config the stats
        backend choice reads from).  ``None`` detaches."""
        self._stats = stats

    # -- runs ----------------------------------------------------------------
    def create_run(
        self,
        spec: Union[BaseSpecification, Dict[str, Any]],
        *,
        name: Optional[str] = None,
        project: str = "default",
        group_id: Optional[int] = None,
        pipeline_id: Optional[int] = None,
        original_id: Optional[int] = None,
        cloning_strategy: Optional[str] = None,
        tags: Optional[Iterable[str]] = None,
        status: str = S.CREATED,
    ) -> Run:
        if isinstance(spec, BaseSpecification):
            spec_data = spec.to_dict()
            kind = spec.kind
            name = name or spec.name
            spec_tags = spec.tags
        else:
            spec_data = dict(spec)
            kind = spec_data.get("kind")
            if kind is None:
                raise RegistryError("spec dict must carry a 'kind'")
            spec_tags = spec_data.get("tags", [])
        lifecycle = lifecycle_for_kind(kind)
        if not lifecycle.can_transition(None, status):
            raise RegistryError(f"Runs of kind {kind!r} cannot be born {status!r}")
        now = time.time()
        run_uuid = uuid_mod.uuid4().hex
        all_tags = sorted(set(spec_tags) | set(tags or ()))
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                """INSERT INTO runs (uuid, kind, name, project, spec, status,
                                     group_id, pipeline_id, original_id,
                                     cloning_strategy, tags, created_at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)""",
                (
                    run_uuid,
                    kind,
                    name,
                    project,
                    json.dumps(spec_data),
                    status,
                    group_id,
                    pipeline_id,
                    original_id,
                    cloning_strategy,
                    json.dumps(all_tags),
                    now,
                    now,
                ),
            )
            run_id = cur.lastrowid
            conn.execute(
                "INSERT INTO statuses (run_id, status, message, created_at) VALUES (?, ?, ?, ?)",
                (run_id, status, None, now),
            )
        return self.get_run(run_id)

    def get_run(self, run: Union[int, str]) -> Run:
        col = "uuid" if isinstance(run, str) else "id"
        row = self._conn().execute(f"SELECT * FROM runs WHERE {col} = ?", (run,)).fetchone()
        if row is None:
            raise RegistryError(f"No run with {col}={run!r}")
        return _row_to_run(row)

    def list_runs(
        self,
        *,
        kind: Optional[str] = None,
        project: Optional[str] = None,
        group_id: Optional[int] = None,
        pipeline_id: Optional[int] = None,
        statuses: Optional[Sequence[str]] = None,
        limit: Optional[int] = None,
        offset: int = 0,
        extra_where: Optional[Tuple[Sequence[str], Sequence[Any]]] = None,
        archived: Optional[bool] = None,
    ) -> List[Run]:
        """``extra_where`` is (clauses, params) compiled by the query DSL
        builder — pushed-down conditions on real columns (the reference
        compiles its DSL into queryset filters, ``query/builder.py:18-31``).

        ``archived`` mirrors the reference's default/archived model
        managers (its archives API lists them separately): False = live
        runs only, True = archived only, None = both.  The default is
        None — include everything — because the control plane itself
        (polyflow dag checks, hpsearch trial accounting, recovery) must
        see archived rows; USER listing surfaces (API/CLI) pass False."""
        clauses, params = [], []
        if archived is False:
            clauses.append("archived_at IS NULL")
        elif archived is True:
            clauses.append("archived_at IS NOT NULL")
        if extra_where is not None:
            clauses.extend(extra_where[0])
            params.extend(extra_where[1])
        if kind is not None:
            clauses.append("kind = ?")
            params.append(kind)
        if project is not None:
            clauses.append("project = ?")
            params.append(project)
        if group_id is not None:
            clauses.append("group_id = ?")
            params.append(group_id)
        if pipeline_id is not None:
            clauses.append("pipeline_id = ?")
            params.append(pipeline_id)
        if statuses:
            clauses.append(f"status IN ({','.join('?' * len(statuses))})")
            params.extend(statuses)
        where = f"WHERE {' AND '.join(clauses)}" if clauses else ""
        sql = f"SELECT * FROM runs {where} ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)} OFFSET {int(offset)}"
        rows = self._conn().execute(sql, params).fetchall()
        return [_row_to_run(r) for r in rows]

    def update_run(self, run_id: int, **fields: Any) -> None:
        allowed = {
            "name",
            "project",
            "outputs_path",
            "code_ref",
            "group_id",
            "pipeline_id",
            "original_id",
            "cloning_strategy",
            "restarts",
            "service_url",
        }
        unknown = set(fields) - allowed
        if unknown:
            raise RegistryError(f"Cannot update fields {sorted(unknown)}")
        if not fields:
            return
        sets = ", ".join(f"{k} = ?" for k in fields)
        with self._lock, self._conn() as conn:
            conn.execute(
                f"UPDATE runs SET {sets}, updated_at = ? WHERE id = ?",
                (*fields.values(), time.time(), run_id),
            )

    def merge_run_meta(self, run_id: int, **patch: Any) -> Dict[str, Any]:
        """Shallow-merge keys into the run's control-plane ``meta`` blob
        under the write lock (read-merge-write, so concurrent patches to
        different keys never clobber each other).  A key set to ``None``
        is removed.  Returns the merged blob."""
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT meta FROM runs WHERE id = ?", (run_id,)
            ).fetchone()
            if row is None:
                raise RegistryError(f"Run {run_id} does not exist")
            meta = json.loads(row["meta"] or "{}")
            for key, value in patch.items():
                if value is None:
                    meta.pop(key, None)
                else:
                    meta[key] = value
            conn.execute(
                "UPDATE runs SET meta = ?, updated_at = ? WHERE id = ?",
                (json.dumps(meta), time.time(), run_id),
            )
        return meta

    # -- archival + deletion ---------------------------------------------------
    # Parity: the reference's archived model managers + archives API
    # (``api/archives/``) and its archive-deletion beat pipeline
    # (``crons/tasks/deletion.py`` → ``scheduler/tasks/deletion.py``,
    # scheduled at ``config_settings/celery_settings.py:740-860``).  The
    # registry only flips rows; stopping gangs and GC-ing artifacts is the
    # orchestrator's job (it owns the spawner and the stores).

    def archive_run(self, run_id: int) -> bool:
        """Hide a run (and its children — a group's trials, a pipeline's
        ops) from user listings; returns False if already archived.
        Archived runs keep full history (statuses/metrics/logs) until the
        retention cron or an explicit delete purges them.  Cascading here
        keeps archive symmetric with delete_run's cascade: nothing can be
        purged by the parent's retention sweep while still presenting as
        a live run in the default view."""
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            family = self._family_fixpoint(run_id)
            marks = ",".join("?" * len(family))
            cur = conn.execute(
                f"UPDATE runs SET archived_at = ?, updated_at = ?"
                f" WHERE id IN ({marks}) AND archived_at IS NULL",
                (now, now, *family),
            )
        return cur.rowcount > 0

    def restore_run(self, run_id: int) -> bool:
        """Un-archive a run and its children (the reference archives
        API's restore endpoints)."""
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            family = self._family_fixpoint(run_id)
            marks = ",".join("?" * len(family))
            cur = conn.execute(
                f"UPDATE runs SET archived_at = NULL, updated_at = ?"
                f" WHERE id IN ({marks}) AND archived_at IS NOT NULL",
                (time.time(), *family),
            )
        return cur.rowcount > 0

    def _family_ids(self, run_id: int) -> List[int]:
        """``run_id`` plus every transitive child (trials via group_id,
        pipeline ops via pipeline_id).  Raises if the root is missing."""
        if not self._run_exists(run_id):
            raise RegistryError(f"No run with id={run_id}")
        out: List[int] = []
        frontier = [run_id]
        seen = set()
        while frontier:
            rid = frontier.pop()
            if rid in seen:
                continue
            seen.add(rid)
            out.append(rid)
            for child in self._conn().execute(
                "SELECT id FROM runs WHERE group_id = ? OR pipeline_id = ?",
                (rid, rid),
            ):
                frontier.append(child["id"])
        return out

    def _family_fixpoint(self, run_id: int) -> List[int]:
        """Family walk re-run until STABLE — called with the write lock
        held (``_lock`` + ``BEGIN IMMEDIATE``), so trial/pipeline children
        created concurrently with an archive/restore/delete cannot land
        between the walk and the mutation and escape the cascade.  The
        re-walk catches children inserted during the first traversal."""
        family = self._family_ids(run_id)
        while True:
            seen = set(family)
            fresh = [i for i in self._family_ids(run_id) if i not in seen]
            if not fresh:
                return family
            family += fresh

    def _run_exists(self, run_id: int) -> bool:
        return (
            self._conn()
            .execute("SELECT 1 FROM runs WHERE id = ?", (run_id,))
            .fetchone()
            is not None
        )

    def delete_run(self, run_id: int) -> List[Run]:
        """Purge a run and every row that references it, CASCADING to its
        children (a group's trials, a pipeline's operations — the reference
        gets this from FK on_delete cascades).  Returns the deleted Run
        records (pre-delete snapshots) so the caller can GC outputs dirs
        and store artifacts — the registry never touches the filesystem."""
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            victims = [self.get_run(rid) for rid in self._family_fixpoint(run_id)]
            ids = [r.id for r in victims]
            marks = ",".join("?" * len(ids))
            # Free any held slices before the claim rows go away.
            conn.execute(
                f"UPDATE devices SET run_id = NULL, updated_at = ?"
                f" WHERE run_id IN ({marks})",
                (time.time(), *ids),
            )
            for table, col in (
                ("device_claims", "run_id"),
                ("statuses", "run_id"),
                ("metrics", "run_id"),
                ("logs", "run_id"),
                ("spans", "run_id"),
                ("progress", "run_id"),
                ("anomalies", "run_id"),
                ("utilization", "run_id"),
                ("metric_samples", "run_id"),
                ("commands", "run_id"),
                ("captures", "run_id"),
                ("alerts", "run_id"),
                ("remediations", "run_id"),
                ("heartbeats", "run_id"),
                ("processes", "run_id"),
                ("bookmarks", "run_id"),
                ("chart_views", "run_id"),
                ("iterations", "group_id"),
                ("runs", "id"),
            ):
                conn.execute(
                    f"DELETE FROM {table} WHERE {col} IN ({marks})", ids
                )
        return victims

    def archived_runs_older_than(
        self, seconds: float, now: Optional[float] = None
    ) -> List[Run]:
        """Archived runs past the retention horizon — the purge cron's
        worklist (reference ``CLEANING_INTERVALS_ARCHIVES`` date check).
        Children of an archived group/pipeline are purged with their
        parent via delete_run's cascade, so only top-level rows return."""
        cutoff = (now or time.time()) - seconds
        rows = self._conn().execute(
            "SELECT * FROM runs WHERE archived_at IS NOT NULL AND archived_at < ?"
            " ORDER BY id",
            (cutoff,),
        ).fetchall()
        return [_row_to_run(r) for r in rows]

    # -- statuses -------------------------------------------------------------
    def set_status(
        self,
        run_id: int,
        status: str,
        message: Optional[str] = None,
    ) -> bool:
        """Gated status write; returns whether the transition was applied."""
        now = time.time()
        with self._lock, self._conn() as conn:
            # The lifecycle gate is check-then-act: take the write lock up
            # front so concurrent *processes* (the in-process lock can't see
            # them) serialize the whole read-check-write.
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT kind, status, started_at FROM runs WHERE id = ?", (run_id,)
            ).fetchone()
            if row is None:
                raise RegistryError(f"No run with id={run_id}")
            lifecycle = lifecycle_for_kind(row["kind"])
            if not lifecycle.can_transition(row["status"], status):
                return False
            started_at = row["started_at"]
            # Strictly the running phase: QUEUED/BUILDING time is waiting
            # (admission, snapshots), not runtime.
            if started_at is None and status in lifecycle.RUNNING_STATUS:
                started_at = now
            finished_at = now if lifecycle.is_done(status) else None
            conn.execute(
                """UPDATE runs SET status = ?, updated_at = ?, started_at = ?,
                                   finished_at = COALESCE(?, finished_at)
                   WHERE id = ?""",
                (status, now, started_at, finished_at, run_id),
            )
            conn.execute(
                "INSERT INTO statuses (run_id, status, message, created_at) VALUES (?, ?, ?, ?)",
                (run_id, status, message, now),
            )
        return True

    def get_statuses(self, run_id: int) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT status, message, created_at FROM statuses WHERE run_id = ? ORDER BY id",
            (run_id,),
        ).fetchall()
        return [dict(r) for r in rows]

    def count_by_status(
        self, *, kind: Optional[str] = None, group_id: Optional[int] = None
    ) -> Dict[str, int]:
        clauses, params = [], []
        if kind is not None:
            clauses.append("kind = ?")
            params.append(kind)
        if group_id is not None:
            clauses.append("group_id = ?")
            params.append(group_id)
        where = f"WHERE {' AND '.join(clauses)}" if clauses else ""
        rows = self._conn().execute(
            f"SELECT status, COUNT(*) AS n FROM runs {where} GROUP BY status", params
        ).fetchall()
        return {r["status"]: r["n"] for r in rows}

    # -- metrics --------------------------------------------------------------
    def add_metric(
        self, run_id: int, values: Dict[str, Any], step: Optional[int] = None
    ) -> None:
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT last_metric FROM runs WHERE id = ?", (run_id,)
            ).fetchone()
            if row is None:
                raise RegistryError(f"No run with id={run_id}")
            merged = json.loads(row["last_metric"])
            merged.update(values)
            conn.execute(
                "INSERT INTO metrics (run_id, step, vals, created_at) VALUES (?, ?, ?, ?)",
                (run_id, step, json.dumps(values), now),
            )
            conn.execute(
                "UPDATE runs SET last_metric = ?, updated_at = ? WHERE id = ?",
                (json.dumps(merged), now, run_id),
            )

    def get_metrics(self, run_id: int, since_id: int = 0) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT id, step, vals, created_at FROM metrics WHERE run_id = ? AND id > ? ORDER BY id",
            (run_id, since_id),
        ).fetchall()
        return [
            {
                "id": r["id"],
                "step": r["step"],
                "values": json.loads(r["vals"]),
                "created_at": r["created_at"],
            }
            for r in rows
        ]

    def last_metric(self, run_id: int) -> Dict[str, Any]:
        return self.get_run(run_id).last_metric

    # -- logs -----------------------------------------------------------------
    def add_log(
        self,
        run_id: int,
        line: str,
        process_id: Optional[int] = None,
        created_at: Optional[float] = None,
    ) -> None:
        with self._lock, self._conn() as conn:
            conn.execute(
                "INSERT INTO logs (run_id, process_id, line, created_at) VALUES (?, ?, ?, ?)",
                (run_id, process_id, line, created_at or time.time()),
            )

    def add_logs(
        self, run_id: int, lines: Iterable[Tuple[Optional[int], str]]
    ) -> None:
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.executemany(
                "INSERT INTO logs (run_id, process_id, line, created_at) VALUES (?, ?, ?, ?)",
                [(run_id, pid, line, now) for pid, line in lines],
            )

    def get_logs(
        self,
        run_id: int,
        *,
        process_id: Optional[int] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        sql = "SELECT id, process_id, line, created_at FROM logs WHERE run_id = ? AND id > ?"
        params: List[Any] = [run_id, since_id]
        if process_id is not None:
            sql += " AND process_id = ?"
            params.append(process_id)
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        return [dict(r) for r in rows]

    # -- spans ----------------------------------------------------------------
    def add_span(
        self,
        run_id: int,
        span: Dict[str, Any],
        process_id: Optional[int] = None,
    ) -> None:
        """Store one finished tracer span (a ``span`` report event).

        ``span`` is the record shape tracking/trace.py emits — unknown
        keys are folded into ``attrs`` so the channel can grow fields
        without a schema change."""
        known = {
            "name",
            "trace_id",
            "span_id",
            "parent_id",
            "thread",
            "start",
            "duration",
            "process_id",
            "attrs",
        }
        attrs = dict(span.get("attrs") or {})
        for key, value in span.items():
            if key not in known and key not in ("type", "ts"):
                attrs[key] = value
        if process_id is None:
            process_id = span.get("process_id")
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO spans
                   (run_id, process_id, trace_id, span_id, parent_id, name,
                    thread, start, duration, attrs, created_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)""",
                (
                    run_id,
                    process_id,
                    span.get("trace_id"),
                    span.get("span_id"),
                    span.get("parent_id"),
                    str(span.get("name") or "span"),
                    span.get("thread"),
                    float(span.get("start") or 0.0),
                    float(span.get("duration") or 0.0),
                    json.dumps(attrs) if attrs else None,
                    time.time(),
                ),
            )

    def get_spans(
        self,
        run_id: int,
        *,
        process_id: Optional[int] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Spans for a run ordered by wall-clock start (timeline order)."""
        sql = (
            "SELECT id, process_id, trace_id, span_id, parent_id, name,"
            " thread, start, duration, attrs, created_at"
            " FROM spans WHERE run_id = ? AND id > ?"
        )
        params: List[Any] = [run_id, since_id]
        if process_id is not None:
            sql += " AND process_id = ?"
            params.append(process_id)
        sql += " ORDER BY start, id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        out: List[Dict[str, Any]] = []
        for r in rows:
            span = dict(r)
            span["attrs"] = json.loads(span["attrs"]) if span["attrs"] else {}
            out.append(span)
        return out

    # -- utilization ledger ----------------------------------------------------
    def add_utilization(
        self,
        run_id: int,
        row: Dict[str, Any],
        process_id: Optional[int] = None,
    ) -> None:
        """Store one utilization-ledger row (a ``ledger`` report event).

        ``row`` is the record shape tracking/ledger.py emits — unknown
        keys are folded into ``attrs`` so the channel can grow fields
        without a schema change."""
        known = {
            "seq",
            "source",
            "wall_s",
            "buckets",
            "steps",
            "tokens",
            "flops",
            "goodput",
            "mfu",
            "tokens_per_device_s",
            "compile_s",
            "compile_events",
            "hbm_peak_bytes",
            "devices",
            "device_kind",
            "peak_flops_per_s",
            "final",
            "process_id",
            "attrs",
        }
        attrs = dict(row.get("attrs") or {})
        for key, value in row.items():
            if key not in known and key not in ("type", "ts"):
                attrs[key] = value
        if process_id is None:
            process_id = row.get("process_id")
        buckets = row.get("buckets") or {}
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO utilization
                   (run_id, process_id, seq, source, wall_s, buckets, steps,
                    tokens, flops, goodput, mfu, tokens_per_device_s,
                    compile_s, compile_events, hbm_peak_bytes, devices,
                    device_kind, peak_flops_per_s, final, attrs, created_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)""",
                (
                    run_id,
                    process_id,
                    int(row.get("seq") or 0),
                    str(row.get("source") or "train"),
                    float(row.get("wall_s") or 0.0),
                    json.dumps(buckets) if buckets else None,
                    int(row.get("steps") or 0),
                    int(row.get("tokens") or 0),
                    float(row.get("flops") or 0.0),
                    float(row.get("goodput") or 0.0),
                    float(row.get("mfu") or 0.0),
                    float(row.get("tokens_per_device_s") or 0.0),
                    float(row.get("compile_s") or 0.0),
                    int(row.get("compile_events") or 0),
                    float(row.get("hbm_peak_bytes") or 0.0),
                    int(row.get("devices") or 0),
                    str(row.get("device_kind") or ""),
                    float(row.get("peak_flops_per_s") or 0.0),
                    1 if row.get("final") else 0,
                    json.dumps(attrs) if attrs else None,
                    float(row.get("ts") or time.time()),
                ),
            )

    def get_utilization(
        self,
        run_id: int,
        *,
        process_id: Optional[int] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Ledger rows for a run in ingest order (rows are cumulative per
        process — the latest per process_id is its current truth)."""
        sql = (
            "SELECT id, process_id, seq, source, wall_s, buckets, steps,"
            " tokens, flops, goodput, mfu, tokens_per_device_s, compile_s,"
            " compile_events, hbm_peak_bytes, devices, device_kind,"
            " peak_flops_per_s, final, attrs, created_at"
            " FROM utilization WHERE run_id = ? AND id > ?"
        )
        params: List[Any] = [run_id, since_id]
        if process_id is not None:
            sql += " AND process_id = ?"
            params.append(process_id)
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        out: List[Dict[str, Any]] = []
        for r in rows:
            rec = dict(r)
            rec["buckets"] = json.loads(rec["buckets"]) if rec["buckets"] else {}
            rec["attrs"] = json.loads(rec["attrs"]) if rec["attrs"] else {}
            rec["final"] = bool(rec["final"])
            out.append(rec)
        return out

    # -- metric history (TSDB write-behind) ------------------------------------
    def add_metric_samples(self, rows: Sequence[Dict[str, Any]]) -> int:
        """Batched ingest for the scrape phase's write-behind: one
        executemany per flush, not one transaction per sample.  A
        ``run="<id>"`` label on the series name is denormalized into the
        ``run_id`` column so delete_run's cascade and the per-run history
        API stay indexed."""
        if not rows:
            return 0
        now = time.time()
        params: List[Tuple[Any, ...]] = []
        for row in rows:
            name = row.get("name")
            if not name:
                continue
            run_id: Optional[int] = row.get("run_id")
            if run_id is None and 'run="' in name:
                _base, labels = split_labeled_key(name)
                raw = labels.get("run")
                if raw is not None:
                    try:
                        run_id = int(raw)
                    except ValueError:
                        run_id = None
            at = row.get("at")
            params.append((
                str(name),
                run_id,
                float(at) if at is not None else now,
                float(row.get("value") or 0.0),
                str(row.get("agg") or "raw"),
                row.get("vmin"),
                row.get("vmax"),
                row.get("vsum"),
                row.get("vcount"),
                now,
            ))
        if not params:
            return 0
        with self._lock, self._conn() as conn:
            conn.executemany(
                """INSERT INTO metric_samples
                   (name, run_id, at, value, agg, vmin, vmax, vsum, vcount,
                    created_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)""",
                params,
            )
        return len(params)

    def get_metric_samples(
        self,
        *,
        name: Optional[str] = None,
        run_id: Optional[int] = None,
        agg: Optional[str] = "raw",
        since: Optional[float] = None,
        until: Optional[float] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Persisted samples in id order (``since_id`` makes it a WS-tail
        cursor).  ``name`` matches the full labeled key exactly, or every
        label set of a base name when given without braces."""
        sql = (
            "SELECT id, name, run_id, at, value, agg, vmin, vmax, vsum,"
            " vcount, created_at FROM metric_samples WHERE id > ?"
        )
        params: List[Any] = [since_id]
        if name is not None:
            if "{" in name:
                sql += " AND name = ?"
                params.append(name)
            else:
                sql += " AND (name = ? OR name LIKE ?)"
                params.extend([name, name + "{%"])
        if run_id is not None:
            sql += " AND run_id = ?"
            params.append(run_id)
        if agg is not None:
            sql += " AND agg = ?"
            params.append(agg)
        if since is not None:
            sql += " AND at >= ?"
            params.append(float(since))
        if until is not None:
            sql += " AND at <= ?"
            params.append(float(until))
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        return [dict(r) for r in rows]

    def fold_metric_baseline(
        self,
        project: str,
        kind: str,
        series: str,
        value: float,
        *,
        alpha: float = 0.3,
        run_id: Optional[int] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Fold one completed-run summary value into its (project, kind,
        series) baseline row — exponentially weighted mean + variance, so
        a drifting fleet tracks and a noisy series widens its own band.
        Returns the *prior* mean/std/count alongside the new ones: the
        regression comparator judges the run against the baseline as it
        stood before this run was folded in.
        """
        now = now or time.time()
        alpha = min(1.0, max(0.0, float(alpha)))
        value = float(value)
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT ewma, ewvar, count FROM metric_baselines"
                " WHERE project = ? AND kind = ? AND series = ?",
                (project, kind, series),
            ).fetchone()
            if row is None:
                prior_mean = prior_var = None
                prior_count = 0
                mean, var, count = value, 0.0, 1
            else:
                prior_mean = float(row["ewma"])
                prior_var = float(row["ewvar"])
                prior_count = int(row["count"])
                # West (1979) EW update: variance first (it uses the old
                # mean), then the mean.
                diff = value - prior_mean
                var = (1.0 - alpha) * (prior_var + alpha * diff * diff)
                mean = prior_mean + alpha * diff
                count = prior_count + 1
            conn.execute(
                """INSERT INTO metric_baselines
                   (project, kind, series, ewma, ewvar, count, last_value,
                    last_run_id, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)
                   ON CONFLICT (project, kind, series) DO UPDATE SET
                     ewma = excluded.ewma, ewvar = excluded.ewvar,
                     count = excluded.count, last_value = excluded.last_value,
                     last_run_id = excluded.last_run_id,
                     updated_at = excluded.updated_at""",
                (project, kind, series, mean, var, count, value, run_id, now),
            )
        return {
            "project": project,
            "kind": kind,
            "series": series,
            "value": value,
            "prior_mean": prior_mean,
            "prior_std": math.sqrt(prior_var) if prior_var is not None else None,
            "prior_count": prior_count,
            "mean": mean,
            "std": math.sqrt(var),
            "count": count,
        }

    def get_metric_baselines(
        self, project: str, kind: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        sql = (
            "SELECT project, kind, series, ewma, ewvar, count, last_value,"
            " last_run_id, updated_at FROM metric_baselines WHERE project = ?"
        )
        params: List[Any] = [project]
        if kind is not None:
            sql += " AND kind = ?"
            params.append(kind)
        sql += " ORDER BY kind, series"
        rows = self._conn().execute(sql, params).fetchall()
        out = []
        for r in rows:
            rec = dict(r)
            rec["std"] = math.sqrt(max(0.0, rec.pop("ewvar")))
            rec["mean"] = rec.pop("ewma")
            out.append(rec)
        return out

    # -- heartbeats -----------------------------------------------------------
    def ping_heartbeat(self, run_id: int, at: Optional[float] = None) -> None:
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO heartbeats (run_id, last_at) VALUES (?, ?)
                   ON CONFLICT (run_id) DO UPDATE SET last_at = excluded.last_at""",
                (run_id, at or time.time()),
            )

    def last_heartbeat(self, run_id: int) -> Optional[float]:
        row = self._conn().execute(
            "SELECT last_at FROM heartbeats WHERE run_id = ?", (run_id,)
        ).fetchone()
        return row["last_at"] if row else None

    def zombie_runs(self, ttl_seconds: float, now: Optional[float] = None) -> List[Run]:
        """Runs in a heartbeat-requiring status whose heartbeat is stale.

        Parity: the reference's zombie cron
        (``crons/tasks/heartbeats.py`` + ``scheduler/tasks/experiments.py:111-120``).
        """
        now = now or time.time()
        # One indexed scan over live statuses; the per-lifecycle predicate is
        # re-checked on the (small) candidate set.
        rows = self._conn().execute(
            """SELECT r.* FROM runs r LEFT JOIN heartbeats h ON h.run_id = r.id
               WHERE r.status = ? AND (h.last_at IS NULL OR ? - h.last_at > ?)""",
            (S.RUNNING, now, ttl_seconds),
        ).fetchall()
        return [
            run
            for run in map(_row_to_run, rows)
            if run.lifecycle.needs_heartbeat(run.status)
        ]

    # -- progress + anomalies --------------------------------------------------
    def upsert_progress(
        self,
        run_id: int,
        process_id: int,
        *,
        step: Optional[int] = None,
        epoch: Optional[int] = None,
        throughput: Optional[float] = None,
        at: Optional[float] = None,
    ) -> None:
        """Latest-wins forward-progress marker per gang process.

        One row per (run, process): the stall/straggler detector only ever
        needs the newest beat, and metric rows already carry history —
        keeping this a fixed-size upsert means the detector's poll is O(gang)
        no matter how long the run is."""
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO progress
                   (run_id, process_id, step, epoch, throughput, at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?)
                   ON CONFLICT (run_id, process_id) DO UPDATE SET
                     step = COALESCE(excluded.step, step),
                     epoch = COALESCE(excluded.epoch, epoch),
                     throughput = COALESCE(excluded.throughput, throughput),
                     at = excluded.at,
                     updated_at = excluded.updated_at""",
                (run_id, process_id, step, epoch, throughput,
                 at or time.time(), time.time()),
            )

    def get_progress(self, run_id: int) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT process_id, step, epoch, throughput, at, updated_at"
            " FROM progress WHERE run_id = ? ORDER BY process_id",
            (run_id,),
        ).fetchall()
        return [dict(r) for r in rows]

    def add_anomaly(
        self,
        run_id: int,
        kind: str,
        *,
        process_id: Optional[int] = None,
        message: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
        created_at: Optional[float] = None,
    ) -> None:
        """One detected anomaly (stall/straggler/crash) — append-only, like
        statuses: the rows ARE the incident timeline."""
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO anomalies
                   (run_id, process_id, kind, message, attrs, created_at)
                   VALUES (?, ?, ?, ?, ?, ?)""",
                (
                    run_id,
                    process_id,
                    str(kind),
                    message,
                    json.dumps(attrs, default=str) if attrs else None,
                    created_at or time.time(),
                ),
            )

    def get_anomalies(
        self,
        run_id: int,
        *,
        kind: Optional[str] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        sql = (
            "SELECT id, process_id, kind, message, attrs, created_at"
            " FROM anomalies WHERE run_id = ? AND id > ?"
        )
        params: List[Any] = [run_id, since_id]
        if kind is not None:
            sql += " AND kind = ?"
            params.append(kind)
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        out: List[Dict[str, Any]] = []
        for r in rows:
            row = dict(r)
            row["attrs"] = json.loads(row["attrs"]) if row["attrs"] else {}
            out.append(row)
        return out

    # -- commands (control plane → worker bus) --------------------------------
    def enqueue_command(
        self,
        run_id: int,
        kind: str,
        *,
        payload: Optional[Dict[str, Any]] = None,
        process_id: Optional[int] = None,
        expected: int = 1,
        uuid: Optional[str] = None,
        status: str = CommandStatus.PENDING,
        message: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Record a worker-directed command (the durable side of the bus;
        delivery is the per-process mailbox the spawner provisions).
        ``expected`` is how many processes must report a terminal state
        before the roll-up resolves; ``process_id`` pins single-host
        commands (None = whole gang)."""
        import uuid as uuid_mod

        cmd_uuid = uuid or uuid_mod.uuid4().hex
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO commands
                   (run_id, uuid, kind, process_id, payload, status, message,
                    acks, expected, created_at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, '{}', ?, ?, ?)""",
                (
                    run_id,
                    cmd_uuid,
                    str(kind),
                    process_id,
                    json.dumps(payload or {}, default=str),
                    status,
                    message,
                    int(expected),
                    now,
                    now,
                ),
            )
        return self.get_command(cmd_uuid)

    def get_command(self, uuid: str) -> Optional[Dict[str, Any]]:
        row = self._conn().execute(
            "SELECT * FROM commands WHERE uuid = ?", (uuid,)
        ).fetchone()
        return self._command_row(row) if row is not None else None

    def get_commands(
        self,
        run_id: int,
        *,
        kind: Optional[str] = None,
        status: Optional[str] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        sql = "SELECT * FROM commands WHERE run_id = ? AND id > ?"
        params: List[Any] = [run_id, since_id]
        if kind is not None:
            sql += " AND kind = ?"
            params.append(kind)
        if status is not None:
            sql += " AND status = ?"
            params.append(status)
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        return [self._command_row(r) for r in rows]

    @staticmethod
    def _command_row(row: sqlite3.Row) -> Dict[str, Any]:
        out = dict(row)
        out["payload"] = json.loads(out["payload"]) if out["payload"] else {}
        out["acks"] = json.loads(out["acks"]) if out["acks"] else {}
        return out

    def mark_command(
        self,
        uuid: str,
        process_id: int,
        state: str,
        *,
        message: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Fold one process's command state into the row and recompute the
        gang roll-up.  Per-process states are acked/complete/failed; the
        roll-up goes COMPLETE once ``expected`` processes are terminal and
        none failed, FAILED if any did.  A command the control plane
        already resolved (EXPIRED) never un-resolves — late worker lines
        land in ``acks`` for forensics but don't flip the status.

        ``attrs`` carries handler result data (e.g. checkpoint-now's saved
        step) — the ack value then becomes ``{"state":..., "attrs":...}``;
        attr-less acks stay plain strings for compatibility."""
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT * FROM commands WHERE uuid = ?", (uuid,)
            ).fetchone()
            if row is None:
                return None
            acks = json.loads(row["acks"]) if row["acks"] else {}
            acks[str(int(process_id))] = (
                {"state": state, "attrs": attrs} if attrs else state
            )
            status = row["status"]
            if status not in CommandStatus.TERMINAL:
                terminal = [
                    s
                    for s in (command_ack_state(v) for v in acks.values())
                    if s in (CommandStatus.COMPLETE, CommandStatus.FAILED)
                ]
                if len(terminal) >= max(1, row["expected"]):
                    status = (
                        CommandStatus.FAILED
                        if CommandStatus.FAILED in terminal
                        else CommandStatus.COMPLETE
                    )
                elif acks:
                    status = CommandStatus.ACKED
            conn.execute(
                """UPDATE commands SET acks = ?, status = ?, updated_at = ?,
                                       message = COALESCE(?, message)
                   WHERE uuid = ?""",
                (json.dumps(acks), status, time.time(), message, uuid),
            )
        return self.get_command(uuid)

    def expire_commands(
        self, run_id: int, *, message: str = "run finished before the gang honored the command"
    ) -> int:
        """Resolve every still-open command on a run to EXPIRED — called
        when the run goes terminal so a command never hangs un-answered."""
        placeholders = ",".join("?" * len(CommandStatus.TERMINAL))
        with self._lock, self._conn() as conn:
            return conn.execute(
                f"""UPDATE commands SET status = ?, message = ?, updated_at = ?
                    WHERE run_id = ? AND status NOT IN ({placeholders})""",
                (
                    CommandStatus.EXPIRED,
                    message,
                    time.time(),
                    run_id,
                    *CommandStatus.TERMINAL,
                ),
            ).rowcount

    # -- remediations (alert-driven actions) ----------------------------------
    @staticmethod
    def _remediation_row(row: sqlite3.Row) -> Dict[str, Any]:
        out = dict(row)
        out["attrs"] = json.loads(out["attrs"]) if out["attrs"] else {}
        return out

    def add_remediation(
        self,
        run_id: int,
        action: str,
        *,
        trigger: Optional[str] = None,
        status: str = RemediationStatus.PENDING,
        message: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Record one remediation action on a run's timeline."""
        now = time.time()
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                """INSERT INTO remediations
                       (run_id, action, trigger, status, message, attrs,
                        created_at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?)""",
                (
                    run_id,
                    action,
                    trigger,
                    status,
                    message,
                    json.dumps(attrs) if attrs else None,
                    now,
                    now,
                ),
            )
            rem_id = cur.lastrowid
        return self.get_remediation(rem_id)

    def update_remediation(
        self,
        rem_id: int,
        *,
        status: Optional[str] = None,
        message: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Advance a remediation row; ``attrs`` shallow-merge into the
        stored blob so phases can accrete result data."""
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT * FROM remediations WHERE id = ?", (rem_id,)
            ).fetchone()
            if row is None:
                return None
            merged = json.loads(row["attrs"]) if row["attrs"] else {}
            if attrs:
                merged.update(attrs)
            conn.execute(
                """UPDATE remediations
                   SET status = COALESCE(?, status),
                       message = COALESCE(?, message),
                       attrs = ?, updated_at = ?
                   WHERE id = ?""",
                (
                    status,
                    message,
                    json.dumps(merged) if merged else None,
                    time.time(),
                    rem_id,
                ),
            )
        return self.get_remediation(rem_id)

    def get_remediation(self, rem_id: int) -> Optional[Dict[str, Any]]:
        with self._lock, self._conn() as conn:
            row = conn.execute(
                "SELECT * FROM remediations WHERE id = ?", (rem_id,)
            ).fetchone()
        return self._remediation_row(row) if row else None

    def get_remediations(
        self,
        run_id: int,
        *,
        action: Optional[str] = None,
        status: Optional[str] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        query = "SELECT * FROM remediations WHERE run_id = ? AND id > ?"
        params: List[Any] = [run_id, since_id]
        if action is not None:
            query += " AND action = ?"
            params.append(action)
        if status is not None:
            query += " AND status = ?"
            params.append(status)
        query += " ORDER BY id"
        if limit is not None:
            query += " LIMIT ?"
            params.append(int(limit))
        with self._lock, self._conn() as conn:
            rows = conn.execute(query, params).fetchall()
        return [self._remediation_row(r) for r in rows]

    def count_remediations(
        self, run_id: int, *, statuses: Optional[Sequence[str]] = None
    ) -> int:
        """How many remediation actions a run has consumed — the budget
        check.  ``statuses`` narrows the count (e.g. exclude SKIPPED so
        recording a refusal doesn't itself consume budget)."""
        query = "SELECT COUNT(*) FROM remediations WHERE run_id = ?"
        params: List[Any] = [run_id]
        if statuses:
            query += f" AND status IN ({','.join('?' * len(statuses))})"
            params.extend(statuses)
        with self._lock, self._conn() as conn:
            return int(conn.execute(query, params).fetchone()[0])

    def expire_remediations(
        self,
        run_id: int,
        *,
        message: str = "run finished before the action resolved",
    ) -> int:
        """Close every still-open remediation row when a run goes
        terminal — mirrors ``expire_commands`` so nothing hangs open."""
        placeholders = ",".join("?" * len(RemediationStatus.OPEN))
        with self._lock, self._conn() as conn:
            return conn.execute(
                f"""UPDATE remediations SET status = ?, message = ?, updated_at = ?
                    WHERE run_id = ? AND status IN ({placeholders})""",
                (
                    RemediationStatus.EXPIRED,
                    message,
                    time.time(),
                    run_id,
                    *RemediationStatus.OPEN,
                ),
            ).rowcount

    # -- captures (on-demand profiling results) -------------------------------
    def upsert_capture(
        self,
        run_id: int,
        capture_id: str,
        process_id: int,
        *,
        status: Optional[str] = None,
        start_step: Optional[int] = None,
        num_steps: Optional[int] = None,
        started_at: Optional[float] = None,
        finished_at: Optional[float] = None,
        artifacts: Optional[List[str]] = None,
        message: Optional[str] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Latest-wins per-(run, capture, process) profiling record — the
        watcher folds workers' typed ``capture`` report lines here, so a
        capture's lifecycle (started → complete/failed) is one row per
        host, like ``progress``."""
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO captures
                   (run_id, capture_id, process_id, status, start_step,
                    num_steps, started_at, finished_at, artifacts, message,
                    attrs, created_at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)
                   ON CONFLICT (run_id, capture_id, process_id) DO UPDATE SET
                     status = COALESCE(excluded.status, status),
                     start_step = COALESCE(excluded.start_step, start_step),
                     num_steps = COALESCE(excluded.num_steps, num_steps),
                     started_at = COALESCE(excluded.started_at, started_at),
                     finished_at = COALESCE(excluded.finished_at, finished_at),
                     artifacts = CASE WHEN excluded.artifacts != '[]'
                                      THEN excluded.artifacts ELSE artifacts END,
                     message = COALESCE(excluded.message, message),
                     attrs = COALESCE(excluded.attrs, attrs),
                     updated_at = excluded.updated_at""",
                (
                    run_id,
                    str(capture_id),
                    int(process_id),
                    status,
                    start_step,
                    num_steps,
                    started_at,
                    finished_at,
                    json.dumps(artifacts or [], default=str),
                    message,
                    json.dumps(attrs, default=str) if attrs else None,
                    now,
                    now,
                ),
            )

    def get_captures(
        self,
        run_id: int,
        *,
        capture_id: Optional[str] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        sql = "SELECT * FROM captures WHERE run_id = ? AND id > ?"
        params: List[Any] = [run_id, since_id]
        if capture_id is not None:
            sql += " AND capture_id = ?"
            params.append(capture_id)
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        out: List[Dict[str, Any]] = []
        for r in rows:
            row = dict(r)
            row["artifacts"] = json.loads(row["artifacts"]) if row["artifacts"] else []
            row["attrs"] = json.loads(row["attrs"]) if row["attrs"] else {}
            out.append(row)
        return out

    # -- alerts (rule-engine lifecycle rows) -----------------------------------
    def upsert_alert(
        self,
        run_id: int,
        rule: str,
        *,
        state: str,
        severity: str,
        message: Optional[str] = None,
        value: Optional[float] = None,
        for_s: Optional[float] = None,
        episodes: Optional[int] = None,
        pending_since: Optional[float] = None,
        fired_at: Optional[float] = None,
        resolved_at: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
        now: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Latest-state-per-(run, rule) alert row, like ``captures`` — but
        each transition REPLACEs the row so it gets a fresh autoincrement
        id: the feed stays one-row-per-alert while since_id pagers and the
        WS tail still observe every lifecycle edge.  ``pending_since`` /
        ``fired_at`` / ``episodes`` carry forward from the previous row
        when not supplied, so a resolve keeps its firing timestamp (that
        difference IS the alert latency bench reads)."""
        now = now or time.time()
        with self._lock, self._conn() as conn:
            prev = conn.execute(
                "SELECT * FROM alerts WHERE run_id = ? AND rule = ?",
                (run_id, str(rule)),
            ).fetchone()
            created_at = prev["created_at"] if prev else now
            if episodes is None:
                episodes = prev["episodes"] if prev else 0
            if pending_since is None and prev is not None:
                pending_since = prev["pending_since"]
            if fired_at is None and prev is not None:
                fired_at = prev["fired_at"]
            cur = conn.execute(
                """INSERT OR REPLACE INTO alerts
                   (run_id, rule, state, severity, message, value, for_s,
                    episodes, pending_since, fired_at, resolved_at, attrs,
                    created_at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)""",
                (
                    run_id,
                    str(rule),
                    str(state),
                    str(severity),
                    message,
                    value,
                    for_s,
                    int(episodes),
                    pending_since,
                    fired_at,
                    resolved_at,
                    json.dumps(attrs, default=str) if attrs else None,
                    created_at,
                    now,
                ),
            )
            row_id = cur.lastrowid
        return {
            "id": row_id,
            "run_id": run_id,
            "rule": str(rule),
            "state": str(state),
            "severity": str(severity),
            "message": message,
            "value": value,
            "for_s": for_s,
            "episodes": int(episodes),
            "pending_since": pending_since,
            "fired_at": fired_at,
            "resolved_at": resolved_at,
            "attrs": attrs or {},
            "created_at": created_at,
            "updated_at": now,
        }

    def get_alerts(
        self,
        run_id: Optional[int] = None,
        *,
        state: Optional[str] = None,
        severity: Optional[str] = None,
        rule: Optional[str] = None,
        since_id: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Alert rows, cluster-wide when ``run_id`` is None — the /alerts
        feed.  since_id pages by transition (REPLACE bumps the id)."""
        sql = "SELECT * FROM alerts WHERE id > ?"
        params: List[Any] = [since_id]
        if run_id is not None:
            sql += " AND run_id = ?"
            params.append(run_id)
        if state is not None:
            sql += " AND state = ?"
            params.append(str(state))
        if severity is not None:
            sql += " AND severity = ?"
            params.append(str(severity))
        if rule is not None:
            sql += " AND rule = ?"
            params.append(str(rule))
        sql += " ORDER BY id"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        out: List[Dict[str, Any]] = []
        for r in rows:
            row = dict(r)
            row["attrs"] = json.loads(row["attrs"]) if row["attrs"] else {}
            out.append(row)
        return out

    def delete_alert(self, run_id: int, rule: str) -> bool:
        """Drop a (run, rule) alert row — a pending alert that recovered
        inside its hold-down vanishes instead of becoming a resolve edge."""
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "DELETE FROM alerts WHERE run_id = ? AND rule = ?",
                (run_id, str(rule)),
            )
        return cur.rowcount > 0

    def stale_queued_runs(
        self, ttl_seconds: float, now: Optional[float] = None
    ) -> List[Run]:
        """Runs stuck in QUEUED past ``ttl_seconds`` since their last write.

        The QUEUED dispatch mark trades the old re-dispatch self-healing for
        debounce; if the dispatched build/start task is ever dropped (task
        error — the bus dead-letters non-Retry exceptions), the run would
        otherwise sit QUEUED forever with the group/pipeline waiting on it.
        The cron re-dispatches these.
        """
        now = now or time.time()
        rows = self._conn().execute(
            "SELECT * FROM runs WHERE status = ? AND ? - updated_at > ?",
            (S.QUEUED, now, ttl_seconds),
        ).fetchall()
        return list(map(_row_to_run, rows))

    # -- devices (accelerator inventory + gang admission) ---------------------
    # Parity: reference ``db/models/nodes.py`` (ClusterNode/NodeGPU) +
    # k8s-delegated placement. TPU-native: the schedulable unit is a whole
    # accelerator SLICE (chips within a slice share ICI and can't be split
    # across jax.distributed worlds), so the inventory is slices and
    # admission is acquire/release of one slice per gang.

    def register_device(
        self,
        name: str,
        accelerator: str,
        chips: int,
        num_hosts: int = 1,
    ) -> Dict[str, Any]:
        """Add (or update) a slice in the inventory. Registering any device
        of a family turns admission control ON for that family."""
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO devices (name, accelerator, chips, num_hosts,
                                        created_at, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?)
                   ON CONFLICT(name) DO UPDATE SET
                     accelerator = excluded.accelerator,
                     chips = excluded.chips,
                     num_hosts = excluded.num_hosts,
                     updated_at = excluded.updated_at""",
                (name, accelerator, chips, num_hosts, now, now),
            )
        return self.get_device(name)

    def get_device(self, name: str) -> Dict[str, Any]:
        row = self._conn().execute(
            "SELECT * FROM devices WHERE name = ?", (name,)
        ).fetchone()
        if row is None:
            raise RegistryError(f"No device named {name!r}")
        return dict(row)

    def list_devices(self) -> List[Dict[str, Any]]:
        """Inventory with usage accounting: ``used_chips`` counts packed
        claims (or the whole slice for an exclusive hold) and ``holders``
        names every run on the row — exclusive or packed."""
        rows = self._conn().execute(
            """SELECT d.*, COALESCE(SUM(c.chips), 0) AS packed_chips,
                      GROUP_CONCAT(c.run_id) AS packed_run_ids
               FROM devices d LEFT JOIN device_claims c ON c.device_id = d.id
               GROUP BY d.id
               ORDER BY d.accelerator, d.chips, d.name"""
        ).fetchall()
        out = []
        for r in rows:
            d = dict(r)
            packed = d.pop("packed_chips", 0) or 0
            packed_ids = d.pop("packed_run_ids", None)
            holders = (
                [int(x) for x in packed_ids.split(",")] if packed_ids else []
            )
            if d.get("run_id") is not None:
                holders = [d["run_id"]] + holders
            d["used_chips"] = d["chips"] if d.get("run_id") is not None else packed
            d["holders"] = holders
            out.append(d)
        return out

    def remove_device(self, name: str) -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute("DELETE FROM devices WHERE name = ?", (name,))
        return cur.rowcount > 0

    def acquire_device(
        self,
        run_id: int,
        accelerator: str,
        chips: int,
        num_slices: int = 1,
        num_hosts: int = 1,
    ) -> Optional[Dict[str, Any]]:
        """Claim capacity for a gang: whole slice(s), or a PACKED share.

        Single-host single-slice gangs pack: they claim ``chips`` chips of
        a slice through the ``device_claims`` accounting table (best fit:
        the row with the least free space that still fits), so K small
        trials share one big slice — the reference's bread-and-butter
        hpsearch bin-packing (``scheduler/experiment_scheduler.py:
        101-140``, k8s-delegated there).  Gangs spanning hosts or slices
        still claim whole EXCLUSIVE rows — an ICI world is one
        ``jax.distributed`` job; splitting a multi-host slice between runs
        would interleave two coordinators on one ring.

        Returns the (first) claimed slice row; ``None`` when the family has
        inventory but nothing fits free (caller queues the run); or
        ``{"unmanaged": True}`` when the family has NO registered inventory
        at all (admission control off — every run admitted).  Idempotent
        per run: a re-dispatched start re-uses the already-held claim.
        All-or-nothing: a partial fit claims nothing.
        """
        num_slices = max(1, int(num_slices))
        if chips % num_slices:
            # Flooring would silently under-claim capacity; the compiler
            # always passes a divisible total, so a remainder is a caller bug.
            raise RegistryError(
                f"chips ({chips}) must divide evenly across num_slices "
                f"({num_slices})"
            )
        per_slice = max(1, chips // num_slices)
        packable = num_slices == 1 and int(num_hosts) <= 1
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            held = conn.execute(
                "SELECT * FROM devices WHERE run_id = ?", (run_id,)
            ).fetchone()
            if held is not None:
                # Flagged so a duplicate dispatch knows it did NOT newly
                # claim anything (and must not release on its failure path).
                return {**dict(held), "already_held": True}
            packed_held = conn.execute(
                """SELECT d.*, c.chips AS claim_chips FROM device_claims c
                   JOIN devices d ON d.id = c.device_id WHERE c.run_id = ?""",
                (run_id,),
            ).fetchone()
            if packed_held is not None:
                return {**dict(packed_held), "already_held": True, "packed": True}
            managed, free_clause, free_params = self._family_fit(
                conn, accelerator, per_slice
            )
            if managed == 0:
                return {"unmanaged": True}
            now = time.time()
            if packable:
                family_clause, family_params = self._family_clause(
                    accelerator, prefix="d."
                )
                row = conn.execute(
                    f"""SELECT d.*, d.chips - COALESCE(SUM(c.chips), 0)
                              AS free_chips
                        FROM devices d
                        LEFT JOIN device_claims c ON c.device_id = d.id
                        WHERE d.run_id IS NULL AND {family_clause}
                        GROUP BY d.id
                        HAVING free_chips >= ?
                        ORDER BY free_chips ASC, d.chips ASC, d.id ASC
                        LIMIT 1""",
                    (*family_params, per_slice),
                ).fetchone()
                if row is None:
                    return None
                conn.execute(
                    """INSERT INTO device_claims (device_id, run_id, chips,
                                                  created_at)
                       VALUES (?, ?, ?, ?)""",
                    (row["id"], run_id, per_slice, now),
                )
                claimed = dict(row)
                claimed.pop("free_chips", None)
                return {
                    **claimed,
                    "run_id": run_id,
                    "packed": True,
                    "claim_chips": per_slice,
                }
            rows = conn.execute(
                f"""SELECT * FROM devices d WHERE {free_clause}
                    AND NOT EXISTS (SELECT 1 FROM device_claims c
                                    WHERE c.device_id = d.id)
                    ORDER BY chips ASC, id ASC LIMIT ?""",
                (*free_params, num_slices),
            ).fetchall()
            if len(rows) < num_slices:
                return None
            for row in rows:
                conn.execute(
                    "UPDATE devices SET run_id = ?, updated_at = ? WHERE id = ?",
                    (run_id, now, row["id"]),
                )
            claimed = {**dict(rows[0]), "run_id": run_id}
            if num_slices > 1:
                claimed["slices"] = [r["name"] for r in rows]
            return claimed

    def release_devices(self, run_id: int) -> int:
        """Free everything held by ``run_id`` — exclusive slice rows AND
        packed claims; returns how many were held."""
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "UPDATE devices SET run_id = NULL, updated_at = ? WHERE run_id = ?",
                (time.time(), run_id),
            )
            packed = conn.execute(
                "DELETE FROM device_claims WHERE run_id = ?", (run_id,)
            )
        return cur.rowcount + packed.rowcount

    @staticmethod
    def _family_clause(
        accelerator: str, prefix: str = "", col: Optional[str] = None
    ) -> Tuple[str, Tuple[Any, ...]]:
        """Family matching shared by acquire and the free count (they MUST
        agree or hp_start dispatches trials that then fail admission).

        Exact-name-or-dash-prefix: family ``v5e`` matches ``v5e`` and
        ``v5e-*`` but never ``v5`` → ``v5e-8`` (prefix LIKE would) —
        cross-generation chips aren't fungible.  ``col`` overrides the
        matched column/expression outright (queued-run counting matches a
        json_extract of the spec).
        """
        family = accelerator_family(accelerator)
        col = col or f"{prefix}accelerator"
        clause = f"({col} = ? OR {col} LIKE ? ESCAPE '\\')"
        like = family.replace("\\", "\\\\").replace("%", "\\%").replace("_", "\\_")
        return clause, (family, like + "-%")

    @classmethod
    def _family_fit(
        cls, conn: sqlite3.Connection, accelerator: str, chips: int
    ) -> Tuple[int, str, Tuple[Any, ...]]:
        family_clause, family_params = cls._family_clause(accelerator)
        managed = conn.execute(
            f"SELECT COUNT(*) AS n FROM devices WHERE {family_clause}",
            family_params,
        ).fetchone()["n"]
        free_clause = f"run_id IS NULL AND {family_clause} AND chips >= ?"
        return managed, free_clause, (*family_params, chips)

    def free_slice_count(
        self, accelerator: str, chips: int, num_hosts: int = 1
    ) -> Optional[int]:
        """Free fitting CLAIM OPPORTUNITIES for a family; None = family
        unmanaged (no inventory registered → admission control off).

        For packable requests (single host) this counts packing slots —
        Σ floor(free_chips / chips) over non-exclusive rows — so a sweep's
        dispatch window sees that a v5e-16 fits four 4-chip trials.  Multi-
        host requests count whole free unpacked slices, matching
        ``acquire_device``'s exclusive path.
        """
        conn = self._conn()
        managed, free_clause, free_params = self._family_fit(conn, accelerator, chips)
        if managed == 0:
            return None
        if int(num_hosts) > 1:
            return conn.execute(
                f"""SELECT COUNT(*) AS n FROM devices d WHERE {free_clause}
                    AND NOT EXISTS (SELECT 1 FROM device_claims c
                                    WHERE c.device_id = d.id)""",
                free_params,
            ).fetchone()["n"]
        family_clause, family_params = self._family_clause(accelerator, prefix="d.")
        rows = conn.execute(
            f"""SELECT d.chips - COALESCE(SUM(c.chips), 0) AS free_chips
                FROM devices d
                LEFT JOIN device_claims c ON c.device_id = d.id
                WHERE d.run_id IS NULL AND {family_clause}
                GROUP BY d.id""",
            family_params,
        ).fetchall()
        return sum(r["free_chips"] // chips for r in rows if r["free_chips"] >= chips)

    def queued_chips_count(self, accelerator: str) -> int:
        """Total CHIPS queued for this accelerator family — capacity
        already spoken for but not yet claimed.  hp_start converts this
        into its own slot units and subtracts it from the free count so
        two sweeps reading the same snapshot don't both dispatch into it
        (the losers would park QUEUED while holding their group's
        concurrency window — wave stalls).  Chips, not run counts: a
        queued 16-chip gang spends four of a 4-chip sweep's slots, and
        eight queued 1-chip trials spend two — run counting would get
        both wrong."""
        family_clause, family_params = self._family_clause(
            accelerator,
            col="COALESCE(json_extract(spec,"
            " '$.environment.topology.accelerator'), 'cpu')",
        )
        row = self._conn().execute(
            f"""SELECT SUM(
                    COALESCE(json_extract(spec,
                        '$.environment.topology.num_devices'), 1)
                    * COALESCE(json_extract(spec,
                        '$.environment.topology.num_slices'), 1)
                ) AS chips
                FROM runs WHERE status = 'queued' AND {family_clause}""",
            family_params,
        ).fetchone()
        return int(row["chips"] or 0)

    # -- iterations (hpsearch) ------------------------------------------------
    def create_iteration(self, group_id: int, data: Dict[str, Any]) -> int:
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute("BEGIN IMMEDIATE")
            row = conn.execute(
                "SELECT MAX(number) AS n FROM iterations WHERE group_id = ?",
                (group_id,),
            ).fetchone()
            number = (row["n"] or 0) + 1
            conn.execute(
                "INSERT INTO iterations (group_id, number, data, created_at, updated_at)"
                " VALUES (?, ?, ?, ?, ?)",
                (group_id, number, json.dumps(data), now, now),
            )
        return number

    def update_iteration(self, group_id: int, number: int, data: Dict[str, Any]) -> None:
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "UPDATE iterations SET data = ?, updated_at = ? WHERE group_id = ? AND number = ?",
                (json.dumps(data), time.time(), group_id, number),
            )
            if cur.rowcount == 0:
                raise RegistryError(f"No iteration {number} for group {group_id}")

    def get_iteration(self, group_id: int, number: Optional[int] = None) -> Optional[Dict[str, Any]]:
        if number is None:
            row = self._conn().execute(
                "SELECT number, data FROM iterations WHERE group_id = ? ORDER BY number DESC LIMIT 1",
                (group_id,),
            ).fetchone()
        else:
            row = self._conn().execute(
                "SELECT number, data FROM iterations WHERE group_id = ? AND number = ?",
                (group_id, number),
            ).fetchone()
        if row is None:
            return None
        return {"number": row["number"], "data": json.loads(row["data"])}

    def get_iterations(self, group_id: int) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT number, data FROM iterations WHERE group_id = ? ORDER BY number",
            (group_id,),
        ).fetchall()
        return [{"number": r["number"], "data": json.loads(r["data"])} for r in rows]

    # -- processes (gang members) ---------------------------------------------
    def upsert_process(
        self,
        run_id: int,
        process_id: int,
        *,
        pid: Optional[int] = None,
        status: str = S.CREATED,
        exit_code: Optional[int] = None,
    ) -> None:
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO processes (run_id, process_id, pid, status, exit_code, updated_at)
                   VALUES (?, ?, ?, ?, ?, ?)
                   ON CONFLICT (run_id, process_id) DO UPDATE SET
                     pid = COALESCE(excluded.pid, pid),
                     status = excluded.status,
                     exit_code = COALESCE(excluded.exit_code, exit_code),
                     updated_at = excluded.updated_at""",
                (run_id, process_id, pid, status, exit_code, time.time()),
            )

    def get_processes(self, run_id: int) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT process_id, pid, status, exit_code, report_offset, updated_at"
            " FROM processes WHERE run_id = ? ORDER BY process_id",
            (run_id,),
        ).fetchall()
        return [dict(r) for r in rows]

    def set_report_offset(self, run_id: int, process_id: int, offset: int) -> None:
        """Persist the watcher's report-tail cursor — a restarted control
        plane resumes ingestion exactly where the dead one stopped (no
        replayed metrics, no lost final status lines)."""
        with self._lock, self._conn() as conn:
            conn.execute(
                "UPDATE processes SET report_offset = ? "
                "WHERE run_id = ? AND process_id = ?",
                (offset, run_id, process_id),
            )

    def clear_processes(self, run_id: int) -> None:
        with self._lock, self._conn() as conn:
            conn.execute("DELETE FROM processes WHERE run_id = ?", (run_id,))

    # -- activity log ----------------------------------------------------------
    def record_activity(self, event_type: str, context: Dict[str, Any]) -> None:
        with self._lock, self._conn() as conn:
            conn.execute(
                "INSERT INTO activity (event_type, context, created_at) VALUES (?, ?, ?)",
                (event_type, json.dumps(context, default=str), time.time()),
            )

    def get_activities(
        self, event_type: Optional[str] = None, limit: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        sql = "SELECT event_type, context, created_at FROM activity"
        params: List[Any] = []
        if event_type is not None:
            sql += " WHERE event_type = ?"
            params.append(event_type)
        sql += " ORDER BY id DESC"
        if limit is not None:
            sql += f" LIMIT {int(limit)}"
        rows = self._conn().execute(sql, params).fetchall()
        return [
            {
                "event_type": r["event_type"],
                "context": json.loads(r["context"]),
                "created_at": r["created_at"],
            }
            for r in rows
        ]

    # -- retention cleanup ----------------------------------------------------
    #: Retention sweep targets: (result key, table, age column, scope to
    #: finished runs?).  ``alerts``/``remediations`` key off ``updated_at``
    #: — a row's last lifecycle edge, not its creation, decides when it
    #: falls off the timeline (a long-lived firing alert must survive).
    _SWEEP_TABLES: Sequence[Tuple[str, str, str, bool]] = (
        ("activity", "activity", "created_at", False),
        ("logs", "logs", "created_at", True),
        ("spans", "spans", "created_at", True),
        ("anomalies", "anomalies", "created_at", True),
        ("utilization", "utilization", "created_at", True),
        ("commands", "commands", "created_at", True),
        ("captures", "captures", "created_at", True),
        ("alerts", "alerts", "updated_at", True),
        ("remediations", "remediations", "updated_at", True),
        # Fleet/control-plane series carry no run_id, so the sweep is
        # unscoped — age alone retires metric history.
        ("metric_samples", "metric_samples", "created_at", False),
    )

    def clean_old_rows(
        self,
        older_than_seconds: float,
        now: Optional[float] = None,
        max_rows: Optional[int] = None,
    ) -> Dict[str, int]:
        """Delete activity/log rows past the retention horizon for DONE runs.

        Parity: the reference's beat cleaners (``crons/tasks/cleaning.py``,
        activity-log & notification cleanup, archived deletion).

        One transaction per call, bounded by a per-tick row budget
        (``max_rows``, default ``POLYAXON_TPU_RETENTION_SWEEP_ROWS``): a
        registry that accumulated months of backlog must not hold the
        write lock for one giant sweep — leftovers age out on later
        ticks.  The result carries per-table delete counts plus
        ``truncated`` (1 when the budget ran out mid-sweep).
        """
        if max_rows is None:
            from polyaxon_tpu.conf.knobs import knob_int

            max_rows = knob_int("POLYAXON_TPU_RETENTION_SWEEP_ROWS")
        now = now or time.time()
        cutoff = now - older_than_seconds
        budget = int(max_rows) if max_rows and max_rows > 0 else None
        counts: Dict[str, int] = {key: 0 for key, *_ in self._SWEEP_TABLES}
        truncated = False
        with self._lock, self._conn() as conn:
            for key, table, age_col, scoped in self._SWEEP_TABLES:
                if budget is not None and budget <= 0:
                    truncated = True
                    break
                # DELETE ... LIMIT isn't guaranteed compiled into the
                # stdlib's sqlite; the rowid-subselect form always works.
                scope = (
                    " AND run_id IN (SELECT id FROM runs WHERE"
                    " finished_at IS NOT NULL AND finished_at < ?)"
                    if scoped
                    else ""
                )
                params: List[Any] = [cutoff] + ([cutoff] if scoped else [])
                sql = (
                    f"DELETE FROM {table} WHERE rowid IN"
                    f" (SELECT rowid FROM {table} WHERE {age_col} < ?{scope}"
                )
                if budget is not None:
                    sql += " LIMIT ?"
                    params.append(budget)
                sql += ")"
                deleted = conn.execute(sql, params).rowcount
                counts[key] = deleted
                if budget is not None:
                    budget -= deleted
                    if budget <= 0 and deleted > 0:
                        truncated = True
        counts["truncated"] = int(truncated)
        return counts

    # -- projects (entity metadata over runs.project) --------------------------
    def create_project(
        self,
        name: str,
        description: Optional[str] = None,
        owner: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Parity: reference project CRUD (``api/projects/``) + ownership
        (``ownership/``): an ``owner`` scopes access to owner+collaborators
        (+admins); ownerless projects stay open — the pre-ACL behavior and
        the single-operator local mode."""
        try:
            with self._lock, self._conn() as conn:
                cur = conn.execute(
                    "INSERT INTO projects (name, description, owner, created_at)"
                    " VALUES (?, ?, ?, ?)",
                    (name, description, owner, time.time()),
                )
        except sqlite3.IntegrityError as e:
            raise RegistryError(f"Project {name!r} already exists") from e
        return {
            "id": cur.lastrowid,
            "name": name,
            "description": description,
            "owner": owner,
        }

    def add_collaborator(self, project: str, username: str) -> None:
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT OR IGNORE INTO project_collaborators
                   (project_name, username, created_at) VALUES (?, ?, ?)""",
                (project, username, time.time()),
            )

    def remove_collaborator(self, project: str, username: str) -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "DELETE FROM project_collaborators WHERE project_name = ?"
                " AND username = ?",
                (project, username),
            )
        return cur.rowcount > 0

    def project_collaborators(self, project: str) -> List[str]:
        rows = self._conn().execute(
            "SELECT username FROM project_collaborators WHERE project_name = ?"
            " ORDER BY username",
            (project,),
        ).fetchall()
        return [r["username"] for r in rows]

    def project_access(self, project: str, username: Optional[str]) -> bool:
        """May ``username`` touch ``project``?  Ownerless (or unregistered)
        projects are open; owned ones admit the owner and collaborators.
        Role checks (admin override) live at the API layer."""
        row = self._conn().execute(
            "SELECT owner FROM projects WHERE name = ?", (project,)
        ).fetchone()
        if row is None or row["owner"] in (None, ""):
            return True
        if username is None:
            return False
        if row["owner"] == username:
            return True
        return (
            self._conn().execute(
                "SELECT 1 FROM project_collaborators WHERE project_name = ?"
                " AND username = ?",
                (project, username),
            ).fetchone()
            is not None
        )

    def list_projects(self) -> List[Dict[str, Any]]:
        """Registered projects ∪ projects implied by runs, with run counts."""
        rows = self._conn().execute(
            """SELECT p.id AS id, p.name AS name, p.description AS description,
                      p.owner AS owner, p.created_at AS created_at,
                      COUNT(r.id) AS num_runs
               FROM projects p LEFT JOIN runs r ON r.project = p.name
               GROUP BY p.id
               UNION ALL
               SELECT NULL AS id, r.project AS name, NULL AS description,
                      NULL AS owner, MIN(r.created_at) AS created_at,
                      COUNT(*) AS num_runs
               FROM runs r
               WHERE r.project NOT IN (SELECT name FROM projects)
               GROUP BY r.project
               ORDER BY 2"""
        ).fetchall()
        return [dict(r) for r in rows]

    def get_project(self, name: str) -> Optional[Dict[str, Any]]:
        row = self._conn().execute(
            "SELECT id, name, description, owner, created_at FROM projects"
            " WHERE name = ?",
            (name,),
        ).fetchone()
        num_runs = self._conn().execute(
            "SELECT COUNT(*) FROM runs WHERE project = ?", (name,)
        ).fetchone()[0]
        if row is None:
            # Run-implied project (list_projects shows these too): the
            # detail endpoint must not 404 on names the listing returned,
            # and must return the SAME shape the listing used.
            if num_runs == 0:
                return None
            first = self._conn().execute(
                "SELECT MIN(created_at) FROM runs WHERE project = ?", (name,)
            ).fetchone()[0]
            return {"id": None, "name": name, "description": None,
                    "owner": None, "collaborators": [],
                    "created_at": first, "num_runs": num_runs}
        return {
            **dict(row),
            "num_runs": num_runs,
            "collaborators": self.project_collaborators(name),
        }

    def delete_project(self, name: str) -> Tuple[bool, List[Run]]:
        """Delete a project, cascading to its ARCHIVED runs (returned so
        the caller can GC their artifacts).  Refuses while live (non-
        archived) runs still reference it — archive-then-delete is the
        flow, matching the reference where only archived entities are
        deletable and ``project.delete()`` cascades."""
        live = self._conn().execute(
            "SELECT COUNT(*) FROM runs WHERE project = ? AND archived_at IS NULL",
            (name,),
        ).fetchone()[0]
        if live:
            raise RegistryError(
                f"Project {name!r} still has {live} live runs; archive or"
                " delete them first"
            )
        victims: List[Run] = []
        for row in self._conn().execute(
            "SELECT id FROM runs WHERE project = ?", (name,)
        ).fetchall():
            try:
                victims.extend(self.delete_run(row["id"]))
            except RegistryError:
                continue  # already cascaded away with an earlier parent
        with self._lock, self._conn() as conn:
            conn.execute(
                "DELETE FROM project_collaborators WHERE project_name = ?", (name,)
            )
            conn.execute("DELETE FROM project_ci WHERE project_name = ?", (name,))
            cur = conn.execute("DELETE FROM projects WHERE name = ?", (name,))
            return cur.rowcount > 0, victims

    # -- chart views (reference db/models/charts.py ChartViewModel) ------------
    def create_chart_view(
        self,
        run_id: int,
        name: str,
        charts: Any,
        meta: Optional[Dict[str, Any]] = None,
        owner: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Save a named chart configuration on a run (what metric set /
        layout the dashboard should plot).  Same-name saves replace —
        a view is a bookmarkable way of LOOKING at a run, not history."""
        if not self._run_exists(run_id):
            raise RegistryError(f"No run with id={run_id}")
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO chart_views (run_id, name, charts, meta, owner, created_at)
                   VALUES (?, ?, ?, ?, ?, ?)
                   ON CONFLICT (run_id, name) DO UPDATE
                   SET charts = excluded.charts, meta = excluded.meta""",
                (
                    run_id,
                    name,
                    json.dumps(charts),
                    json.dumps(meta or {}),
                    owner,
                    now,
                ),
            )
            # Read back INSIDE the lock: a concurrent delete between the
            # upsert and the select would hand _chart_view_row a None.
            row = conn.execute(
                "SELECT * FROM chart_views WHERE run_id = ? AND name = ?",
                (run_id, name),
            ).fetchone()
        return self._chart_view_row(row)

    @staticmethod
    def _chart_view_row(row: sqlite3.Row) -> Dict[str, Any]:
        return {
            "id": row["id"],
            "run_id": row["run_id"],
            "name": row["name"],
            "charts": json.loads(row["charts"]),
            "meta": json.loads(row["meta"]),
            "owner": row["owner"],
            "created_at": row["created_at"],
        }

    def list_chart_views(self, run_id: int) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT * FROM chart_views WHERE run_id = ? ORDER BY created_at",
            (run_id,),
        ).fetchall()
        return [self._chart_view_row(r) for r in rows]

    def delete_chart_view(self, run_id: int, view_id: int) -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "DELETE FROM chart_views WHERE run_id = ? AND id = ?",
                (run_id, view_id),
            )
        return cur.rowcount > 0

    # -- usage analytics (reference tracker/, served at /api/v1/analytics) -----
    def usage_rollup(
        self, days: int = 14, now: Optional[float] = None
    ) -> Dict[str, Any]:
        """Event counts per day + platform summary.  Counts come from the
        activity feed, so the window is bounded by the activity retention
        horizon (``logs.retention_days``, default 30)."""
        now = now or time.time()
        cutoff = now - days * 86400.0
        conn = self._conn()
        per_day: Dict[str, Dict[str, int]] = {}
        for row in conn.execute(
            """SELECT date(created_at, 'unixepoch') AS day, event_type,
                      COUNT(*) AS n
               FROM activity WHERE created_at >= ?
               GROUP BY day, event_type ORDER BY day""",
            (cutoff,),
        ):
            per_day.setdefault(row["day"], {})[row["event_type"]] = row["n"]
        runs_by_kind = {
            r["kind"]: r["n"]
            for r in conn.execute(
                "SELECT kind, COUNT(*) AS n FROM runs GROUP BY kind"
            )
        }
        runs_by_status = {
            r["status"]: r["n"]
            for r in conn.execute(
                "SELECT status, COUNT(*) AS n FROM runs GROUP BY status"
            )
        }
        return {
            "window_days": days,
            "events_per_day": per_day,
            "runs_by_kind": runs_by_kind,
            "runs_by_status": runs_by_status,
            "num_users": conn.execute("SELECT COUNT(*) FROM users").fetchone()[0],
            "num_projects": conn.execute(
                "SELECT COUNT(*) FROM projects"
            ).fetchone()[0],
            "num_devices": conn.execute(
                "SELECT COUNT(*) FROM devices"
            ).fetchone()[0],
        }

    # -- CI (per-project trigger config) ---------------------------------------
    # Parity: the reference's CI app (``api/ci/`` + ``ci/service.py``) —
    # a per-project toggle holding the spec to run whenever NEW code
    # arrives.  There "new code" is a repo commit; here it's a new
    # content-hashed snapshot ref (``stores/snapshots.py`` is the
    # dockerizer replacement, so the snapshot hash IS the code ref).

    def set_project_ci(self, project: str, spec: Dict[str, Any]) -> Dict[str, Any]:
        now = time.time()
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO project_ci (project_name, spec, created_at, updated_at)
                   VALUES (?, ?, ?, ?)
                   ON CONFLICT (project_name) DO UPDATE
                   SET spec = excluded.spec, updated_at = excluded.updated_at,
                       last_code_ref = NULL""",
                (project, json.dumps(spec), now, now),
            )
        return self.get_project_ci(project)

    def get_project_ci(self, project: str) -> Optional[Dict[str, Any]]:
        row = self._conn().execute(
            "SELECT * FROM project_ci WHERE project_name = ?", (project,)
        ).fetchone()
        if row is None:
            return None
        return {
            "project": row["project_name"],
            "spec": json.loads(row["spec"]),
            "last_code_ref": row["last_code_ref"],
            "created_at": row["created_at"],
            "updated_at": row["updated_at"],
        }

    def delete_project_ci(self, project: str) -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "DELETE FROM project_ci WHERE project_name = ?", (project,)
            )
        return cur.rowcount > 0

    def advance_ci_code_ref(self, project: str, code_ref: str) -> bool:
        """Record ``code_ref`` as seen; True only when it was NEW (the
        reference's ``CIService.sync`` code-ref comparison) — the atomic
        check-and-set is what makes concurrent triggers fire once."""
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                """UPDATE project_ci SET last_code_ref = ?, updated_at = ?
                   WHERE project_name = ? AND
                         (last_code_ref IS NULL OR last_code_ref != ?)""",
                (code_ref, time.time(), project, code_ref),
            )
        return cur.rowcount > 0

    # -- saved searches (reference api/searches/) ------------------------------
    def create_search(
        self, name: str, query: str, owner: Optional[str] = None
    ) -> Dict[str, Any]:
        try:
            with self._lock, self._conn() as conn:
                cur = conn.execute(
                    "INSERT INTO searches (name, query, owner, created_at)"
                    " VALUES (?, ?, ?, ?)",
                    (name, query, owner, time.time()),
                )
        except sqlite3.IntegrityError as e:
            raise RegistryError(f"Search {name!r} already exists") from e
        return {"id": cur.lastrowid, "name": name, "query": query, "owner": owner}

    def list_searches(self) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT id, name, query, owner, created_at FROM searches ORDER BY name"
        ).fetchall()
        return [dict(r) for r in rows]

    def get_search(self, name: str) -> Optional[Dict[str, Any]]:
        row = self._conn().execute(
            "SELECT id, name, query, owner FROM searches WHERE name = ?", (name,)
        ).fetchone()
        return dict(row) if row else None

    def delete_search(self, name: str) -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute("DELETE FROM searches WHERE name = ?", (name,))
            return cur.rowcount > 0

    # -- bookmarks (reference api/bookmarks/) ----------------------------------
    def add_bookmark(self, run_id: int, owner: str = "") -> None:
        self.get_run(run_id)  # 404 before write
        with self._lock, self._conn() as conn:
            conn.execute(
                "INSERT OR IGNORE INTO bookmarks (run_id, owner, created_at)"
                " VALUES (?, ?, ?)",
                (run_id, owner, time.time()),
            )

    def remove_bookmark(self, run_id: int, owner: str = "") -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute(
                "DELETE FROM bookmarks WHERE run_id = ? AND owner = ?",
                (run_id, owner),
            )
            return cur.rowcount > 0

    def list_bookmarked_runs(self, owner: str = "") -> List[Run]:
        rows = self._conn().execute(
            """SELECT runs.* FROM runs
               JOIN bookmarks ON bookmarks.run_id = runs.id
               WHERE bookmarks.owner = ? ORDER BY bookmarks.created_at DESC""",
            (owner,),
        ).fetchall()
        return [_row_to_run(r) for r in rows]

    # -- users (per-user API tokens) -------------------------------------------
    @staticmethod
    def _token_hash(token: str) -> str:
        import hashlib

        # surrogateescape: a garbage (non-UTF-8) Authorization header must
        # hash to a non-match, not raise into a 500.
        return hashlib.sha256(
            token.encode("utf-8", "surrogateescape")
        ).hexdigest()

    def create_user(self, username: str, role: str = "user") -> Tuple[Dict[str, Any], str]:
        """Create a user and mint their token (returned ONCE, stored hashed).

        Parity: reference users + per-user auth tokens (``scopes/``,
        ``db/models`` user tables) — collapsed to username/role/token.
        """
        import secrets

        if role not in ("admin", "user"):
            raise RegistryError(f"Unknown role {role!r} (admin|user)")
        token = secrets.token_hex(20)
        try:
            with self._lock, self._conn() as conn:
                cur = conn.execute(
                    "INSERT INTO users (username, token_hash, role, created_at)"
                    " VALUES (?, ?, ?, ?)",
                    (username, self._token_hash(token), role, time.time()),
                )
                user_id = cur.lastrowid
        except sqlite3.IntegrityError as e:
            raise RegistryError(f"User {username!r} already exists") from e
        return {"id": user_id, "username": username, "role": role}, token

    def ensure_sso_user(
        self, provider: str, username: str, role: str = "user"
    ) -> Tuple[Dict[str, Any], str]:
        """Upsert the SSO identity ``provider:username``, minting a FRESH
        token (returned once, stored hashed) — every login rotates it, so
        a stale leaked token dies at the next sign-in.  Existing role is
        preserved (an admin promoted in-platform stays admin).

        An identity only ever matches a user row CREATED BY THE SAME
        PROVIDER: a locally-minted user (or another provider's) with a
        colliding name is a hard error, never a takeover — on a public
        provider anyone can register any free username."""
        if role not in ("admin", "user"):
            raise RegistryError(f"Unknown role {role!r} (admin|user)")
        import secrets

        token = secrets.token_hex(20)
        with self._lock, self._conn() as conn:
            row = conn.execute(
                "SELECT id, role, sso_provider FROM users WHERE username = ?",
                (username,),
            ).fetchone()
            if row is not None and row["sso_provider"] != provider:
                kind = (
                    "locally-created"
                    if not row["sso_provider"]
                    else f"{row['sso_provider']}-linked"
                )
                raise RegistryError(
                    f"A {kind} user named {username!r} already exists; "
                    f"refusing to link the {provider} identity to it"
                )
            if row is None:
                cur = conn.execute(
                    "INSERT INTO users (username, token_hash, role,"
                    " sso_provider, created_at) VALUES (?, ?, ?, ?, ?)",
                    (
                        username,
                        self._token_hash(token),
                        role,
                        provider,
                        time.time(),
                    ),
                )
                return (
                    {
                        "id": cur.lastrowid,
                        "username": username,
                        "role": role,
                        "created": True,
                    },
                    token,
                )
            conn.execute(
                "UPDATE users SET token_hash = ? WHERE id = ?",
                (self._token_hash(token), row["id"]),
            )
            return (
                {
                    "id": row["id"],
                    "username": username,
                    "role": row["role"],
                    "created": False,
                },
                token,
            )

    def get_user(self, username: str) -> Optional[Dict[str, Any]]:
        row = self._conn().execute(
            "SELECT id, username, role, sso_provider, last_used_at FROM users"
            " WHERE username = ?",
            (username,),
        ).fetchone()
        return dict(row) if row is not None else None

    def get_user_by_token(self, token: str) -> Optional[Dict[str, Any]]:
        row = self._conn().execute(
            "SELECT id, username, role, last_used_at FROM users WHERE token_hash = ?",
            (self._token_hash(token),),
        ).fetchone()
        if row is None:
            return None
        now = time.time()
        # last_used_at is observability, not security: refresh at most once
        # a minute so the hot auth path isn't a write transaction per call.
        if row["last_used_at"] is None or now - row["last_used_at"] > 60.0:
            with self._lock, self._conn() as conn:
                conn.execute(
                    "UPDATE users SET last_used_at = ? WHERE id = ?",
                    (now, row["id"]),
                )
        return {k: row[k] for k in ("id", "username", "role")}

    def list_users(self) -> List[Dict[str, Any]]:
        rows = self._conn().execute(
            "SELECT id, username, role, created_at, last_used_at FROM users"
            " ORDER BY username"
        ).fetchall()
        return [dict(r) for r in rows]

    def remove_user(self, username: str) -> bool:
        with self._lock, self._conn() as conn:
            cur = conn.execute("DELETE FROM users WHERE username = ?", (username,))
            return cur.rowcount > 0

    def has_users(self) -> bool:
        return self._conn().execute("SELECT 1 FROM users LIMIT 1").fetchone() is not None

    # -- options (DB-backed conf store) ---------------------------------------
    def set_option(self, key: str, value: Any) -> None:
        with self._lock, self._conn() as conn:
            conn.execute(
                """INSERT INTO options (key, value) VALUES (?, ?)
                   ON CONFLICT (key) DO UPDATE SET value = excluded.value""",
                (key, json.dumps(value)),
            )

    def get_option(self, key: str, default: Any = None) -> Any:
        row = self._conn().execute(
            "SELECT value FROM options WHERE key = ?", (key,)
        ).fetchone()
        return json.loads(row["value"]) if row else default

    def delete_option(self, key: str) -> None:
        with self._lock, self._conn() as conn:
            conn.execute("DELETE FROM options WHERE key = ?", (key,))


# Instrument every public RunRegistry method with the op-family timer.
# Done once at import — the per-call cost without an attached stats
# backend is one attribute check inside the wrapper.
import types as _types

for _name, _fn in list(vars(RunRegistry).items()):
    if (
        _name.startswith("_")
        or _name in ("attach_stats", "close")
        or not isinstance(_fn, _types.FunctionType)
    ):
        continue
    setattr(RunRegistry, _name, _timed_op(_name, _fn))
del _name, _fn
