"""Flagship model: a decoder-only transformer LM, TPU-first.

The reference platform ships no models (training code lives in user
containers — SURVEY §2.8); the TPU framework needs a first-class flagship
so sharding templates, benchmarks, and the driver hooks have a real
workload.  Design choices are all MXU/HBM-driven:

- **bfloat16 compute, float32 params/accumulation** — MXU-native.
- **einsum everywhere** — large, fusable contractions XLA tiles onto the
  systolic array; no per-head Python loops.
- **stacked layer parameters + ``lax.scan``** — one compiled block body
  regardless of depth (fast compiles), and the leading ``layers`` axis IS
  the pipeline-stage axis for pp sharding.
- **logical axis names on every parameter** (``param_axes``) — the
  parallelism templates (``polyaxon_tpu.parallel.templates``) map them onto
  any mesh; the model never mentions a mesh axis.
- optional **MoE MLP** (top-1 switch routing, einsum dispatch/combine) for
  expert parallelism; optional **ring attention** for sequence parallelism.
- ``jax.checkpoint`` on the block body (``remat=True``) to trade FLOPs for
  HBM on long sequences.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from polyaxon_tpu.parallel.axes import AxisRules, with_logical_constraint


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    head_dim: int = 64
    d_ff: int = 2048
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    #: 0 = dense MLP; >0 = MoE with this many experts (top-1 switch routing)
    n_experts: int = 0
    #: per-expert capacity = capacity_factor * tokens / n_experts
    capacity_factor: float = 1.25
    remat: bool = False
    #: What the checkpointed block may KEEP across the bwd recompute:
    #: "none" (recompute everything — max memory savings), "dots" (keep
    #: matmul outputs), "dots_no_batch" (keep batch-free matmuls),
    #: "save_attn" (keep the attention output — skips re-running the
    #: attention subgraph; the measured v5e sweet spot, docs/bench-notes),
    #: "save_attn_mlp" (also keep the post-activation MLP product).
    remat_policy: str = "none"

    #: Pallas flash kernel tile edge (block_q = block_k); a VMEM-budget
    #: knob.  1024 is the measured v5e optimum — 3.9x the throughput of
    #: 128 at T=8192; 2048 exceeds the 16M scoped-vmem limit
    #: (docs/bench-notes.md).
    flash_block: int = 1024
    #: Grouped-query attention: number of K/V heads (None = n_heads, i.e.
    #: full multi-head).  Fewer KV heads shrink the KV params/optimizer
    #: state and — under sp_ring — the per-hop ppermute payload by
    #: n_heads/n_kv_heads (the ring rotates UNEXPANDED KV blocks and
    #: broadcasts them to the query heads only inside the kernel call).
    n_kv_heads: Optional[int] = None

    def __post_init__(self) -> None:
        allowed = (
            "none", "dots", "dots_no_batch", "save_attn", "save_attn_mlp",
            "save_qkv_attn",
        )
        if self.remat_policy not in allowed:
            raise ValueError(
                f"Unknown remat_policy {self.remat_policy!r} (one of {allowed})"
            )
        if self.n_kv_heads is not None and not (
            0 < self.n_kv_heads <= self.n_heads
        ):
            raise ValueError(
                f"n_kv_heads ({self.n_kv_heads}) must be in [1, n_heads="
                f"{self.n_heads}]"
            )
        if self.n_heads % self.kv_heads:
            raise ValueError(
                f"n_heads ({self.n_heads}) must be divisible by n_kv_heads "
                f"({self.kv_heads})"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_heads if self.n_kv_heads is None else self.n_kv_heads
    #: "auto" = pallas flash kernel on single-device TPU, XLA attention
    #: elsewhere; "dense" forces XLA; "flash" forces the pallas kernel.
    #: (A pallas call is a custom call GSPMD can't partition, so the
    #: unsharded flash path is only taken when attention runs on one
    #: device.  With a ring template the value selects the RING body
    #: instead: flash-per-block inside shard_map — sharded long context
    #: runs the O(T_local) kernel per shard; see parallel/flash.py.)
    attention_impl: str = "auto"
    #: Blockwise cross-entropy sequence-chunk size (0 = off).  When set
    #: (and T divides evenly), loss_fn never materializes the full
    #: [B,T,vocab] f32 logits — the step's single largest activation
    #: (2.1G at the bench shape) — computing logsumexp + target logit one
    #: [B,chunk] slice at a time under jax.checkpoint, so the backward
    #: recomputes each chunk's logits instead of keeping them resident.
    ce_chunk: int = 0

    def scaled(self, **overrides) -> "TransformerConfig":
        return replace(self, **overrides)

    @property
    def n_params(self) -> int:
        """Parameter count (for MFU math)."""
        c = self
        attn = c.d_model * c.head_dim * (2 * c.n_heads + 2 * c.kv_heads)
        if c.n_experts:
            mlp = c.d_model * c.n_experts + c.n_experts * c.d_model * c.d_ff * 3
        else:
            mlp = c.d_model * c.d_ff * 3
        per_layer = attn + mlp + 2 * c.d_model
        return c.vocab_size * c.d_model * 2 + c.n_layers * per_layer + c.d_model


def param_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical axis names for every parameter (mirrors ``init_params``)."""
    block: Dict[str, Any] = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "heads", "head_dim"),
        "wv": ("layers", "embed", "heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
    }
    if cfg.n_experts:
        block.update(
            router=("layers", "embed", "experts"),
            wi=("layers", "experts", "embed", "mlp"),
            wg=("layers", "experts", "embed", "mlp"),
            wd=("layers", "experts", "mlp", "embed"),
        )
    else:
        block.update(
            wi=("layers", "embed", "mlp"),
            wg=("layers", "embed", "mlp"),
            wd=("layers", "mlp", "embed"),
        )
    return {
        "embed": ("vocab", "embed"),
        "unembed": ("embed", "vocab"),
        "final_norm": ("embed",),
        "block": block,
    }


def init_params(key: jax.Array, cfg: TransformerConfig) -> Dict[str, Any]:
    c = cfg
    k = iter(jax.random.split(key, 16))
    dt = c.param_dtype

    def norm(*shape, scale):
        return jax.random.normal(next(k), shape, dt) * scale

    L, D, H, hd, F = c.n_layers, c.d_model, c.n_heads, c.head_dim, c.d_ff
    Hkv = c.kv_heads
    block: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": norm(L, D, H, hd, scale=D**-0.5),
        "wk": norm(L, D, Hkv, hd, scale=D**-0.5),
        "wv": norm(L, D, Hkv, hd, scale=D**-0.5),
        "wo": norm(L, H, hd, D, scale=(H * hd) ** -0.5),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if c.n_experts:
        E = c.n_experts
        block.update(
            router=norm(L, D, E, scale=D**-0.5),
            wi=norm(L, E, D, F, scale=D**-0.5),
            wg=norm(L, E, D, F, scale=D**-0.5),
            wd=norm(L, E, F, D, scale=F**-0.5),
        )
    else:
        block.update(
            wi=norm(L, D, F, scale=D**-0.5),
            wg=norm(L, D, F, scale=D**-0.5),
            wd=norm(L, F, D, scale=F**-0.5),
        )
    return {
        "embed": norm(c.vocab_size, D, scale=1.0),
        "unembed": norm(D, c.vocab_size, scale=D**-0.5),
        "final_norm": jnp.ones((D,), dt),
        "block": block,
    }


def _rmsnorm(x: jax.Array, w: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + 1e-6).astype(x.dtype)) * w.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over the last (head_dim) axis. x: [B,T,H,d]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,d/2]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


def _dense_attention(q, k, v, q_pos, k_pos):
    """Causal attention. q:[B,Tq,H,d] k,v:[B,Tk,H,d] → [B,Tq,H,d]."""
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    mask = q_pos[:, None, :, None] >= k_pos[:, None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _flash_attention(q, k, v, block: int = 1024):
    """Pallas fused causal attention (TPU): O(T) memory, no [T,T] scores.

    The HBM-bandwidth win the reference could never express (its compute
    lived in user containers): the score matrix never leaves VMEM, so long
    sequences fit without remat.  Uses the framework's own kernel
    (parallel/flash.py — the ring body's block kernel over the full
    sequence): measured 1.9x the jax-bundled pallas kernel in full train
    steps at T=8192 on v5e.
    """
    from polyaxon_tpu.parallel.flash import _on_tpu, flash_attention

    cfg = (q.shape[-1] ** -0.5, block, block, not _on_tpu())
    return flash_attention(cfg, q, k, v)


def _platform_is_tpu() -> bool:
    from polyaxon_tpu.parallel.flash import _on_tpu

    return _on_tpu()


def _use_flash(
    cfg: TransformerConfig, mesh, ring_axis, pipeline_axis, seq_len: int
) -> bool:
    if cfg.attention_impl == "dense" or ring_axis is not None:
        return False
    if cfg.attention_impl == "flash":
        return True
    # auto: whenever attention runs unsharded on a TPU backend. With
    # 1024-edge tiles the in-house kernel beats XLA's dense path at EVERY
    # measured shape on v5e full train steps (remat, 671M params):
    # 0.554 vs 0.529 at T=1024, 0.507 vs 0.394 at T=2048, 0.482 vs 0.325
    # at T=4096, and past the dense HBM wall it is the only thing that
    # runs (0.459 at T=8192, 0.405 at T=16384 via sp_ring n=1) — see
    # docs/bench-notes.md for the sweep.
    if pipeline_axis is not None or (mesh is not None and mesh.size > 1):
        return False
    return _platform_is_tpu()


def _moe_mlp(x, layer, cfg: TransformerConfig, rules: AxisRules, mesh):
    """Top-1 (switch) MoE with einsum dispatch/combine.

    Token dispatch is expressed as dense einsums over a capacity-bounded
    one-hot: with ``experts``→``expert`` sharding, XLA lowers the dispatch/
    combine contractions into the expert all-to-alls — no manual comms.
    """
    B, T, D = x.shape
    E = cfg.n_experts
    tokens = B * T
    capacity = max(1, int(cfg.capacity_factor * tokens / E))

    logits = jnp.einsum("btd,de->bte", x, layer["router"].astype(x.dtype))
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [B,T,E]
    flat_gates = gates.reshape(tokens, E)
    expert_idx = jnp.argmax(flat_gates, axis=-1)  # [tokens]
    gate_val = jnp.take_along_axis(flat_gates, expert_idx[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [tokens,E]
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0  # rank within expert
    keep = (position < capacity) & (onehot > 0)
    pos_onehot = jax.nn.one_hot(
        jnp.where(keep.any(-1), position.max(-1), -1).astype(jnp.int32),
        capacity,
        dtype=jnp.float32,
    )  # [tokens, C]
    dispatch = (onehot * keep)[:, :, None] * pos_onehot[:, None, :]  # [tokens,E,C]
    combine = dispatch * gate_val[:, None, None]

    xf = x.reshape(tokens, D)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xf)
    expert_in = with_logical_constraint(expert_in, ("experts",), rules, mesh)
    h = jnp.einsum("ecd,edf->ecf", expert_in, layer["wi"].astype(x.dtype))
    g = jnp.einsum("ecd,edf->ecf", expert_in, layer["wg"].astype(x.dtype))
    h = jax.nn.silu(g) * h
    out = jnp.einsum("ecf,efd->ecd", h, layer["wd"].astype(x.dtype))
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out)
    return y.reshape(B, T, D), gates, expert_idx.reshape(B, T)


def moe_aux_loss(gates: jax.Array, expert_idx: jax.Array, n_experts: int) -> jax.Array:
    """Switch-transformer load-balancing loss (mean over layers outside)."""
    me = jnp.mean(gates.reshape(-1, n_experts), axis=0)
    ce = jnp.mean(
        jax.nn.one_hot(expert_idx.reshape(-1), n_experts, dtype=jnp.float32), axis=0
    )
    return n_experts * jnp.sum(me * ce)


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    cfg: TransformerConfig,
    template=None,
    mesh=None,
    positions: Optional[jax.Array] = None,
    return_kv: bool = False,
    return_hidden: bool = False,
) -> jax.Array:
    """tokens [B,T] → logits [B,T,vocab] (float32).

    ``return_kv`` additionally returns the per-layer POST-rope,
    UNEXPANDED (GQA) key/value stacks ``[L,B,T,Hkv,d]`` — the decode
    prefill (``models/decode.py``) rides this so the cache layout comes
    from the SAME block the training forward runs, instead of a
    duplicated one.  Plain-scan single-program path only (no template),
    dense MLP only.

    ``template`` (a :class:`~polyaxon_tpu.parallel.StrategyTemplate`) plus
    ``mesh`` activate logical sharding constraints and select the attention/
    layer-evaluation path: ring attention when ``template.ring_axis`` is
    set, the GPipe schedule when ``template.pipeline_axis`` is set, plain
    scan otherwise.  With a sequence-sharded template, ``positions`` carries
    each shard's global token positions.
    """
    c = cfg
    rules: AxisRules = template.rules if template is not None else {}
    ring_axis = template.ring_axis if template is not None else None
    pipeline_axis = template.pipeline_axis if template is not None else None
    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))

    # Inside a fully-manual pipeline shard_map, sharding constraints must
    # be inert; the composed mode (pp_tp) keeps the other mesh axes auto,
    # so constraints stay live and GSPMD shards the stage body over them.
    composed = bool(template is not None and template.pipeline_composed)
    cmesh = None if (pipeline_axis and not composed) else mesh
    use_flash = _use_flash(c, mesh, ring_axis, pipeline_axis, T)
    if return_kv and (template is not None or c.n_experts):
        raise NotImplementedError(
            "return_kv supports the plain-scan dense path only (no "
            "parallelism template, no MoE)"
        )
    # Ulysses long-context: the flash kernel can't ride GSPMD (a pallas
    # call is an unpartitionable custom call), so past the dense memory
    # wall (or when forced) the attention goes through the EXPLICIT
    # all-to-all shard_map twin instead of the attn_heads constraints.
    ulysses_axis = template.ulysses_axis if template is not None else None
    ulysses_flash = bool(
        ulysses_axis is not None
        and pipeline_axis is None
        and (
            c.attention_impl == "flash"
            or (c.attention_impl == "auto" and T >= 8192 and _platform_is_tpu())
        )
    )

    table = params["embed"].astype(c.dtype)
    if cmesh is not None and cmesh.size > 1 and (
        rules.get("vocab") or rules.get("embed")
    ):
        # Sharded table: express the lookup as a one-hot matmul (iota
        # embed).  A gather's transpose is a scatter-add, and SPMD's
        # scatter partitioner cannot place batch-sharded updates into an
        # embed/vocab-sharded table without an involuntary full
        # rematerialization (replicate dx, then repartition — an
        # all-gather of [B,T,D] over the whole mesh, DCN included, every
        # step).  The one-hot contraction instead yields partial grads
        # that reduce-scatter into the param placement like every other
        # matmul.  Single-device keeps the free gather.
        onehot = jax.nn.one_hot(tokens, c.vocab_size, dtype=c.dtype)
        x = jnp.einsum("btv,vd->btd", onehot, table)
    else:
        x = table[tokens]  # [B,T,D]
    x = with_logical_constraint(x, ("batch", "seq", None), rules, cmesh)

    def norm_w(w):
        # Replicate norm weights at point of use: under fsdp their embed
        # dim is sharded over a data-like axis, and if that sharding rides
        # into the scan's saved residual, the backward multiplies a
        # batch-sharded cotangent with an embed-sharded [1,1,D] tensor —
        # SPMD resolves that with an involuntary full rematerialization
        # (replicate-then-repartition of the whole activation, every
        # layer).  An explicit replicate of D floats is noise and keeps
        # the residual conflict-free; the weight GRAD still reduces into
        # the sharded param placement.
        return with_logical_constraint(w, (None,), rules, cmesh)

    def block(x, pos, layer):
        h = _rmsnorm(x, norm_w(layer["attn_norm"]))
        q = jnp.einsum("btd,dhk->bthk", h, layer["wq"].astype(h.dtype))
        k = jnp.einsum("btd,dhk->bthk", h, layer["wk"].astype(h.dtype))
        v = jnp.einsum("btd,dhk->bthk", h, layer["wv"].astype(h.dtype))
        q = _rope(q, pos, c.rope_theta)
        k = _rope(k, pos, c.rope_theta)
        # GQA: the ring carries UNEXPANDED KV (its ppermute payload shrinks
        # by n_heads/n_kv_heads and the ring broadcasts inside the kernel
        # call); every other path broadcasts KV heads to the query heads
        # here — the einsum/flash/Ulysses machinery then sees plain MHA.
        group = c.n_heads // c.kv_heads
        kv_cache_k, kv_cache_v = k, v  # post-rope, pre-broadcast (GQA)
        if group > 1 and ring_axis is None:
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        if not ulysses_flash and ring_axis is None:
            # Ulysses switch-point (GSPMD/dense form): constraining
            # attn_heads re-shards heads across the sequence axis (XLA
            # inserts the all-to-all).  The flash form does its own
            # all-to-alls inside shard_map, and the RING likewise wants
            # its seq-sharded inputs untouched — for both, constraining
            # here would force a redundant gather/reshard round-trip
            # (sp_ring maps no attn_heads rule, so the constraint would
            # degrade to "replicate the sequence dim").
            q = with_logical_constraint(q, ("batch", None, "attn_heads", None), rules, cmesh)
            k = with_logical_constraint(k, ("batch", None, "attn_heads", None), rules, cmesh)
            v = with_logical_constraint(v, ("batch", None, "attn_heads", None), rules, cmesh)
        # Named AFTER the attn_heads constraint so remat policies save the
        # post-reshard tensors: under Ulysses the bwd recompute must not
        # re-run the all-to-alls the save exists to skip.
        q = checkpoint_name(q, "q_proj")
        k = checkpoint_name(k, "k_proj")
        v = checkpoint_name(v, "v_proj")
        kv_out = (kv_cache_k, kv_cache_v) if return_kv else None
        if ulysses_flash:
            from polyaxon_tpu.parallel.ulysses import ulysses_attention_sharded

            attn = ulysses_attention_sharded(
                q, k, v, mesh, ulysses_axis,
                batch_axes=rules.get("batch"),
                block_q=c.flash_block,
                block_k=c.flash_block,
            )
        elif ring_axis is not None:
            from polyaxon_tpu.parallel.ring import ring_attention_sharded

            # The ring resolves its own kernel: pallas flash per block on
            # TPU (O(T_local) memory per shard), dense blockwise elsewhere.
            attn = ring_attention_sharded(
                q, k, v, mesh, ring_axis,
                batch_axes=rules.get("batch"),
                impl=c.attention_impl,
                block_q=c.flash_block,
                block_k=c.flash_block,
            )
        elif use_flash:
            attn = _flash_attention(q, k, v, block=c.flash_block)
        else:
            attn = _dense_attention(q, k, v, pos, pos)
        attn = with_logical_constraint(
            attn, ("batch", "seq", "attn_heads", None), rules, cmesh
        )
        # Named for remat policies: saving the attention OUTPUT (O(B·T·D),
        # cheap) lets the checkpointed block skip re-running the whole
        # attention kernel during its backward-pass recompute.
        attn = checkpoint_name(attn, "attn_out")
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["wo"].astype(h.dtype))

        h = _rmsnorm(x, norm_w(layer["mlp_norm"]))
        if c.n_experts:
            y, gates, idx = _moe_mlp(h, layer, c, rules, cmesh)
            x = x + y
            return x, (gates, idx)
        up = jnp.einsum("btd,df->btf", h, layer["wi"].astype(h.dtype))
        gate = jnp.einsum("btd,df->btf", h, layer["wg"].astype(h.dtype))
        y = jax.nn.silu(gate) * up
        y = with_logical_constraint(y, ("batch", "seq", "act_mlp"), rules, cmesh)
        # Saving this one [B,T,F] product (policy save_attn_mlp) spares the
        # recompute of BOTH up/gate projections — 2 of the 3 MLP matmuls.
        y = checkpoint_name(y, "mlp_act")
        x = x + jnp.einsum("btf,fd->btd", y, layer["wd"].astype(h.dtype))
        x = with_logical_constraint(x, ("batch", "seq", None), rules, cmesh)
        return x, kv_out

    if c.remat:
        # The policy trades HBM for recompute FLOPs: keeping dot outputs
        # skips re-running the MXU-heavy contractions in the bwd pass.
        policies = {
            "dots": jax.checkpoint_policies.dots_saveable,
            "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "save_attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
            "save_attn_mlp": jax.checkpoint_policies.save_only_these_names(
                "attn_out", "mlp_act"
            ),
            "save_qkv_attn": jax.checkpoint_policies.save_only_these_names(
                "q_proj", "k_proj", "v_proj", "attn_out"
            ),
        }
        policy = policies.get(c.remat_policy)
        body = (
            jax.checkpoint(block, policy=policy) if policy else jax.checkpoint(block)
        )
    else:
        body = block

    aux = None
    if pipeline_axis is not None:
        from polyaxon_tpu.parallel.pipeline import (
            pipeline_scan,
            pipeline_scan_composed,
        )

        # pp×MoE: the balance loss is reduced to a scalar INSIDE the
        # schedule (per stage, valid ticks only) because the raw gate
        # tensors live per-microbatch inside the shard_map.
        aux_fn = (
            (lambda a: moe_aux_loss(a[0], a[1], c.n_experts))
            if c.n_experts
            else None
        )
        if composed:
            x, pp_aux = pipeline_scan_composed(
                body,
                x,
                positions,
                params["block"],
                mesh,
                axis=pipeline_axis,
                num_microbatches=template.num_microbatches,
                aux_fn=aux_fn,
            )
        else:
            x, pp_aux = pipeline_scan(
                body,
                x,
                positions,
                params["block"],
                mesh,
                axis=pipeline_axis,
                num_microbatches=template.num_microbatches,
                batch_axes=rules.get("batch"),
                aux_fn=aux_fn,
            )
        if c.n_experts:
            aux = {"aux_loss": pp_aux}
    else:
        x, scan_aux = lax.scan(
            lambda carry, layer: body(carry, positions, layer), x, params["block"]
        )
        # scan_aux is the per-layer stack of whatever ``block`` returned as
        # its aux: MoE gate stats when n_experts, else the (k, v) cache
        # rows when return_kv (each [L, B, T, Hkv, d] after stacking).
        if c.n_experts or return_kv:
            aux = scan_aux

    x = _rmsnorm(x, norm_w(params["final_norm"]))
    if return_hidden:
        # Pre-unembed hidden states for the blockwise cross-entropy
        # (loss_fn's ce_chunk path): the [B,T,vocab] f32 logits tensor —
        # the single largest activation of the whole step — is never
        # materialized; the caller contracts x against ``unembed`` one
        # sequence chunk at a time.
        if c.n_experts and aux is not None:
            return x, aux
        return x
    logits = jnp.einsum("btd,dv->btv", x, params["unembed"].astype(x.dtype))
    logits = with_logical_constraint(logits, ("batch", "seq", None), rules, cmesh)
    if c.n_experts and aux is not None:
        return logits.astype(jnp.float32), aux
    if return_kv:
        return logits.astype(jnp.float32), aux
    return logits.astype(jnp.float32)


def _blockwise_ce(
    x: jax.Array,
    unembed: jax.Array,
    targets: jax.Array,
    mask: Optional[jax.Array],
    chunk: int,
) -> jax.Array:
    """Mean masked next-token NLL without materializing [B,T,V] logits.

    Scans over T/chunk sequence slices; each body projects one [B,C,D]
    slice to logits, reduces to logsumexp + the target logit, and drops
    the logits again.  ``jax.checkpoint`` makes the backward RECOMPUTE
    each chunk's logits rather than saving them — peak CE memory falls
    from O(B·T·V) to O(B·chunk·V) in both passes, trading one extra
    [B,C,D]×[D,V] matmul per chunk (MXU-shaped, cheap next to the HBM
    traffic it saves).  The d(unembed) grads accumulate across chunks
    inside the scan like any scanned-weight gradient.
    """
    B, T, D = x.shape
    n = T // chunk
    xs = jnp.moveaxis(x.reshape(B, n, chunk, D), 1, 0)  # [n,B,C,D]
    ts = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)
    m = (
        jnp.ones((B, T), jnp.float32)
        if mask is None
        else mask.astype(jnp.float32)
    )
    ms = jnp.moveaxis(m.reshape(B, n, chunk), 1, 0)

    @jax.checkpoint
    def body(carry, inp):
        xc, tc, mc = inp
        logits = jnp.einsum(
            "bcd,dv->bcv", xc, unembed.astype(xc.dtype)
        ).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        nll_sum, cnt = carry
        return (
            nll_sum + jnp.sum((lse - tl) * mc),
            cnt + jnp.sum(mc),
        ), None

    (nll_sum, cnt), _ = lax.scan(
        body, (jnp.float32(0.0), jnp.float32(0.0)), (xs, ts, ms)
    )
    return nll_sum / jnp.maximum(cnt, 1.0)


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: TransformerConfig,
    template=None,
    mesh=None,
    aux_weight: float = 0.01,
) -> jax.Array:
    """Next-token cross-entropy (+ MoE balance loss when configured)."""
    targets = batch["targets"]
    mask = batch.get("mask")
    chunked = bool(
        cfg.ce_chunk and targets.shape[-1] % cfg.ce_chunk == 0
    )
    out = forward(
        params,
        batch["tokens"],
        cfg,
        template=template,
        mesh=mesh,
        positions=batch.get("positions"),
        return_hidden=chunked,
    )
    if cfg.n_experts:
        hidden_or_logits, aux = out
    else:
        hidden_or_logits = out
    if chunked:
        loss = _blockwise_ce(
            hidden_or_logits, params["unembed"], targets, mask, cfg.ce_chunk
        )
    else:
        logits = hidden_or_logits
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        if mask is None:
            loss = jnp.mean(nll)
        else:
            loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if cfg.n_experts:
        if isinstance(aux, dict):
            # Pipeline path: already reduced inside the GPipe schedule.
            aux_loss = aux["aux_loss"]
        else:
            gates, idx = aux
            aux_loss = jnp.mean(
                jax.vmap(partial(moe_aux_loss, n_experts=cfg.n_experts))(gates, idx)
            )
        loss = loss + aux_weight * aux_loss
    return loss
