"""Autoregressive decoding for the flagship LM: KV cache + sampling.

The inference half of the model family (the reference has no serving
story at all — its notebooks/tensorboards are the closest surface; this
is capability beyond parity).  TPU-first shape:

- **static-shape KV cache** — a [L, B, max_len, Hkv, d] ring of keys and
  values updated with ``lax.dynamic_update_slice`` at the current
  position; no dynamic shapes anywhere, so the whole decode loop is one
  compiled ``lax.scan``.
- **GQA-native cache** — the cache stores the UNEXPANDED KV heads
  (n_kv_heads), the dominant HBM saving of grouped-query attention at
  inference; broadcast to the query heads happens inside the per-token
  attention contraction.
- **prefill via one batched forward** over the prompt (MXU-shaped), then
  one-token steps; both paths share the same cache layout.

Decode is memory-bandwidth-bound (one token's FLOPs against the whole
cache), so attention here is plain einsum with a position mask — the
flash kernel's VMEM blocking buys nothing at query length 1.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from polyaxon_tpu.models.transformer import (
    TransformerConfig,
    _rmsnorm,
    _rope,
)


def init_cache(
    cfg: TransformerConfig, batch: int, max_len: int
) -> Dict[str, jax.Array]:
    """Zeroed KV cache: k/v [L, B, max_len, Hkv, d] in the compute dtype."""
    c = cfg
    shape = (c.n_layers, batch, max_len, c.kv_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
    }


#: Block matmul weights the int8 path quantizes — ONE list shared by
#: quantize_weights (emit) and decode_step (consume) so they can't drift.
#: Mapped to the contraction dims of each layout: [L,D,H,k] contracts D;
#: [L,H,k,D] contracts H,k; [L,D,F] contracts D; [L,F,D] contracts F.
QUANTIZED_BLOCK_WEIGHTS = {
    "wq": (1,),
    "wk": (1,),
    "wv": (1,),
    "wo": (1, 2),
    "wi": (1,),
    "wg": (1,),
    "wd": (1,),
}


def quantize_weights(params: Dict[str, Any]) -> Dict[str, Any]:
    """int8 weight-only quantization of the decode matmul weights.

    Decode is weight-HBM-bandwidth-bound (the whole parameter set streams
    per token while the MXU idles), so halving the bytes is ~linear
    speedup: measured 469 → 711 tok/s (+51%) GQA-8 and 295 → 419 (+42%)
    full-MHA on the 671M bench model (v5e); single-step fidelity: 2.4%
    relative logits error, top-1 intact (docs/bench-notes.md).
    Symmetric per-output-channel scales over each weight's CONTRACTION
    dims; norms and the embedding table stay full precision (tiny, and
    the gather is not a matmul).  Returns a tree of ``(int8_q,
    f32_scale)`` pairs the decode path consumes via :func:`_wdq`;
    training params are untouched — prefill still rides the
    full-precision forward.
    """
    import numpy as np

    def q(w, axes):
        w = np.asarray(w, np.float32)
        amax = np.max(np.abs(w), axis=axes, keepdims=True) + 1e-12
        scale = (amax / 127.0).astype(np.float32)
        qi = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        return (jnp.asarray(qi), jnp.asarray(scale))

    blk = params["block"]
    out = {
        name: q(blk[name], axes)
        for name, axes in QUANTIZED_BLOCK_WEIGHTS.items()
    }
    out["unembed"] = q(params["unembed"], (0,))  # [D, V]: contract D
    return out


def _wdq(w, dtype):
    """Weight as compute dtype: dequantize ``(int8, scale)`` pairs (XLA
    fuses the convert+scale into the consuming matmul's operand read —
    the HBM stream stays int8) or plain astype."""
    if isinstance(w, tuple):
        qi, scale = w
        return qi.astype(dtype) * scale.astype(dtype)
    return w.astype(dtype)


def _attend_cached(q, ck, cv, pos, group):
    """One-token attention against the cache.

    q: [B, 1, H, d]; ck/cv: [B, max_len, Hkv, d]; ``pos`` is the current
    absolute position (entries > pos are future/zero slots — masked).
    """
    B, L, Hkv, d = ck.shape
    scale = d**-0.5
    # GQA stays grouped INSIDE the contraction — the cache is never
    # materialized at the query-head count, which is the point of storing
    # unexpanded heads in the bandwidth-bound decode loop.
    qg = q.reshape(B, 1, Hkv, group, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, ck) * scale  # [B,Hkv,g,1,L]
    valid = (jnp.arange(L) <= pos)[None, None, None, None, :]
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv)
    return out.reshape(B, 1, Hkv * group, d)


def _block_step(x, pos, layer, ck, cv, cfg: TransformerConfig):
    """One transformer block for ONE new token, reading+updating the cache.

    x: [B, 1, D]; ck/cv: [B, max_len, Hkv, d] (this layer's cache slices).
    Returns (x, ck, cv) with the token's KV rows written at ``pos``.
    """
    c = cfg
    h = _rmsnorm(x, layer["attn_norm"])
    q = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wq"], h.dtype))
    k = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wk"], h.dtype))
    v = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wv"], h.dtype))
    positions = jnp.full((x.shape[0], 1), pos)
    q = _rope(q, positions, c.rope_theta)
    k = _rope(k, positions, c.rope_theta)
    ck = lax.dynamic_update_slice(ck, k, (0, pos, 0, 0))
    cv = lax.dynamic_update_slice(cv, v, (0, pos, 0, 0))
    attn = _attend_cached(q, ck, cv, pos, c.n_heads // c.kv_heads)
    x = x + jnp.einsum("bthk,hkd->btd", attn, _wdq(layer["wo"], h.dtype))

    h = _rmsnorm(x, layer["mlp_norm"])
    up = jnp.einsum("btd,df->btf", h, _wdq(layer["wi"], h.dtype))
    gate = jnp.einsum("btd,df->btf", h, _wdq(layer["wg"], h.dtype))
    y = jax.nn.silu(gate) * up
    x = x + jnp.einsum("btf,fd->btd", y, _wdq(layer["wd"], h.dtype))
    return x, ck, cv


def decode_step(
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    token: jax.Array,
    pos: jax.Array,
    cfg: TransformerConfig,
    qweights: Optional[Dict[str, Any]] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """token [B] at absolute ``pos`` → (logits [B, vocab], updated cache).

    With ``qweights`` (from :func:`quantize_weights`) the matmul weights
    stream int8 from HBM, dequantized inside each contraction."""
    c = cfg
    x = params["embed"].astype(c.dtype)[token][:, None, :]  # [B,1,D]

    blk = params["block"]
    if qweights is None:
        layers = blk
        unembed = params["unembed"]
    else:
        # Quantized (q, scale) pairs are ordinary pytree leaves-of-tuples:
        # scan slices both halves per layer and _wdq sees the pair.
        layers = {
            "attn_norm": blk["attn_norm"],
            "mlp_norm": blk["mlp_norm"],
            **{k: qweights[k] for k in QUANTIZED_BLOCK_WEIGHTS},
        }
        unembed = qweights["unembed"]

    def layer_body(carry, inputs):
        x = carry
        layer, ck, cv = inputs
        x, ck, cv = _block_step(x, pos, layer, ck, cv, c)
        return x, (ck, cv)

    x, (new_ck, new_cv) = lax.scan(
        layer_body, x, (layers, cache["k"], cache["v"])
    )
    x = _rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, _wdq(unembed, x.dtype))
    return logits[:, 0].astype(jnp.float32), {"k": new_ck, "v": new_cv}


def prefill(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: Dict[str, jax.Array],
    cfg: TransformerConfig,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Run the prompt [B, T] through the model, filling cache[:, :, :T].

    Rides the TRAINING forward (``return_kv=True``) — one batched
    MXU-shaped pass whose block is the exact code training runs, so
    prefill can never drift from it; only the cache write lives here.
    """
    from polyaxon_tpu.models.transformer import forward

    logits, (k, v) = forward(params, tokens, cfg, return_kv=True)
    ck = lax.dynamic_update_slice(cache["k"], k, (0, 0, 0, 0, 0))
    cv = lax.dynamic_update_slice(cache["v"], v, (0, 0, 0, 0, 0))
    return logits[:, -1], {"k": ck, "v": cv}


# -- slot-addressed cache ops (continuous batching) ------------------------
# The serving engine (polyaxon_tpu/serving/engine.py) owns ONE fixed-shape
# cache of ``slots`` rows and admits/retires requests at decode-step
# granularity.  Everything below keeps the [L, S, max_len, Hkv, d] shapes
# static — slot index, per-slot positions, and the active mask are all
# DATA, so one compiled step serves any mix of in-flight requests with
# zero steady-state recompilation.


def insert_prompt(
    cache: Dict[str, jax.Array], slot: jax.Array, k: jax.Array, v: jax.Array
) -> Dict[str, jax.Array]:
    """Write one prefilled prompt's KV into batch slot ``slot``.

    k/v: [L, T, Hkv, d] (the ``return_kv`` stacks of a B=1 prefill);
    ``slot`` is a traced scalar, so reusing a slot never recompiles —
    only each distinct prompt length T mints a compilation (the engine
    pads prompts to a small bucket set to bound that).
    """
    k = k.astype(cache["k"].dtype)[:, None]  # [L, 1, T, Hkv, d]
    v = v.astype(cache["v"].dtype)[:, None]
    return {
        "k": lax.dynamic_update_slice(cache["k"], k, (0, slot, 0, 0, 0)),
        "v": lax.dynamic_update_slice(cache["v"], v, (0, slot, 0, 0, 0)),
    }


def _attend_slots(q, ck, cv, pos, group):
    """One-token attention where every slot is at its OWN position.

    q: [S, 1, H, d]; ck/cv: [S, max_len, Hkv, d]; pos: [S] per-slot
    absolute positions (entries > pos[s] in slot s are future/garbage —
    masked; a freed slot's stale rows beyond a new occupant's prompt are
    masked the same way until decode overwrites them in place).
    """
    S, L, Hkv, d = ck.shape
    scale = d**-0.5
    qg = q.reshape(S, 1, Hkv, group, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, ck) * scale  # [S,Hkv,g,1,L]
    valid = (jnp.arange(L)[None, :] <= pos[:, None])[:, None, None, None, :]
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv)
    return out.reshape(S, 1, Hkv * group, d)


def _slot_block_step(x, pos, layer, ck, cv, cfg: TransformerConfig):
    """One transformer block for one token PER SLOT, each at its own
    position.  x: [S, 1, D]; ck/cv: [S, max_len, Hkv, d]; pos: [S].
    The per-slot KV row lands via a vmapped dynamic_update_slice (XLA
    lowers it to a batched scatter — the cache is updated in place, not
    rewritten)."""
    c = cfg
    h = _rmsnorm(x, layer["attn_norm"])
    q = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wq"], h.dtype))
    k = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wk"], h.dtype))
    v = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wv"], h.dtype))
    positions = pos[:, None]  # [S, 1]
    q = _rope(q, positions, c.rope_theta)
    k = _rope(k, positions, c.rope_theta)
    write = jax.vmap(
        lambda cc, kk, p: lax.dynamic_update_slice(cc, kk, (p, 0, 0))
    )
    ck = write(ck, k, pos)
    cv = write(cv, v, pos)
    attn = _attend_slots(q, ck, cv, pos, c.n_heads // c.kv_heads)
    x = x + jnp.einsum("bthk,hkd->btd", attn, _wdq(layer["wo"], h.dtype))

    h = _rmsnorm(x, layer["mlp_norm"])
    up = jnp.einsum("btd,df->btf", h, _wdq(layer["wi"], h.dtype))
    gate = jnp.einsum("btd,df->btf", h, _wdq(layer["wg"], h.dtype))
    y = jax.nn.silu(gate) * up
    x = x + jnp.einsum("btf,fd->btd", y, _wdq(layer["wd"], h.dtype))
    return x, ck, cv


def slot_decode_step(
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    tokens: jax.Array,
    pos: jax.Array,
    active: jax.Array,
    cfg: TransformerConfig,
    qweights: Optional[Dict[str, Any]] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Advance a MIXED batch one token: slot s feeds ``tokens[s]`` at
    absolute position ``pos[s]`` → (logits [S, vocab] f32, updated cache).

    ``active`` [S] bool gates the write position: inactive slots write
    their (garbage) row at position 0 of their own FREE slot, which the
    next occupant's prompt insert overwrites — so idle slots cost one
    wasted lane of compute but can never corrupt a live slot.  This is
    the engine's one jitted hot function; its shapes depend only on the
    slot count, so steady-state serving never recompiles.
    """
    c = cfg
    pos = jnp.where(active, pos, 0)
    x = params["embed"].astype(c.dtype)[tokens][:, None, :]  # [S,1,D]

    blk = params["block"]
    if qweights is None:
        layers = blk
        unembed = params["unembed"]
    else:
        layers = {
            "attn_norm": blk["attn_norm"],
            "mlp_norm": blk["mlp_norm"],
            **{k: qweights[k] for k in QUANTIZED_BLOCK_WEIGHTS},
        }
        unembed = qweights["unembed"]

    def layer_body(carry, inputs):
        x = carry
        layer, ck, cv = inputs
        x, ck, cv = _slot_block_step(x, pos, layer, ck, cv, c)
        return x, (ck, cv)

    x, (new_ck, new_cv) = lax.scan(
        layer_body, x, (layers, cache["k"], cache["v"])
    )
    x = _rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, _wdq(unembed, x.dtype))
    return logits[:, 0].astype(jnp.float32), {"k": new_ck, "v": new_cv}


# -- paged (block-table) cache ops -----------------------------------------
# The vLLM-style refinement of the slot cache: KV lives in a POOL of
# fixed-size blocks [L, num_blocks, block_size, Hkv, d] and each in-flight
# sequence owns a BLOCK TABLE of physical block ids covering its logical
# positions.  Two consequences the slot layout can't express:
#
# - **sharing** — two sequences with a common token prefix point their
#   leading table entries at the SAME physical blocks (the engine
#   ref-counts them; a block a sequence must WRITE into is copied first);
# - **chunked prefill** — a prompt is inserted C tokens at a time by
#   :func:`paged_prefill_chunk`, each chunk attending to the KV already in
#   the table, so a long prompt never stalls the decode loop for its full
#   length.
#
# Shapes stay static everywhere (pool size, table width, chunk bucket);
# tables, positions, and the active mask are DATA, so steady-state serving
# still never recompiles.  Block 0 is reserved by the engine as a trash
# lane: inactive decode lanes and prompt-pad writes land there, and unset
# table entries point at it — every such read is masked by the position
# mask before it can influence a live row.


def init_block_pool(
    cfg: TransformerConfig,
    num_blocks: int,
    block_size: int,
    kv_dtype: Optional[str] = None,
) -> Dict[str, jax.Array]:
    """Zeroed paged KV pool: k/v [L, num_blocks, block_size, Hkv, d].

    With ``kv_dtype="int8"`` the pool instead stores symmetric-quantized
    rows plus their scales — ``k_q``/``v_q`` int8 [L, NB, bs, Hkv, d] and
    ``k_scale``/``v_scale`` f32 [L, NB, bs, Hkv] (one scale per appended
    row per kv-head, so appends quantize once and never touch rows
    already in the block).  At head_dim d that is (d + 4) bytes per head
    row versus 4d for an f32 pool — under 0.3× the HBM at the same
    ``num_blocks × block_size``, i.e. >2× the live blocks at a fixed
    memory budget.
    """
    c = cfg
    shape = (c.n_layers, num_blocks, block_size, c.kv_heads, c.head_dim)
    if kv_dtype is None:
        return {
            "k": jnp.zeros(shape, c.dtype),
            "v": jnp.zeros(shape, c.dtype),
        }
    if str(kv_dtype) != "int8":
        raise ValueError(f"unsupported kv_dtype {kv_dtype!r} (int8 or None)")
    return {
        "k_q": jnp.zeros(shape, jnp.int8),
        "k_scale": jnp.zeros(shape[:-1], jnp.float32),
        "v_q": jnp.zeros(shape, jnp.int8),
        "v_scale": jnp.zeros(shape[:-1], jnp.float32),
    }


def is_quantized_pool(pool: Dict[str, jax.Array]) -> bool:
    """True for the (k_q, k_scale, v_q, v_scale) int8 pool layout."""
    return "k_q" in pool


def pool_geometry(pool: Dict[str, jax.Array]) -> Tuple[int, int, int]:
    """(block_size, kv_heads, head_dim) for either pool layout."""
    leaf = pool["k_q"] if is_quantized_pool(pool) else pool["k"]
    return leaf.shape[2], leaf.shape[3], leaf.shape[4]


def kv_block_bytes(
    cfg: TransformerConfig, block_size: int, kv_dtype: Optional[str] = None
) -> int:
    """Device bytes ONE pool block costs (all layers, k+v, incl. scales).

    The sizing primitive for fixed-HBM capacity math: at a fixed byte
    budget B the pool holds ``B // kv_block_bytes(...)`` blocks.
    """
    c = cfg
    rows = c.n_layers * block_size * c.kv_heads  # head-rows per block
    if kv_dtype is None:
        return 2 * rows * c.head_dim * jnp.dtype(c.dtype).itemsize
    if str(kv_dtype) != "int8":
        raise ValueError(f"unsupported kv_dtype {kv_dtype!r} (int8 or None)")
    return 2 * rows * (c.head_dim + 4)  # int8 row + one f32 scale


def _kv_quant(rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 per-head-row quantization: rows [..., Hkv, d] →
    (int8 [..., Hkv, d], f32 scale [..., Hkv]).  Zero rows (trash-lane
    writes, padding) get scale 0 and dequantize back to exact zeros."""
    r = rows.astype(jnp.float32)
    scale = jnp.max(jnp.abs(r), axis=-1) / 127.0
    q = jnp.round(r / jnp.where(scale > 0, scale, 1.0)[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _kv_dequant(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    """Fused-into-the-read dequant (the ``_wdq`` pattern for KV): the
    gather streams int8 + one scale per head row; XLA fuses the widen
    and multiply into the attention einsum's operand read."""
    return q.astype(dtype) * scale[..., None].astype(dtype)


def _pool_append(
    pool_l: Dict[str, jax.Array],
    name: str,
    rows: jax.Array,
    write_blk: jax.Array,
    write_off: jax.Array,
) -> Dict[str, jax.Array]:
    """Scatter freshly-computed KV rows for one layer into per-layer pool
    leaves at (write_blk, write_off), quantizing on append for the int8
    layout.  ``rows``: [..., Hkv, d] aligned with write_blk/write_off
    [...] — one index pair per row, any leading shape (a decode step's
    [S], a verify step's [S, T])."""
    if name + "_q" in pool_l:
        q, scale = _kv_quant(rows)
        return {
            **pool_l,
            name + "_q": pool_l[name + "_q"].at[write_blk, write_off].set(q),
            name + "_scale": pool_l[name + "_scale"]
            .at[write_blk, write_off]
            .set(scale),
        }
    leaf = pool_l[name]
    return {**pool_l, name: leaf.at[write_blk, write_off].set(rows.astype(leaf.dtype))}


def _pool_gather(
    pool_l: Dict[str, jax.Array], name: str, table: jax.Array, dtype
) -> jax.Array:
    """Gather a layer's KV rows for a block table, dequantizing int8
    leaves fused into the read.  table [..., W] → [..., W, bs, Hkv, d]."""
    if name + "_q" in pool_l:
        return _kv_dequant(
            pool_l[name + "_q"][table], pool_l[name + "_scale"][table], dtype
        )
    return pool_l[name][table]


def copy_block(
    pool: Dict[str, jax.Array], src: jax.Array, dst: jax.Array
) -> Dict[str, jax.Array]:
    """Copy one physical block's KV rows (all layers) — the copy-on-write
    primitive: a shared block a sequence must write into is duplicated
    into a private block first.  ``src``/``dst`` are traced scalars, so
    every COW reuses one compilation.  Generic over the pool layout: an
    int8 pool's quantized rows and scales copy bit-exact, so a COW'd
    block dequantizes identically to the shared original."""
    out = {}
    for name, leaf in pool.items():
        sl = lax.dynamic_slice_in_dim(leaf, src, 1, axis=1)
        idx = (0, dst) + (0,) * (leaf.ndim - 2)
        out[name] = lax.dynamic_update_slice(leaf, sl, idx)
    return out


def export_block(
    pool: Dict[str, jax.Array], src: jax.Array
) -> Dict[str, jax.Array]:
    """Slice one physical block's KV rows (all layers) OUT of the pool —
    the device→host half of the hierarchical-KV spill path.  ``src`` is a
    traced scalar, so every spill reuses one compilation.  Returns
    ``{leaf: [L, block_size, ...]}`` in the pool's own storage dtypes
    (an int8 pool exports int8 rows + f32 scales), so a spilled block's
    payload is the block's bits, never a requantization.

    Jit this WITHOUT donation: the engine donates the pool to every
    subsequent step/chunk/import call, and a non-donating jitted slice
    returns independent buffers — the runtime orders the read before any
    later donated write, so the device→host copy can drain asynchronously
    while serving moves on (materialize with ``np.asarray`` when the
    payload is actually needed)."""
    return {
        name: lax.dynamic_slice_in_dim(leaf, src, 1, axis=1)[:, 0]
        for name, leaf in pool.items()
    }


def import_block(
    pool: Dict[str, jax.Array],
    data: Dict[str, jax.Array],
    dst: jax.Array,
) -> Dict[str, jax.Array]:
    """Write an :func:`export_block` payload back into the pool at block
    ``dst`` — the host→device half of spill/restore.  ``dst`` is a traced
    scalar and ``data`` leaves keep the pool's storage dtypes, so the
    round trip is bit-exact for both pool layouts (values never
    requantize; only the block's address changes).  Jit with the pool
    donated, like every other pool-mutating fn."""
    out = {}
    for name, leaf in pool.items():
        blk = jnp.expand_dims(data[name].astype(leaf.dtype), 1)
        idx = (0, dst) + (0,) * (leaf.ndim - 2)
        out[name] = lax.dynamic_update_slice(leaf, blk, idx)
    return out


def paged_prefill_chunk(
    params: Dict[str, Any],
    pool: Dict[str, jax.Array],
    table: jax.Array,
    tokens: jax.Array,
    start: jax.Array,
    length: jax.Array,
    cfg: TransformerConfig,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Insert one prompt chunk into a paged cache and return the logits of
    its last REAL token.

    tokens: [C] (right-padded to the engine's chunk bucket); ``start`` is
    the chunk's absolute start position, ``length`` the valid count (both
    traced scalars — only C mints a compilation).  ``table`` [W] maps the
    sequence's logical blocks to pool blocks; blocks covering
    [start, start+length) must already be allocated (and private — the
    chunk WRITES its KV rows there).  The chunk attends to everything the
    table already holds (a reused shared prefix, earlier chunks) plus
    itself, causally — which is what makes chunked prefill and
    prefix-reuse recompute the same operation.  Pad positions write their
    garbage rows to trash block 0 and are masked out of attention.

    Numerics mirror the training ``forward`` block exactly (broadcast GQA
    heads, ``_dense_attention``'s masked f32 softmax), so greedy outputs
    stay token-identical to the sequential :func:`generate` path.
    """
    from polyaxon_tpu.models.transformer import _dense_attention

    c = cfg
    C = tokens.shape[0]
    W = table.shape[0]
    bs, Hkv, d = pool_geometry(pool)
    group = c.n_heads // c.kv_heads

    qpos = start + jnp.arange(C)  # [C] absolute positions
    valid = jnp.arange(C) < length
    # Pad writes are redirected to the trash block: their logical blocks
    # may not be allocated yet (they belong to future generation).
    write_blk = jnp.where(valid, table[jnp.clip(qpos // bs, 0, W - 1)], 0)
    write_off = jnp.where(valid, qpos % bs, 0)
    kpos = jnp.arange(W * bs)[None]  # gathered keys sit in logical order

    x = params["embed"].astype(c.dtype)[tokens][None]  # [1, C, D]
    positions = qpos[None]  # [1, C]

    def layer_body(x, inputs):
        layer, pool_l = inputs  # pool_l leaves: [NB, bs, Hkv, ...]
        h = _rmsnorm(x, layer["attn_norm"])
        q = jnp.einsum("btd,dhk->bthk", h, layer["wq"].astype(h.dtype))
        k = jnp.einsum("btd,dhk->bthk", h, layer["wk"].astype(h.dtype))
        v = jnp.einsum("btd,dhk->bthk", h, layer["wv"].astype(h.dtype))
        q = _rope(q, positions, c.rope_theta)
        k = _rope(k, positions, c.rope_theta)
        # Write the chunk's KV rows, then attend against the whole table —
        # the rows just written ARE the chunk's causal self-attention keys.
        pool_l = _pool_append(pool_l, "k", k[0], write_blk, write_off)
        pool_l = _pool_append(pool_l, "v", v[0], write_blk, write_off)
        ck = _pool_gather(pool_l, "k", table, h.dtype).reshape(1, W * bs, Hkv, d)
        cv = _pool_gather(pool_l, "v", table, h.dtype).reshape(1, W * bs, Hkv, d)
        if group > 1:
            ck = jnp.repeat(ck, group, axis=2)
            cv = jnp.repeat(cv, group, axis=2)
        attn = _dense_attention(q, ck, cv, positions, kpos)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["wo"].astype(h.dtype))

        h = _rmsnorm(x, layer["mlp_norm"])
        up = jnp.einsum("btd,df->btf", h, layer["wi"].astype(h.dtype))
        gate = jnp.einsum("btd,df->btf", h, layer["wg"].astype(h.dtype))
        y = jax.nn.silu(gate) * up
        x = x + jnp.einsum("btf,fd->btd", y, layer["wd"].astype(h.dtype))
        return x, pool_l

    x, new_pool = lax.scan(layer_body, x, (params["block"], pool))
    x = _rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, params["unembed"].astype(x.dtype))
    last = jnp.take(logits[0], length - 1, axis=0)
    return last.astype(jnp.float32), new_pool


def _attend_paged(q, ck, cv, pos, group):
    """One-token attention over block-table-gathered KV.

    q: [S, 1, H, d]; ck/cv: [S, W*bs, Hkv, d] in logical-position order;
    pos: [S] per-slot absolute positions.  Identical contraction shape to
    :func:`_attend_slots` — the gather changed where keys LIVE, not how a
    row attends — which is what keeps paged greedy outputs token-identical
    to the slot (and sequential) paths.
    """
    S, K, Hkv, d = ck.shape
    scale = d**-0.5
    qg = q.reshape(S, 1, Hkv, group, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, ck) * scale  # [S,Hkv,g,1,K]
    valid = (jnp.arange(K)[None, :] <= pos[:, None])[:, None, None, None, :]
    s = jnp.where(valid, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv)
    return out.reshape(S, 1, Hkv * group, d)


def paged_decode_step(
    params: Dict[str, Any],
    pool: Dict[str, jax.Array],
    tables: jax.Array,
    tokens: jax.Array,
    pos: jax.Array,
    active: jax.Array,
    cfg: TransformerConfig,
    qweights: Optional[Dict[str, Any]] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Advance a mixed batch one token against the paged pool.

    tables: [S, W] physical block ids per slot (the engine maps unset
    entries to trash block 0); tokens/pos/active as in
    :func:`slot_decode_step`.  Inactive lanes write their garbage row to
    block 0 offset 0 — never into a live block — and every gathered
    position beyond a slot's ``pos`` is masked.  Shapes depend only on
    (slots, pool size, table width): steady-state serving never
    recompiles, whichever requests come and go or how their blocks are
    scattered across the pool.
    """
    c = cfg
    S, W = tables.shape
    bs, Hkv, d = pool_geometry(pool)
    pos = jnp.where(active, pos, 0)
    write_blk = jnp.where(active, tables[jnp.arange(S), pos // bs], 0)
    write_off = jnp.where(active, pos % bs, 0)

    x = params["embed"].astype(c.dtype)[tokens][:, None, :]  # [S,1,D]

    blk = params["block"]
    if qweights is None:
        layers = blk
        unembed = params["unembed"]
    else:
        layers = {
            "attn_norm": blk["attn_norm"],
            "mlp_norm": blk["mlp_norm"],
            **{k: qweights[k] for k in QUANTIZED_BLOCK_WEIGHTS},
        }
        unembed = qweights["unembed"]

    def layer_body(carry, inputs):
        x = carry
        layer, pool_l = inputs  # pool_l leaves: [NB, bs, Hkv, ...]
        h = _rmsnorm(x, layer["attn_norm"])
        q = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wq"], h.dtype))
        k = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wk"], h.dtype))
        v = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wv"], h.dtype))
        positions = pos[:, None]  # [S, 1]
        q = _rope(q, positions, c.rope_theta)
        k = _rope(k, positions, c.rope_theta)
        pool_l = _pool_append(pool_l, "k", k[:, 0], write_blk, write_off)
        pool_l = _pool_append(pool_l, "v", v[:, 0], write_blk, write_off)
        ck = _pool_gather(pool_l, "k", tables, h.dtype).reshape(S, W * bs, Hkv, d)
        cv = _pool_gather(pool_l, "v", tables, h.dtype).reshape(S, W * bs, Hkv, d)
        attn = _attend_paged(q, ck, cv, pos, c.n_heads // c.kv_heads)
        x = x + jnp.einsum("bthk,hkd->btd", attn, _wdq(layer["wo"], h.dtype))

        h = _rmsnorm(x, layer["mlp_norm"])
        up = jnp.einsum("btd,df->btf", h, _wdq(layer["wi"], h.dtype))
        gate = jnp.einsum("btd,df->btf", h, _wdq(layer["wg"], h.dtype))
        y = jax.nn.silu(gate) * up
        x = x + jnp.einsum("btf,fd->btd", y, _wdq(layer["wd"], h.dtype))
        return x, pool_l

    x, new_pool = lax.scan(layer_body, x, (layers, pool))
    x = _rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, _wdq(unembed, x.dtype))
    return logits[:, 0].astype(jnp.float32), new_pool


def _attend_spec(q, ck, cv, qpos, group):
    """Multi-query-row attention over block-table-gathered KV.

    The T-row generalization of :func:`_attend_paged` for speculative
    verification: q [S, T, H, d] carries one query row per drafted token,
    ck/cv [S, W*bs, Hkv, d] sit in logical-position order, and qpos
    [S, T] gives each row's absolute position.  Per output element the
    contraction and the masked f32 softmax are identical to the T=1
    step's, which is what keeps a verify row's logits bit-identical to
    the single-token decode step that would have produced them — the
    foundation of the greedy parity guarantee.
    """
    S, K, Hkv, d = ck.shape
    T = q.shape[1]
    scale = d**-0.5
    qg = q.reshape(S, T, Hkv, group, d)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, ck) * scale  # [S,Hkv,g,T,K]
    valid = jnp.arange(K)[None, None, :] <= qpos[:, :, None]  # [S,T,K]
    s = jnp.where(valid[:, None, None, :, :], s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p, cv)
    return out.reshape(S, T, Hkv * group, d)


def paged_verify_step(
    params: Dict[str, Any],
    pool: Dict[str, jax.Array],
    tables: jax.Array,
    tokens: jax.Array,
    pos: jax.Array,
    n_tok: jax.Array,
    active: jax.Array,
    cfg: TransformerConfig,
    qweights: Optional[Dict[str, Any]] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Score a batch of drafted token runs in ONE forward pass.

    The speculative-decoding verify kernel: ``tokens`` [S, T] holds, per
    lane, the next token to feed followed by up to T-1 drafted
    continuations (right-padded); ``pos`` [S] is the absolute position
    of ``tokens[:, 0]`` and ``n_tok`` [S] the valid token count (1 for a
    lane taking a plain single-token step, up to T for a fully drafted
    lane — both are DATA, so one compilation serves every draft-length
    mix).  Rows beyond ``n_tok`` (and every row of inactive lanes) write
    their garbage KV to trash block 0; valid rows land at their real
    (block, offset) exactly like :func:`paged_prefill_chunk`, and each
    row attends causally to the whole table plus the rows written before
    it in this same call.

    Returns ``(logits [S, T, vocab] f32, new_pool)``.  ``logits[s, j]``
    is the model's next-token distribution AFTER feeding
    ``tokens[s, :j+1]``, so the caller accepts draft ``tokens[s, j+1]``
    iff it equals ``argmax(logits[s, j])`` — the accept mask — and row
    ``n_accept`` yields the bonus/correction token.  Rejected rows leave
    stale KV beyond the lane's rolled-back position: masked out of every
    later attention (position mask) and overwritten in place as decoding
    proceeds; whole tail blocks are freed host-side
    (:func:`~polyaxon_tpu.serving.paging.truncate_table`).

    Numerics mirror :func:`paged_decode_step` exactly — same ``_wdq``
    weight streaming (int8 qweights compose), same ``_pool_append`` /
    ``_pool_gather`` (int8 KV pools compose), same masked f32 softmax —
    so greedy outputs stay token-identical to the non-speculative path.
    """
    c = cfg
    S, W = tables.shape
    T = tokens.shape[1]
    bs, Hkv, d = pool_geometry(pool)
    pos = jnp.where(active, pos, 0)
    qpos = pos[:, None] + jnp.arange(T)[None, :]  # [S, T] absolute
    row_ok = active[:, None] & (jnp.arange(T)[None, :] < n_tok[:, None])
    write_blk = jnp.where(
        row_ok,
        tables[jnp.arange(S)[:, None], jnp.clip(qpos // bs, 0, W - 1)],
        0,
    )
    write_off = jnp.where(row_ok, qpos % bs, 0)

    x = params["embed"].astype(c.dtype)[tokens]  # [S, T, D]

    blk = params["block"]
    if qweights is None:
        layers = blk
        unembed = params["unembed"]
    else:
        layers = {
            "attn_norm": blk["attn_norm"],
            "mlp_norm": blk["mlp_norm"],
            **{k: qweights[k] for k in QUANTIZED_BLOCK_WEIGHTS},
        }
        unembed = qweights["unembed"]

    def layer_body(carry, inputs):
        x = carry
        layer, pool_l = inputs  # pool_l leaves: [NB, bs, Hkv, ...]
        h = _rmsnorm(x, layer["attn_norm"])
        q = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wq"], h.dtype))
        k = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wk"], h.dtype))
        v = jnp.einsum("btd,dhk->bthk", h, _wdq(layer["wv"], h.dtype))
        q = _rope(q, qpos, c.rope_theta)
        k = _rope(k, qpos, c.rope_theta)
        # Write every row, then gather: rows written earlier in the run
        # ARE later rows' causal keys, exactly like a prefill chunk.
        pool_l = _pool_append(pool_l, "k", k, write_blk, write_off)
        pool_l = _pool_append(pool_l, "v", v, write_blk, write_off)
        ck = _pool_gather(pool_l, "k", tables, h.dtype).reshape(S, W * bs, Hkv, d)
        cv = _pool_gather(pool_l, "v", tables, h.dtype).reshape(S, W * bs, Hkv, d)
        attn = _attend_spec(q, ck, cv, qpos, c.n_heads // c.kv_heads)
        x = x + jnp.einsum("bthk,hkd->btd", attn, _wdq(layer["wo"], h.dtype))

        h = _rmsnorm(x, layer["mlp_norm"])
        up = jnp.einsum("btd,df->btf", h, _wdq(layer["wi"], h.dtype))
        gate = jnp.einsum("btd,df->btf", h, _wdq(layer["wg"], h.dtype))
        y = jax.nn.silu(gate) * up
        x = x + jnp.einsum("btf,fd->btd", y, _wdq(layer["wd"], h.dtype))
        return x, pool_l

    x, new_pool = lax.scan(layer_body, x, (layers, pool))
    x = _rmsnorm(x, params["final_norm"])
    logits = jnp.einsum("btd,dv->btv", x, _wdq(unembed, x.dtype))
    return logits.astype(jnp.float32), new_pool


def _fit_spec(spec, leaf, mesh_shape):
    """Drop sharding on axes whose mesh size doesn't divide the leaf's
    actual dimension (shape-aware replication fallback)."""
    import math

    from jax.sharding import PartitionSpec

    names = tuple(spec) + (None,) * (leaf.ndim - len(tuple(spec)))
    out = []
    for dim, name in zip(leaf.shape, names):
        if name is None:
            out.append(None)
            continue
        axes = name if isinstance(name, (tuple, list)) else (name,)
        total = math.prod(mesh_shape[a] for a in axes)
        out.append(name if total and dim % total == 0 else None)
    return PartitionSpec(*out)


def quantized_weight_shardings(cfg: TransformerConfig, mesh, template, qweights):
    """NamedShardings for a :func:`quantize_weights` tree: each ``(q,
    scale)`` pair inherits its source weight's logical axes — the int8
    tensor shards exactly like the full-precision weight it replaced,
    and the keepdims-1 scale dims fall back to replication via the
    shape-aware fit.  This is what lets int8 and tensor-parallel serving
    COMPOSE: every chip streams only its head-shard's int8 bytes."""
    from polyaxon_tpu.models.transformer import param_axes
    from polyaxon_tpu.parallel.axes import tree_shardings, tree_specs

    mesh_shape = dict(mesh.shape)
    axes = param_axes(cfg)
    name_axes = {k: axes["block"][k] for k in QUANTIZED_BLOCK_WEIGHTS}
    name_axes["unembed"] = axes["unembed"]
    base_specs = tree_specs(name_axes, template.rules, mesh_shape)
    fitted = {
        name: tuple(
            _fit_spec(base_specs[name], leaf, mesh_shape)
            for leaf in qweights[name]
        )
        for name in qweights
    }
    return tree_shardings(mesh, fitted)


def decode_param_shardings(
    cfg: TransformerConfig, mesh, template, params: Optional[Any] = None
):
    """NamedShardings for the weights under a template's rules (what the
    serving path places restored checkpoints with).

    When ``params`` (or any same-shaped tree) is given, axes whose mesh
    size doesn't divide the actual dimension fall back to replication —
    e.g. a GQA model with ``n_kv_heads: 1`` under ``tp=2`` keeps its KV
    projections replicated while the query-side weights still shard.
    Serving must degrade to replication, not crash, for any model the
    spec accepts."""
    from jax.sharding import PartitionSpec

    from polyaxon_tpu.models.transformer import param_axes
    from polyaxon_tpu.parallel.axes import tree_shardings, tree_specs

    mesh_shape = dict(mesh.shape)
    specs = tree_specs(param_axes(cfg), template.rules, mesh_shape)
    if params is not None:
        specs = jax.tree.map(
            lambda spec, leaf: _fit_spec(spec, leaf, mesh_shape),
            specs,
            params,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )
    return tree_shardings(mesh, specs)


def sharded_generate_fn(
    cfg: TransformerConfig,
    mesh,
    template,
    *,
    max_new_tokens: int,
    greedy: bool = True,
    params: Optional[Any] = None,
    param_shardings: Optional[Any] = None,
    qweights_shardings: Optional[Any] = None,
):
    """(jitted fn, param_shardings) for MULTI-CHIP decode under a template.

    TP-native serving: the template's rules shard every weight (heads on
    the tensor axis under ``tp``), and GSPMD propagates those shardings
    through the decode scan — the KV cache lands heads-sharded, each
    chip attending over its own head group, with one collective per
    token for the logit reduction.  The caller places restored params
    with the returned shardings and invokes ``fn(params, prompt, key,
    temperature, qweights)``; prompt/key/temperature replicate (decode
    batches are small — sharding model weights, not the batch, is what
    scales).  ``qweights_shardings`` (from
    :func:`quantized_weight_shardings`) composes int8 with the sharding:
    pass the placed quantized tree as the 5th argument, or None.
    Sharded-vs-single-device token parity is asserted in
    ``tests/test_parallel/test_decode_sharded.py``.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    # Callers that already placed their weights pass the shardings in —
    # recomputing the fitted tree per compiled shape would be waste.
    param_sh = (
        param_shardings
        if param_shardings is not None
        else decode_param_shardings(cfg, mesh, template, params=params)
    )
    repl = NamedSharding(mesh, PartitionSpec())

    def _run(p, prompt, key, temp, qw):
        return generate(
            p,
            prompt,
            cfg,
            max_new_tokens=max_new_tokens,
            temperature=0.0 if greedy else temp,
            rng=key,
            qweights=qw,
        )

    fn = jax.jit(
        _run,
        in_shardings=(param_sh, repl, repl, repl, qweights_shardings),
    )
    return fn, param_sh


def generate(
    params: Dict[str, Any],
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int,
    temperature: Any = 0.0,
    rng: Optional[jax.Array] = None,
    qweights: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """prompt [B, T] → generated tokens [B, max_new_tokens].

    Greedy when ``temperature == 0``; otherwise temperature sampling.
    ``temperature`` may be a traced array (a jitted caller can pass it as
    an argument rather than baking each value into a fresh compilation);
    a traced value always takes the sampling branch — greedy-vs-sampling
    is the only Python-level fork.  ``qweights`` (precompute once with
    :func:`quantize_weights`) switches the per-token loop to int8 weight
    streaming (+51% measured); prefill stays full-precision — it is
    MXU-bound, not bandwidth-bound.  The whole decode loop is one
    ``lax.scan`` of compiled one-token steps — no host round-trips
    between tokens.
    """
    if cfg.n_experts:
        raise NotImplementedError("MoE decoding is not supported yet")
    B, T = prompt.shape
    max_len = T + max_new_tokens
    if max_len > cfg.max_seq:
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq ({cfg.max_seq})"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    cache = init_cache(cfg, B, max_len)
    logits, cache = prefill(params, prompt, cache, cfg)

    # Concrete zeros of ANY scalar flavor (python float, np.float32,
    # jnp scalar) select the greedy branch — only a TRACED temperature is
    # forced down the sampling path (a tracer has no concrete value to
    # fork on, and dividing by a concrete 0.0 would NaN the logits).
    greedy = (
        not isinstance(temperature, jax.core.Tracer)
        and float(temperature) <= 0.0
    )

    def pick(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(key, logits / temperature, axis=-1)

    def step(carry, i):
        logits, cache, key = carry
        key, sub = jax.random.split(key)
        token = pick(logits, sub)
        logits, cache = decode_step(
            params, cache, token, T + i, cfg, qweights=qweights
        )
        return (logits, cache, key), token

    # N-1 scanned steps; the final token needs only a pick, not another
    # full decode_step whose logits nobody reads.
    (logits, _, key), tokens = lax.scan(
        step, (logits, cache, rng), jnp.arange(max_new_tokens - 1)
    )
    last = pick(logits, jax.random.split(key)[1])
    return jnp.concatenate([tokens.T, last[:, None]], axis=1)
