from polyaxon_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    param_axes,
)
from polyaxon_tpu.models import cnn, decode, vit

__all__ = [
    "TransformerConfig",
    "forward",
    "init_params",
    "loss_fn",
    "param_axes",
    "cnn",
    "decode",
    "vit",
]
