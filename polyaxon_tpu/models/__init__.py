from polyaxon_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    param_axes,
)

__all__ = [
    "TransformerConfig",
    "forward",
    "init_params",
    "loss_fn",
    "param_axes",
]
