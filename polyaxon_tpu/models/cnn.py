"""Image-classifier CNN: the quick-start CIFAR/MNIST workload family.

Parity: the reference's north-star workloads are its quick-start tutorials
(TF MNIST / CIFAR-10 distributed — ``docs/guides/training-cifar10.md``,
BASELINE.md configs); the platform itself ships no models.  Here the
workload is first-class: a pure-JAX conv net (NHWC, bf16 matmul-heavy
conv + dense head) sharing the logical-axis vocabulary so the same
dp/fsdp templates apply.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class CNNConfig:
    image_size: int = 32
    in_channels: int = 3
    channels: Tuple[int, ...] = (64, 128, 256)
    n_classes: int = 10
    dense_dim: int = 256
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def n_params(self) -> int:
        total = 0
        cin = self.in_channels
        for cout in self.channels:
            total += 3 * 3 * cin * cout + cout
            cin = cout
        spatial = self.image_size // (2 ** len(self.channels))
        flat = spatial * spatial * self.channels[-1]
        total += flat * self.dense_dim + self.dense_dim
        total += self.dense_dim * self.n_classes + self.n_classes
        return total


def param_axes(cfg: CNNConfig) -> Dict[str, Any]:
    """Logical axes: conv output channels / dense hidden map to ``embed``
    so the fsdp template shards them; everything else replicates."""
    axes: Dict[str, Any] = {}
    for i in range(len(cfg.channels)):
        axes[f"conv{i}"] = {"w": (None, None, None, "embed"), "b": ("embed",)}
    axes["dense"] = {"w": (None, "embed"), "b": ("embed",)}
    axes["head"] = {"w": ("embed", "vocab"), "b": ("vocab",)}
    return axes


def init_params(key: jax.Array, cfg: CNNConfig) -> Dict[str, Any]:
    params: Dict[str, Any] = {}
    keys = jax.random.split(key, len(cfg.channels) + 2)
    cin = cfg.in_channels
    for i, cout in enumerate(cfg.channels):
        fan_in = 3 * 3 * cin
        params[f"conv{i}"] = {
            "w": jax.random.normal(keys[i], (3, 3, cin, cout), cfg.param_dtype)
            * (2.0 / fan_in) ** 0.5,
            "b": jnp.zeros((cout,), cfg.param_dtype),
        }
        cin = cout
    spatial = cfg.image_size // (2 ** len(cfg.channels))
    flat = spatial * spatial * cfg.channels[-1]
    params["dense"] = {
        "w": jax.random.normal(keys[-2], (flat, cfg.dense_dim), cfg.param_dtype)
        * (2.0 / flat) ** 0.5,
        "b": jnp.zeros((cfg.dense_dim,), cfg.param_dtype),
    }
    params["head"] = {
        "w": jax.random.normal(keys[-1], (cfg.dense_dim, cfg.n_classes), cfg.param_dtype)
        * cfg.dense_dim**-0.5,
        "b": jnp.zeros((cfg.n_classes,), cfg.param_dtype),
    }
    return params


def forward(params: Dict[str, Any], images: jax.Array, cfg: CNNConfig) -> jax.Array:
    """images [B,H,W,C] → logits [B,n_classes] (float32)."""
    x = images.astype(cfg.dtype)
    for i in range(len(cfg.channels)):
        layer = params[f"conv{i}"]
        x = lax.conv_general_dilated(
            x,
            layer["w"].astype(cfg.dtype),
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + layer["b"].astype(cfg.dtype)
        x = jax.nn.relu(x)
        x = lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(
        x @ params["dense"]["w"].astype(cfg.dtype) + params["dense"]["b"].astype(cfg.dtype)
    )
    logits = x @ params["head"]["w"].astype(cfg.dtype) + params["head"]["b"].astype(cfg.dtype)
    return logits.astype(jnp.float32)


def loss_fn(
    params: Dict[str, Any], batch: Dict[str, jax.Array], cfg: CNNConfig
) -> jax.Array:
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def accuracy(
    params: Dict[str, Any], batch: Dict[str, jax.Array], cfg: CNNConfig
) -> jax.Array:
    logits = forward(params, batch["images"], cfg)
    return jnp.mean((jnp.argmax(logits, -1) == batch["labels"]).astype(jnp.float32))
