"""Vision Transformer: the image-classification flagship family.

Parity framing: the reference ships no models (workloads live in user
containers — SURVEY §2.8); the TPU framework makes them first-class so
sharding templates apply to vision exactly as to language.  This ViT
reuses the transformer's design vocabulary end to end:

- **patchify as one einsum** — [B,H,W,C] → [B,N,D] is a single MXU-shaped
  contraction over (patch_h, patch_w, C), not a conv;
- **bidirectional attention** (no mask, no rope — learned position
  embeddings), einsum-only;
- **stacked layer params + ``lax.scan``** — the same compile-once block
  body, leading ``layers`` axis ready for pp sharding;
- **the shared logical-axis names** (``embed``/``heads``/``mlp``/
  ``vocab``…) — every parallelism template (ddp/fsdp/tp/…) applies with
  zero model changes;
- mean-pool head (no CLS token: pooling is free and shards trivially).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax

from polyaxon_tpu.models.transformer import _rmsnorm
from polyaxon_tpu.parallel.axes import AxisRules, with_logical_constraint


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    in_channels: int = 3
    d_model: int = 192
    n_layers: int = 6
    n_heads: int = 6
    head_dim: int = 32
    d_ff: int = 768
    n_classes: int = 10
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    def scaled(self, **overrides) -> "ViTConfig":
        return replace(self, **overrides)

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.in_channels

    @property
    def n_params(self) -> int:
        c = self
        attn = c.d_model * c.n_heads * c.head_dim * 4
        mlp = c.d_model * c.d_ff * 3
        per_layer = attn + mlp + 2 * c.d_model
        return (
            c.patch_dim * c.d_model  # patch embed
            + c.num_patches * c.d_model  # positions
            + c.n_layers * per_layer
            + c.d_model  # final norm
            + c.d_model * c.n_classes  # head
        )


def param_axes(cfg: ViTConfig) -> Dict[str, Any]:
    """Logical axes mirror the LM's (``transformer.param_axes``) so the
    same templates shard both families."""
    block = {
        "attn_norm": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "heads", "head_dim"),
        "wv": ("layers", "embed", "heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "embed"),
        "wi": ("layers", "embed", "mlp"),
        "wg": ("layers", "embed", "mlp"),
        "wd": ("layers", "mlp", "embed"),
    }
    return {
        "patch_embed": (None, "embed"),
        "pos_embed": (None, "embed"),
        "final_norm": ("embed",),
        "head": ("embed", "vocab"),
        "block": block,
    }


def init_params(key: jax.Array, cfg: ViTConfig) -> Dict[str, Any]:
    c = cfg
    k = iter(jax.random.split(key, 16))
    dt = c.param_dtype

    def norm(*shape, scale):
        return jax.random.normal(next(k), shape, dt) * scale

    L, D, H, hd, F = c.n_layers, c.d_model, c.n_heads, c.head_dim, c.d_ff
    block = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": norm(L, D, H, hd, scale=D**-0.5),
        "wk": norm(L, D, H, hd, scale=D**-0.5),
        "wv": norm(L, D, H, hd, scale=D**-0.5),
        "wo": norm(L, H, hd, D, scale=(H * hd) ** -0.5),
        "mlp_norm": jnp.ones((L, D), dt),
        "wi": norm(L, D, F, scale=D**-0.5),
        "wg": norm(L, D, F, scale=D**-0.5),
        "wd": norm(L, F, D, scale=F**-0.5),
    }
    return {
        "patch_embed": norm(c.patch_dim, D, scale=c.patch_dim**-0.5),
        "pos_embed": norm(c.num_patches, D, scale=0.02),
        "final_norm": jnp.ones((D,), dt),
        "head": norm(D, c.n_classes, scale=D**-0.5),
        "block": block,
    }


def _patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B,H,W,C] uint8/float → [B, num_patches, patch_dim] model dtype."""
    B = images.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = images.astype(jnp.float32) / 255.0 - 0.5
    x = x.reshape(B, g, p, g, p, cfg.in_channels)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, g * g, cfg.patch_dim)
    return x.astype(cfg.dtype)


def forward(
    params: Dict[str, Any],
    images: jax.Array,
    cfg: ViTConfig,
    template=None,
    mesh=None,
) -> jax.Array:
    """images [B,H,W,C] → logits [B, n_classes] (float32)."""
    c = cfg
    rules: AxisRules = template.rules if template is not None else {}

    x = jnp.einsum(
        "bnp,pd->bnd", _patchify(images, c), params["patch_embed"].astype(c.dtype)
    )
    x = x + params["pos_embed"].astype(c.dtype)[None]
    x = with_logical_constraint(x, ("batch", "seq", None), rules, mesh)

    def block(x, layer):
        h = _rmsnorm(x, layer["attn_norm"])
        q = jnp.einsum("bnd,dhk->bnhk", h, layer["wq"].astype(h.dtype))
        k = jnp.einsum("bnd,dhk->bnhk", h, layer["wk"].astype(h.dtype))
        v = jnp.einsum("bnd,dhk->bnhk", h, layer["wv"].astype(h.dtype))
        q = with_logical_constraint(q, ("batch", None, "attn_heads", None), rules, mesh)
        k = with_logical_constraint(k, ("batch", None, "attn_heads", None), rules, mesh)
        v = with_logical_constraint(v, ("batch", None, "attn_heads", None), rules, mesh)
        scale = c.head_dim**-0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        attn = with_logical_constraint(
            attn, ("batch", "seq", "attn_heads", None), rules, mesh
        )
        x = x + jnp.einsum("bnhk,hkd->bnd", attn, layer["wo"].astype(h.dtype))

        h = _rmsnorm(x, layer["mlp_norm"])
        up = jnp.einsum("bnd,df->bnf", h, layer["wi"].astype(h.dtype))
        gate = jnp.einsum("bnd,df->bnf", h, layer["wg"].astype(h.dtype))
        y = jax.nn.silu(gate) * up
        y = with_logical_constraint(y, ("batch", "seq", "act_mlp"), rules, mesh)
        x = x + jnp.einsum("bnf,fd->bnd", y, layer["wd"].astype(h.dtype))
        x = with_logical_constraint(x, ("batch", "seq", None), rules, mesh)
        return x, None

    body = jax.checkpoint(block) if c.remat else block
    x, _ = lax.scan(lambda carry, layer: body(carry, layer), x, params["block"])

    x = _rmsnorm(x, params["final_norm"])
    pooled = jnp.mean(x, axis=1)  # [B, D]
    logits = jnp.einsum("bd,dk->bk", pooled, params["head"].astype(x.dtype))
    return logits.astype(jnp.float32)


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: ViTConfig,
    template=None,
    mesh=None,
) -> jax.Array:
    logits = forward(params, batch["images"], cfg, template=template, mesh=mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def accuracy(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    cfg: ViTConfig,
    template=None,
    mesh=None,
) -> jax.Array:
    logits = forward(params, batch["images"], cfg, template=template, mesh=mesh)
    return jnp.mean((jnp.argmax(logits, axis=-1) == batch["labels"]).astype(jnp.float32))
