"""The ``polyaxon-tpu`` CLI.

Parity: the reference's external ``polyaxon-cli`` (run/init/logs/stop over
REST+WS, SURVEY §1 layer 1).  Two modes:

- **local** (default): embed the orchestrator over ``--base-dir`` and drive
  it in-process — no server needed, the dev/test workflow.
- **remote** (``--host``): talk to a running ``polyaxon-tpu serve`` API.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from pathlib import Path
from typing import Any, Dict, Optional

from polyaxon_tpu.conf.knobs import knob_str

DEFAULT_BASE = knob_str("POLYAXON_TPU_HOME")
AUTH_FILE = Path(DEFAULT_BASE).expanduser() / "auth.json"


def _stored_auth() -> dict:
    try:
        return json.loads(AUTH_FILE.read_text())
    except (OSError, ValueError):
        return {}


#: A down/hung control plane must error the CLI, not freeze the terminal.
#: Generous enough for slow artifact streams; connect failures surface in
#: seconds regardless.
_REQUEST_TIMEOUT_S = 60.0


class RemoteClient:
    """Thin urllib client for the REST API (no extra deps in the CLI path)."""

    def __init__(self, host: str, token: Optional[str] = None) -> None:
        self.base = host.rstrip("/")
        if not self.base.startswith("http"):
            self.base = f"http://{self.base}"
        # Priority: explicit flag > env > `polyaxon-tpu login` stored auth.
        stored = _stored_auth()
        self.token = (
            token
            or knob_str("POLYAXON_TPU_AUTH_TOKEN")
            or (stored.get("token") if stored.get("host") in (host, self.base) else None)
        )

    def _request(self, method: str, path: str, body: Optional[dict] = None) -> Any:
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            f"{self.base}{path}",
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers=headers,
        )
        with urllib.request.urlopen(req, timeout=_REQUEST_TIMEOUT_S) as resp:
            return json.loads(resp.read() or "{}")

    def submit(self, spec, project, name, tags):
        return self._request(
            "POST",
            "/api/v1/runs",
            {"spec": spec, "project": project, "name": name, "tags": tags},
        )

    def list(self, **query):
        from urllib.parse import urlencode

        qs = urlencode({k: v for k, v in query.items() if v is not None})
        return self._request("GET", f"/api/v1/runs?{qs}")["results"]

    def get(self, run_id):
        return self._request("GET", f"/api/v1/runs/{run_id}")

    def stop(self, run_id):
        return self._request("POST", f"/api/v1/runs/{run_id}/stop")

    def clone(self, run_id, strategy):
        return self._request("POST", f"/api/v1/runs/{run_id}/{strategy}")

    def archive(self, run_id):
        return self._request("POST", f"/api/v1/runs/{run_id}/archive")

    def restore(self, run_id):
        return self._request("POST", f"/api/v1/runs/{run_id}/restore")

    def delete(self, run_id):
        return self._request("DELETE", f"/api/v1/runs/{run_id}")

    def list_archives(self):
        return self._request("GET", "/api/v1/archives")["results"]

    def logs(self, run_id, since_id=0):
        return self._request(
            "GET", f"/api/v1/runs/{run_id}/logs?since_id={since_id}"
        )["results"]

    def statuses(self, run_id):
        return self._request("GET", f"/api/v1/runs/{run_id}/statuses")["results"]

    def list_devices(self):
        return self._request("GET", "/api/v1/devices")["results"]

    def register_device(self, name, accelerator, chips, num_hosts):
        return self._request(
            "POST",
            "/api/v1/devices",
            {
                "name": name,
                "accelerator": accelerator,
                "chips": chips,
                "num_hosts": num_hosts,
            },
        )

    def remove_device(self, name):
        return self._request("DELETE", f"/api/v1/devices/{name}")

    def list_artifacts(self, run_id):
        return self._request("GET", f"/api/v1/runs/{run_id}/artifacts")["results"]

    def create_user(self, username, role):
        return self._request(
            "POST", "/api/v1/users", {"username": username, "role": role}
        )

    def list_options(self):
        return self._request("GET", "/api/v1/options")["results"]

    def set_option(self, key, value):
        return self._request("PUT", f"/api/v1/options/{key}", {"value": value})

    def list_users(self):
        return self._request("GET", "/api/v1/users")["results"]

    def remove_user(self, username):
        return self._request("DELETE", f"/api/v1/users/{username}")

    def create_search(self, name, query):
        return self._request("POST", "/api/v1/searches", {"name": name, "query": query})

    def list_searches(self):
        return self._request("GET", "/api/v1/searches")["results"]

    def delete_search(self, name):
        return self._request("DELETE", f"/api/v1/searches/{name}")

    def execute_search(self, name):
        return self._request("GET", f"/api/v1/searches/{name}/runs")["results"]

    def create_project(self, name, description, owner=None):
        body = {"name": name, "description": description}
        if owner is not None:
            body["owner"] = owner
        return self._request("POST", "/api/v1/projects", body)

    def list_projects(self):
        return self._request("GET", "/api/v1/projects")["results"]

    def delete_project(self, name):
        return self._request("DELETE", f"/api/v1/projects/{name}")

    def set_ci(self, project, spec):
        return self._request("PUT", f"/api/v1/projects/{project}/ci", {"spec": spec})

    def get_ci(self, project):
        return self._request("GET", f"/api/v1/projects/{project}/ci")

    def delete_ci(self, project):
        return self._request("DELETE", f"/api/v1/projects/{project}/ci")

    def trigger_ci(self, project, context=None):
        body = {"context": context} if context else {}
        return self._request(
            "POST", f"/api/v1/projects/{project}/ci/trigger", body
        )

    def share_project(self, name, username):
        return self._request(
            "POST", f"/api/v1/projects/{name}/collaborators", {"username": username}
        )

    def unshare_project(self, name, username):
        return self._request(
            "DELETE", f"/api/v1/projects/{name}/collaborators/{username}"
        )

    def add_bookmark(self, run_id):
        return self._request("POST", f"/api/v1/runs/{run_id}/bookmark")

    def remove_bookmark(self, run_id):
        return self._request("DELETE", f"/api/v1/runs/{run_id}/bookmark")

    def list_bookmarks(self):
        return self._request("GET", "/api/v1/bookmarks")["results"]

    def open_artifact(self, run_id, key):
        """A readable stream over the artifact (caller closes)."""
        from urllib.parse import quote

        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            f"{self.base}/api/v1/runs/{run_id}/artifacts/{quote(key)}",
            headers=headers,
        )
        return urllib.request.urlopen(req, timeout=_REQUEST_TIMEOUT_S)


class LocalClient:
    """Embedded-orchestrator backend (creates it lazily, pumps eagerly)."""

    def __init__(self, base_dir: str, recover: bool = False) -> None:
        from polyaxon_tpu.api.app import run_to_dict
        from polyaxon_tpu.orchestrator import Orchestrator

        self._to_dict = run_to_dict
        self.orch = Orchestrator(Path(base_dir).expanduser())
        # Each CLI invocation is a fresh control plane over the same durable
        # registry. Commands that intentionally drive work (run/stop/clones,
        # logs --follow) re-enqueue dispatch tasks the previous process took
        # with it; pure reads (ps/get/statuses/...) must NOT — recovery has
        # write side effects (re-dispatch, process-row cleanup) that would
        # turn `ps` into an unmonitored gang launcher.
        if recover:
            self.orch.recover()

    def submit(self, spec, project, name, tags):
        run = self.orch.submit(spec, project=project, name=name, tags=tags)
        return self._to_dict(run)

    def list(self, **query):
        from polyaxon_tpu.query import apply_query, filters_archived, parse_query

        conds = parse_query(query.get("q"))
        runs = self.orch.registry.list_runs(
            project=query.get("project"),
            kind=query.get("kind"),
            # A query on `archived:` owns that dimension (else its clause
            # would contradict the live-only default and match nothing).
            archived=None if filters_archived(conds) else False,
        )
        if conds:
            runs = apply_query(runs, conditions=conds)
        return [self._to_dict(r) for r in runs[: int(query.get("limit") or 100)]]

    def get(self, run_id):
        self.orch.pump()
        return self._to_dict(self.orch.get_run(int(run_id)))

    def stop(self, run_id):
        self.orch.stop_run(int(run_id))
        self.orch.pump(max_wait=1.0)
        return {"ok": True}

    def clone(self, run_id, strategy):
        return self._to_dict(self.orch.clone_run(int(run_id), strategy=strategy))

    def archive(self, run_id):
        self.orch.archive_run(int(run_id))
        self.orch.pump(max_wait=1.0)
        return self._to_dict(self.orch.get_run(int(run_id)))

    def restore(self, run_id):
        self.orch.restore_run(int(run_id))
        return self._to_dict(self.orch.get_run(int(run_id)))

    def delete(self, run_id):
        deleted = self.orch.delete_run(int(run_id))
        return {"ok": True, "deleted": deleted}

    def list_archives(self):
        return [
            self._to_dict(r)
            for r in self.orch.registry.list_runs(archived=True)
        ]

    def logs(self, run_id, since_id=0):
        self.orch.pump()
        return self.orch.registry.get_logs(int(run_id), since_id=since_id)

    def statuses(self, run_id):
        self.orch.pump()
        return self.orch.registry.get_statuses(int(run_id))

    def list_devices(self):
        return self.orch.registry.list_devices()

    def register_device(self, name, accelerator, chips, num_hosts):
        return self.orch.register_device(name, accelerator, chips, num_hosts=num_hosts)

    def remove_device(self, name):
        if not self.orch.registry.remove_device(name):
            raise SystemExit(f"no device named {name!r}")
        return {"ok": True}

    def list_artifacts(self, run_id):
        return self.orch.list_artifacts(int(run_id))

    def create_user(self, username, role):
        user, token = self.orch.registry.create_user(username, role=role)
        return {**user, "token": token}

    def list_options(self):
        from polyaxon_tpu.conf.options import options_payload

        return options_payload(self.orch.conf)

    def set_option(self, key, value):
        from polyaxon_tpu.conf.options import display_value, option_by_key

        opt = option_by_key(key)
        if opt is None:
            raise SystemExit(f"unknown option {key!r}")
        try:
            self.orch.conf.set(key, value)
        except (TypeError, ValueError) as e:
            raise SystemExit(str(e))  # clean message, like the API's 400
        return {"key": key, "value": display_value(opt, self.orch.conf.get(key))}

    def list_users(self):
        return self.orch.registry.list_users()

    def remove_user(self, username):
        if not self.orch.registry.remove_user(username):
            raise SystemExit(f"no user named {username!r}")
        return {"ok": True}

    def create_search(self, name, query):
        from polyaxon_tpu.query import compile_to_sql, parse_query

        # Field validation too (same as the API): a stored search must
        # never blow up at ps --search time.
        compile_to_sql(parse_query(query))
        return self.orch.registry.create_search(name, query)

    def list_searches(self):
        return self.orch.registry.list_searches()

    def delete_search(self, name):
        if not self.orch.registry.delete_search(name):
            raise SystemExit(f"no search named {name!r}")
        return {"ok": True}

    def execute_search(self, name):
        search = self.orch.registry.get_search(name)
        if search is None:
            raise SystemExit(f"no search named {name!r}")
        from polyaxon_tpu.query import apply_query, filters_archived, parse_query

        conds = parse_query(search["query"])
        runs = apply_query(
            self.orch.registry.list_runs(
                archived=None if filters_archived(conds) else False
            ),
            conditions=conds,
        )
        return [self._to_dict(r) for r in runs]

    def create_project(self, name, description, owner=None):
        return self.orch.registry.create_project(
            name, description=description, owner=owner
        )

    def set_ci(self, project, spec):
        return self.orch.set_project_ci(project, spec)

    def get_ci(self, project):
        ci = self.orch.registry.get_project_ci(project)
        if ci is None:
            raise SystemExit(f"no CI configured for {project!r}")
        return ci

    def delete_ci(self, project):
        if not self.orch.delete_project_ci(project):
            raise SystemExit(f"no CI configured for {project!r}")
        return {"ok": True}

    def trigger_ci(self, project, context=None):
        run = self.orch.trigger_ci(project, context=context)
        self.orch.pump(max_wait=1.0)
        if run is None:
            return {"triggered": False}
        return {"triggered": True, "run": self._to_dict(run)}

    def share_project(self, name, username):
        if self.orch.registry.get_project(name) is None:
            raise SystemExit(f"no project named {name!r}")
        self.orch.registry.add_collaborator(name, username)
        return self.orch.registry.get_project(name)

    def unshare_project(self, name, username):
        if not self.orch.registry.remove_collaborator(name, username):
            raise SystemExit(f"{username!r} is not a collaborator on {name!r}")
        return {"ok": True}

    def list_projects(self):
        return self.orch.registry.list_projects()

    def delete_project(self, name):
        from polyaxon_tpu.exceptions import PolyaxonTPUError

        try:
            removed = self.orch.delete_project(name)
        except PolyaxonTPUError as e:
            raise SystemExit(str(e))
        if not removed:
            raise SystemExit(f"no project named {name!r}")
        return {"ok": True}

    def add_bookmark(self, run_id):
        # Owner '' == anonymous — the same convention the API middleware
        # maps its open-mode actor to, so local and serve modes share
        # bookmarks on a common base dir.
        self.orch.registry.add_bookmark(int(run_id))
        return {"ok": True}

    def remove_bookmark(self, run_id):
        if not self.orch.registry.remove_bookmark(int(run_id)):
            raise SystemExit("not bookmarked")
        return {"ok": True}

    def list_bookmarks(self):
        return [self._to_dict(r) for r in self.orch.registry.list_bookmarked_runs()]

    def open_artifact(self, run_id, key):
        f = self.orch.open_artifact(int(run_id), key)
        if f is None:
            raise SystemExit(f"artifact {key!r} not found for run {run_id}")
        return f

    def pump(self, max_wait: float) -> None:
        self.orch.pump(max_wait=max_wait)

    def close(self) -> None:
        self.orch.stop()


#: The clone strategies (reference CloningStrategy, SURVEY §5) — one list
#: shared by the parser, the dispatch, and the recovery gate so a new
#: strategy can't ship with recovery silently missing.
CLONE_STRATEGIES = ("restart", "resume", "copy")

#: Local-mode commands that drive the task graph and therefore recover
#: stranded work on startup. `logs --follow` is included: following a run
#: started by a previous invocation requires reattaching its gang to make
#: progress (each CLI invocation is a fresh control plane).
_DRIVING_COMMANDS = {"run", "stop", "archive", "delete", *CLONE_STRATEGIES}


def _client(args):
    if args.host:
        return RemoteClient(args.host, token=getattr(args, "token", None))
    recover = args.command in _DRIVING_COMMANDS or (
        args.command == "logs" and getattr(args, "follow", False)
    ) or (
        args.command == "ci" and getattr(args, "ci_command", None) == "trigger"
    )
    return LocalClient(args.base_dir, recover=recover)


def _watch(client, run_id: int, poll: float = 0.5) -> str:
    seen_status = None
    log_cursor = 0
    while True:
        if isinstance(client, LocalClient):
            client.pump(max_wait=poll)
        run = client.get(run_id)
        if run["status"] != seen_status:
            seen_status = run["status"]
            print(f"[status] {seen_status}", file=sys.stderr)
        for row in client.logs(run_id, since_id=log_cursor):
            log_cursor = max(log_cursor, row["id"])
            prefix = f"p{row['process_id']}| " if row.get("process_id") is not None else ""
            print(f"{prefix}{row['line']}")
        if run["is_done"]:
            return run["status"]
        if not isinstance(client, LocalClient):
            time.sleep(poll)


def _print_runs(runs) -> None:
    fmt = "{:>5}  {:12}  {:10}  {:12}  {:}"
    print(fmt.format("ID", "KIND", "PROJECT", "STATUS", "NAME"))
    for r in runs:
        print(
            fmt.format(
                r["id"], r["kind"], r["project"][:10], r["status"], r["name"] or ""
            )
        )


#: `init` scaffolds (the reference's `polyaxon init` starter files).
_STARTERS = {
    "experiment": """kind: experiment
run:
  entrypoint: polyaxon_tpu.builtins.trainers:lm_train
declarations:
  steps: 100
  batch: 8
  seq: 512
environment:
  seed: 42
  topology:
    accelerator: v5e-8
    strategy: fsdp
""",
    "group": """kind: group
run:
  entrypoint: polyaxon_tpu.builtins.trainers:lm_train
declarations:
  steps: 100
hptuning:
  concurrency: 2
  matrix:
    lr: {values: [1.0e-4, 3.0e-4, 1.0e-3]}
environment:
  topology:
    accelerator: v5e-8
    strategy: ddp
""",
    "pipeline": """kind: pipeline
ops:
  - name: prepare
    run:
      entrypoint: polyaxon_tpu.builtins.trainers:noop
    environment:
      topology: {accelerator: v5e-8}
  - name: train
    run:
      entrypoint: polyaxon_tpu.builtins.trainers:lm_train
    environment:
      topology: {accelerator: v5e-8}
    dependencies: [prepare]
""",
    "tensorboard": """kind: tensorboard
declarations:
  target: <run-uuid>   # whose outputs to visualize
environment:
  topology:
    accelerator: cpu-1
    num_devices: 1
    num_hosts: 1
""",
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="polyaxon-tpu", description="TPU-native experiment platform CLI"
    )
    from polyaxon_tpu.version import __version__

    parser.add_argument(
        "--version", action="version", version=f"polyaxon-tpu {__version__}"
    )
    parser.add_argument("--host", help="API server address (remote mode)")
    parser.add_argument(
        "--token", help="API bearer token (or POLYAXON_TPU_AUTH_TOKEN)"
    )
    parser.add_argument(
        "--base-dir", default=DEFAULT_BASE, help="platform state dir (local mode)"
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_init = sub.add_parser("init", help="write a starter polyaxonfile")
    p_init.add_argument("-f", "--file", default="polyaxonfile.yml")
    p_init.add_argument(
        "--kind", default="experiment",
        choices=("experiment", "group", "pipeline", "tensorboard"),
    )

    p_run = sub.add_parser("run", help="submit a polyaxonfile")
    p_run.add_argument("-f", "--file", required=True, help="spec file (yaml/json)")
    p_run.add_argument("--project", default="default")
    p_run.add_argument("--name")
    p_run.add_argument("--tags", nargs="*")
    p_run.add_argument(
        "-w", "--watch", action="store_true", help="stream statuses/logs until done"
    )

    p_ps = sub.add_parser("ps", help="list runs")
    p_ps.add_argument("--project")
    p_ps.add_argument("--kind")
    p_ps.add_argument("--limit", type=int, default=50)
    p_ps.add_argument(
        "-q", "--query", help='filter DSL, e.g. "status:running,metric.loss:<0.5"'
    )
    p_ps.add_argument("--search", help="run a saved search by name")

    p_get = sub.add_parser("get", help="show one run as json")
    p_get.add_argument("run_id")

    p_logs = sub.add_parser("logs", help="print run logs")
    p_logs.add_argument("run_id")
    p_logs.add_argument("-f", "--follow", action="store_true")

    p_stop = sub.add_parser("stop", help="stop a run")
    p_stop.add_argument("run_id")

    p_archive = sub.add_parser(
        "archive", help="hide a run from listings (stops it if live)"
    )
    p_archive.add_argument("run_id")

    p_restore = sub.add_parser("restore", help="un-archive a run")
    p_restore.add_argument("run_id")

    p_delete = sub.add_parser(
        "delete", help="purge a run: rows, outputs, logs, store artifacts"
    )
    p_delete.add_argument("run_id")

    sub.add_parser("archives", help="list archived runs")

    for strategy in CLONE_STRATEGIES:
        p = sub.add_parser(strategy, help=f"{strategy} a run as a clone")
        p.add_argument("run_id")

    p_statuses = sub.add_parser("statuses", help="status history")
    p_statuses.add_argument("run_id")

    p_dev = sub.add_parser("devices", help="accelerator inventory (admission)")
    dev_sub = p_dev.add_subparsers(dest="devices_command", required=True)
    dev_sub.add_parser("list", help="show registered slices and holders")
    p_dev_add = dev_sub.add_parser("add", help="register a slice")
    p_dev_add.add_argument("name")
    p_dev_add.add_argument("--accelerator", required=True, help="e.g. v5e-8")
    p_dev_add.add_argument("--chips", type=int, required=True)
    p_dev_add.add_argument("--hosts", type=int, default=1)
    p_dev_rm = dev_sub.add_parser("remove", help="drop a slice")
    p_dev_rm.add_argument("name")

    p_pool = sub.add_parser(
        "pools", help="provision/teardown TPU-VM slices (local mode, gcloud)"
    )
    pool_sub = p_pool.add_subparsers(dest="pools_command", required=True)
    p_pool_up = pool_sub.add_parser(
        "provision", help="create N slices, register them for admission + ssh"
    )
    p_pool_up.add_argument("prefix", help="slice name prefix ({prefix}-{i})")
    p_pool_up.add_argument("--count", type=int, default=1)
    p_pool_up.add_argument(
        "--type", required=True, dest="accelerator_type",
        help="accelerator type, e.g. v5litepod-16",
    )
    p_pool_up.add_argument("--version", help="tpu-vm image (default from conf)")
    p_pool_up.add_argument("--preemptible", action="store_true")
    pool_sub.add_parser("list", help="management-plane view joined with admission")
    p_pool_down = pool_sub.add_parser(
        "teardown", help="delete slices and unregister them"
    )
    p_pool_down.add_argument("names", nargs="+")

    p_data = sub.add_parser("data", help="store-resident datasets (local mode)")
    data_sub = p_data.add_subparsers(dest="data_command", required=True)
    data_sub.add_parser("ls", help="list registered datasets")
    p_data_cifar = data_sub.add_parser(
        "register-cifar10", help="register CIFAR-10 from the standard archive dir"
    )
    p_data_cifar.add_argument("batches_dir", help="path to cifar-10-batches-py")

    p_art = sub.add_parser("artifacts", help="browse/fetch run artifacts")
    art_sub = p_art.add_subparsers(dest="artifacts_command", required=True)
    p_art_ls = art_sub.add_parser("ls", help="list a run's artifact keys")
    p_art_ls.add_argument("run_id")
    p_art_pull = art_sub.add_parser("pull", help="download one artifact")
    p_art_pull.add_argument("run_id")
    p_art_pull.add_argument("key")
    p_art_pull.add_argument("-o", "--output", help="write here (default: stdout)")

    p_proj = sub.add_parser("projects", help="project metadata")
    proj_sub = p_proj.add_subparsers(dest="projects_command", required=True)
    p_proj_add = proj_sub.add_parser("add", help="register a project")
    p_proj_add.add_argument("name")
    p_proj_add.add_argument("--description")
    p_proj_add.add_argument(
        "--owner", help="scope access to this user (+collaborators/admins)"
    )
    proj_sub.add_parser("list", help="projects with run counts")
    p_proj_rm = proj_sub.add_parser("remove", help="delete an empty project")
    p_proj_rm.add_argument("name")
    p_proj_share = proj_sub.add_parser("share", help="add a collaborator")
    p_proj_share.add_argument("name")
    p_proj_share.add_argument("username")
    p_proj_unshare = proj_sub.add_parser("unshare", help="drop a collaborator")
    p_proj_unshare.add_argument("name")
    p_proj_unshare.add_argument("username")

    p_ci = sub.add_parser(
        "ci", help="per-project CI: run a spec on every new code snapshot"
    )
    ci_sub = p_ci.add_subparsers(dest="ci_command", required=True)
    p_ci_set = ci_sub.add_parser("set", help="enable/replace a project's CI spec")
    p_ci_set.add_argument("-f", "--file", required=True, help="polyaxonfile to run")
    p_ci_set.add_argument("-p", "--project", default="default")
    p_ci_show = ci_sub.add_parser("show", help="show a project's CI config")
    p_ci_show.add_argument("-p", "--project", default="default")
    p_ci_off = ci_sub.add_parser("off", help="disable a project's CI")
    p_ci_off.add_argument("-p", "--project", default="default")
    p_ci_trigger = ci_sub.add_parser(
        "trigger", help="snapshot a context dir and run CI if the code is new"
    )
    p_ci_trigger.add_argument("-p", "--project", default="default")
    p_ci_trigger.add_argument(
        "--context", help="directory to snapshot (default: the CI spec's build context)"
    )

    p_search = sub.add_parser("searches", help="saved run searches")
    search_sub = p_search.add_subparsers(dest="searches_command", required=True)
    p_search_add = search_sub.add_parser("add", help="save a query under a name")
    p_search_add.add_argument("name")
    p_search_add.add_argument("query")
    search_sub.add_parser("list", help="list saved searches")
    p_search_rm = search_sub.add_parser("remove", help="delete a saved search")
    p_search_rm.add_argument("name")

    p_bm = sub.add_parser("bookmark", help="bookmark a run")
    p_bm.add_argument("run_id")
    p_bm.add_argument("-d", "--delete", action="store_true", help="remove instead")
    sub.add_parser("bookmarks", help="list bookmarked runs")

    p_cfg = sub.add_parser("config", help="runtime-mutable platform options")
    cfg_sub = p_cfg.add_subparsers(dest="config_command", required=True)
    cfg_sub.add_parser("list", help="all options with resolved values")
    p_cfg_set = cfg_sub.add_parser("set", help="write an option to the DB store")
    p_cfg_set.add_argument("key")
    p_cfg_set.add_argument("value")

    p_login = sub.add_parser("login", help="store an API host + token")
    p_login.add_argument("--api-host", required=True, help="API server address")
    p_login.add_argument("--api-token", required=True, help="your user token")

    p_users = sub.add_parser("users", help="manage users (admin)")
    users_sub = p_users.add_subparsers(dest="users_command", required=True)
    p_users_add = users_sub.add_parser("add", help="create a user, print their token")
    p_users_add.add_argument("username")
    p_users_add.add_argument("--role", default="user", choices=("user", "admin"))
    users_sub.add_parser("list", help="list users")
    p_users_rm = users_sub.add_parser("remove", help="delete a user")
    p_users_rm.add_argument("username")

    p_serve = sub.add_parser("serve", help="run the API service")
    p_serve.add_argument("--port", type=int, default=8000)
    p_serve.add_argument("--bind", default="127.0.0.1")

    args = parser.parse_args(argv)

    if args.command == "login":
        host = args.api_host.rstrip("/")
        if not host.startswith("http"):
            host = f"http://{host}"  # the normalization RemoteClient applies
        AUTH_FILE.parent.mkdir(parents=True, exist_ok=True)
        # 0600 from birth — no window where the token is world-readable.
        import os as _os

        fd = _os.open(
            AUTH_FILE, _os.O_WRONLY | _os.O_CREAT | _os.O_TRUNC, 0o600
        )
        with _os.fdopen(fd, "w") as fh:
            fh.write(json.dumps({"host": host, "token": args.api_token}))
        print(f"stored credentials for {host} in {AUTH_FILE}", file=sys.stderr)
        return 0

    if args.command == "serve":
        from polyaxon_tpu.api.app import serve

        serve(
            str(Path(args.base_dir).expanduser()),
            host=args.bind,
            port=args.port,
            auth_token=args.token,
        )
        return 0

    if args.command == "init":
        target = Path(args.file)
        try:
            # Exclusive create: refuses existing files atomically (no
            # exists()-then-write race).
            with target.open("x") as fh:
                fh.write(_STARTERS[args.kind])
        except FileExistsError:
            raise SystemExit(f"{target} already exists")
        print(f"wrote {target} ({args.kind})", file=sys.stderr)
        return 0

    client = _client(args)
    try:
        if args.command == "run":
            spec_text = Path(args.file).read_text()
            run = client.submit(spec_text, args.project, args.name, args.tags)
            print(f"Created run {run['id']} ({run['kind']})", file=sys.stderr)
            if args.watch:
                status = _watch(client, run["id"])
                return 0 if status == "succeeded" else 1
            print(json.dumps(run, indent=2, default=str))
            return 0
        if args.command == "ps":
            if args.search:
                _print_runs(client.execute_search(args.search))
            else:
                _print_runs(
                    client.list(
                        project=args.project,
                        kind=args.kind,
                        limit=args.limit,
                        q=args.query,
                    )
                )
            return 0
        if args.command == "get":
            print(json.dumps(client.get(args.run_id), indent=2, default=str))
            return 0
        if args.command == "logs":
            if args.follow:
                _watch(client, int(args.run_id))
            else:
                for row in client.logs(args.run_id):
                    print(row["line"])
            return 0
        if args.command == "stop":
            client.stop(args.run_id)
            print("stopped", file=sys.stderr)
            return 0
        if args.command == "archive":
            run = client.archive(args.run_id)
            print(f"archived run {run['id']}", file=sys.stderr)
            return 0
        if args.command == "restore":
            run = client.restore(args.run_id)
            print(f"restored run {run['id']}", file=sys.stderr)
            return 0
        if args.command == "delete":
            out = client.delete(args.run_id)
            print(
                f"deleted {out.get('deleted', 1)} run(s) and their data",
                file=sys.stderr,
            )
            return 0
        if args.command == "archives":
            _print_runs(client.list_archives())
            return 0
        if args.command in CLONE_STRATEGIES:
            clone = client.clone(args.run_id, args.command)
            print(json.dumps(clone, indent=2, default=str))
            return 0
        if args.command == "statuses":
            for s in client.statuses(args.run_id):
                msg = f"  {s['message']}" if s.get("message") else ""
                print(f"{s['created_at']:.1f}  {s['status']}{msg}")
            return 0
        if args.command == "data":
            if not isinstance(client, LocalClient):
                raise SystemExit("data commands run in local mode (datasets live in the store layout)")
            from polyaxon_tpu.runtime.datasets import list_datasets, register_cifar10

            data_dir = client.orch.layout.data_dir
            if args.data_command == "ls":
                for d in list_datasets(data_dir):
                    print(
                        f"{d['name']:24} {d['num_examples']:>8} examples, "
                        f"{d['shards']} shards"
                    )
            elif args.data_command == "register-cifar10":
                out = register_cifar10(data_dir, args.batches_dir)
                for split, meta in out.items():
                    print(f"registered cifar10-{split}: {meta['num_examples']} examples")
            return 0
        if args.command == "artifacts":
            if args.artifacts_command == "ls":
                for key in client.list_artifacts(args.run_id):
                    print(key)
            elif args.artifacts_command == "pull":
                import shutil

                with client.open_artifact(args.run_id, args.key) as src:
                    if args.output:
                        with open(args.output, "wb") as dst:
                            shutil.copyfileobj(src, dst)
                        print(f"wrote {args.output}", file=sys.stderr)
                    else:
                        shutil.copyfileobj(src, sys.stdout.buffer)
            return 0
        if args.command == "projects":
            if args.projects_command == "add":
                print(json.dumps(client.create_project(
                    args.name, args.description, owner=args.owner
                )))
            elif args.projects_command == "list":
                fmt = "{:16}  {:>6}  {:10}  {:}"
                print(fmt.format("NAME", "RUNS", "OWNER", "DESCRIPTION"))
                for pr in client.list_projects():
                    print(fmt.format(
                        pr["name"], pr["num_runs"], pr.get("owner") or "-",
                        pr.get("description") or "",
                    ))
            elif args.projects_command == "remove":
                client.delete_project(args.name)
                print("removed", file=sys.stderr)
            elif args.projects_command == "share":
                print(json.dumps(client.share_project(args.name, args.username)))
            elif args.projects_command == "unshare":
                client.unshare_project(args.name, args.username)
                print("removed collaborator", file=sys.stderr)
            return 0
        if args.command == "ci":
            if args.ci_command == "set":
                spec_text = Path(args.file).read_text()
                import yaml

                ci = client.set_ci(args.project, yaml.safe_load(spec_text))
                print(json.dumps(ci, indent=2, default=str))
            elif args.ci_command == "show":
                print(json.dumps(client.get_ci(args.project), indent=2, default=str))
            elif args.ci_command == "off":
                client.delete_ci(args.project)
                print("CI disabled", file=sys.stderr)
            elif args.ci_command == "trigger":
                out = client.trigger_ci(args.project, context=args.context)
                if out.get("triggered"):
                    run = out["run"]
                    print(
                        f"CI triggered run {run['id']} ({run['kind']})",
                        file=sys.stderr,
                    )
                else:
                    print("code unchanged — nothing to run", file=sys.stderr)
            return 0
        if args.command == "searches":
            if args.searches_command == "add":
                print(json.dumps(client.create_search(args.name, args.query)))
            elif args.searches_command == "list":
                for sr in client.list_searches():
                    print(f"{sr['name']:20} {sr['query']}")
            elif args.searches_command == "remove":
                client.delete_search(args.name)
                print("removed", file=sys.stderr)
            return 0
        if args.command == "bookmark":
            if args.delete:
                client.remove_bookmark(args.run_id)
                print("unbookmarked", file=sys.stderr)
            else:
                client.add_bookmark(args.run_id)
                print("bookmarked", file=sys.stderr)
            return 0
        if args.command == "bookmarks":
            _print_runs(client.list_bookmarks())
            return 0
        if args.command == "config":
            if args.config_command == "list":
                fmt = "{:36}  {:18}  {:}"
                print(fmt.format("KEY", "VALUE", "DESCRIPTION"))
                for o in client.list_options():
                    print(fmt.format(o["key"], str(o["value"])[:18],
                                     o["description"][:60]))
            elif args.config_command == "set":
                out = client.set_option(args.key, args.value)
                print(json.dumps(out))
            return 0
        if args.command == "users":
            if args.users_command == "add":
                user = client.create_user(args.username, args.role)
                print(
                    f"user {user['username']} ({user['role']}) created; token "
                    "(shown once):",
                    file=sys.stderr,
                )
                print(user["token"])
            elif args.users_command == "list":
                fmt = "{:>4}  {:16}  {:6}  {:}"
                print(fmt.format("ID", "USERNAME", "ROLE", "LAST USED"))
                for u in client.list_users():
                    last = u.get("last_used_at")
                    print(
                        fmt.format(
                            u["id"], u["username"], u["role"],
                            f"{last:.0f}" if last else "-",
                        )
                    )
            elif args.users_command == "remove":
                client.remove_user(args.username)
                print("removed", file=sys.stderr)
            return 0
        if args.command == "pools":
            if not isinstance(client, LocalClient):
                raise SystemExit(
                    "pools commands run in local mode (gcloud + registry access)"
                )
            from polyaxon_tpu.spawner.provision import TPUPool, TPUVMProvisioner

            conf = client.orch.conf
            zone = conf.get("provision.zone")
            if not zone:
                raise SystemExit(
                    "set provision.zone first: polyaxon-tpu config set provision.zone <zone>"
                )
            pool = TPUPool(
                TPUVMProvisioner(
                    zone=zone,
                    gcloud_bin=conf.get("provision.gcloud_bin") or "gcloud",
                    project=conf.get("provision.project") or None,
                ),
                client.orch.registry,
                conf,
                orchestrator=client.orch,
            )
            if args.pools_command == "provision":
                infos = pool.provision(
                    args.prefix,
                    args.count,
                    accelerator_type=args.accelerator_type,
                    version=args.version or conf.get("provision.version"),
                    preemptible=args.preemptible,
                )
                for info in infos:
                    print(
                        f"{info.name}: {info.state} {info.accelerator_type} "
                        f"chips={info.chips} hosts={','.join(info.hosts)}"
                    )
            elif args.pools_command == "list":
                fmt = "{:16}  {:14}  {:14}  {:>6}  {:>6}  {:10}  {:}"
                print(fmt.format(
                    "NAME", "STATE", "ACCEL", "CHIPS", "HOSTS", "HELD BY", "IPS"
                ))
                for row in pool.status():
                    print(fmt.format(
                        row["name"], row["state"], row["accelerator"],
                        row["chips"], row["num_hosts"], str(row["run_id"] or "-"),
                        ",".join(row["hosts"]),
                    ))
            elif args.pools_command == "teardown":
                n = pool.teardown(args.names)
                print(f"deleted {n} slice(s)", file=sys.stderr)
            return 0
        if args.command == "devices":
            if args.devices_command == "list":
                fmt = "{:>4}  {:16}  {:10}  {:>9}  {:>6}  {:}"
                print(fmt.format("ID", "NAME", "ACCEL", "CHIPS", "HOSTS", "HELD BY"))
                for d in client.list_devices():
                    used = d.get("used_chips", d["chips"] if d.get("run_id") else 0)
                    holders = d.get("holders") or (
                        [d["run_id"]] if d.get("run_id") else []
                    )
                    print(
                        fmt.format(
                            d["id"], d["name"], d["accelerator"],
                            f"{used}/{d['chips']}", d["num_hosts"],
                            ",".join(str(h) for h in holders) or "-",
                        )
                    )
            elif args.devices_command == "add":
                d = client.register_device(
                    args.name, args.accelerator, args.chips, args.hosts
                )
                print(json.dumps(d, indent=2, default=str))
            elif args.devices_command == "remove":
                client.remove_device(args.name)
                print("removed", file=sys.stderr)
            return 0
    finally:
        if isinstance(client, LocalClient):
            client.close()
    return 2


if __name__ == "__main__":
    sys.exit(main())
