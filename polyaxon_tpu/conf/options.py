"""Typed option registry.

Parity: reference ``options/option.py:13-40`` (``Option`` with key/type/
default/store) and the ~40 registry modules under ``options/registry/``
(scheduler intervals, heartbeats, groups chunking, TPU keys
``options/registry/k8s.py:20-23``).  Collapsed to one module: the platform
has far fewer knobs because celery/k8s/redis are gone — what remains are
the scheduler cadences, restart policy bounds, store paths, and bench/
mesh defaults.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple


class OptionStores:
    ENV = "env"  # POLYAXON_TPU_<KEY>
    DB = "db"  # registry options table (cluster-editable at runtime)
    DEFAULT = "default"


@dataclass(frozen=True)
class Option:
    key: str
    typing: type
    default: Any
    description: str = ""
    #: resolution order, first hit wins
    stores: Tuple[str, ...] = (OptionStores.DB, OptionStores.ENV, OptionStores.DEFAULT)
    #: secrets are write-only over every surface (API/CLI list AND set
    #: responses mask them)
    secret: bool = False
    #: closed value set; coerce() rejects anything else
    choices: Optional[Tuple[str, ...]] = None

    @property
    def env_var(self) -> str:
        return "POLYAXON_TPU_" + self.key.upper().replace(".", "_")

    def coerce(self, raw: Any) -> Any:
        if raw is None:
            return raw
        if not isinstance(raw, self.typing):
            if self.typing is bool:
                raw = str(raw).lower() in ("1", "true", "yes", "on")
            else:
                raw = self.typing(raw)
        if self.choices is not None and raw not in self.choices:
            raise ValueError(
                f"{self.key} must be one of {self.choices}, got {raw!r}"
            )
        return raw


_ALL = [
    Option("scheduler.monitor_interval", float, 0.2,
           "gang poll cadence (reference Intervals.EXPERIMENTS_SYNC=30s analog)"),
    Option("scheduler.heartbeat_ttl", float, 600.0,
           "no-heartbeat window before a run is declared zombie"),
    Option("scheduler.heartbeat_check_interval", float, 60.0,
           "zombie-check cron cadence (reference beat: 600s)"),
    Option("scheduler.terminal_grace", float, 10.0,
           "grace before force-stopping a logically-done gang"),
    Option("scheduler.monitor_failure_streak", int, 25,
           "consecutive monitor-poll failures before a run is failed"),
    Option("scheduler.queued_redispatch_ttl", float, 60.0,
           "age before a run stranded in QUEUED is re-dispatched"),
    Option("worker.heartbeat_interval", float, 5.0,
           "in-process heartbeat cadence (reference sidecar poll: 2s)"),
    Option("spawner.default_accelerator", str, "cpu",
           "topology.accelerator default for specs that omit it"),
    Option("spawner.backend", str, "local",
           "gang transport (restart required)", choices=("local", "ssh")),
    Option("spawner.hosts", str, "",
           "comma-separated worker host addresses for the ssh backend "
           "(slice order: worker 0 first — it hosts the coordinator)"),
    Option("spawner.ssh_user", str, "", "ssh login user ('' = current user)"),
    Option("spawner.ssh_identity_file", str, "", "ssh private key path"),
    Option("spawner.remote_python", str, "python3",
           "python interpreter on worker hosts"),
    Option("spawner.coordinator_port_base", int, 8476,
           "base of the 512-wide jax.distributed coordinator port range"),
    Option("sso.provider", str, "",
           "single sign-on provider ('' = SSO off; oidc = endpoints from "
           "sso.*_url)",
           choices=("", "github", "gitlab", "bitbucket", "azure", "oidc")),
    Option("sso.client_id", str, "", "OAuth2 client id"),
    Option("sso.client_secret", str, "", "OAuth2 client secret", secret=True),
    Option("sso.authorize_url", str, "",
           "authorize endpoint override (oidc/self-hosted providers)"),
    Option("sso.token_url", str, "", "token endpoint override"),
    Option("sso.userinfo_url", str, "", "userinfo endpoint override"),
    Option("sso.username_field", str, "",
           "userinfo JSON field naming the user ('' = provider default)"),
    Option("sso.redirect_base", str, "",
           "public base URL of this platform for the OAuth callback "
           "('' = derive from the request)"),
    Option("sso.allowed_users", str, "",
           "comma-separated provider usernames allowed to self-provision "
           "via SSO (existing same-provider users always may log in)"),
    Option("sso.auto_create", bool, False,
           "create a platform user for ANY provider identity — on a "
           "public provider this opens the platform to every account "
           "there; prefer the allowlist"),
    Option("provision.zone", str, "",
           "GCE zone for tpu-vm provisioning (e.g. us-central2-b); "
           "'' disables the pools commands"),
    Option("provision.project", str, "",
           "GCP project for tpu-vm provisioning ('' = gcloud default)"),
    Option("provision.gcloud_bin", str, "gcloud",
           "gcloud binary (tests point this at a fake)"),
    Option("provision.version", str, "tpu-ubuntu2204-base",
           "tpu-vm software version for created slices"),
    Option("stores.artifacts_url", str, "",
           "durable artifact store (file:///path or gs://bucket/prefix); "
           "'' disables off-box sync"),
    Option("notifier.webhook_url", str, "",
           "notification webhook endpoint ('' = off)"),
    Option("notifier.webhook_kind", str, "",
           "payload dialect ('' = raw JSON; restart required)",
           choices=("", "slack", "discord", "mattermost", "pagerduty")),
    Option("notifier.pagerduty_routing_key", str, "",
           "Events-API-v2 integration key (webhook_kind=pagerduty)"),
    Option("notifier.email_host", str, "", "SMTP host ('' = email off)"),
    Option("notifier.email_port", int, 25, "SMTP port"),
    Option("notifier.email_from", str, "polyaxon-tpu@localhost", "sender address"),
    Option("notifier.email_to", str, "", "comma-separated recipients"),
    Option("notifier.email_tls", bool, False, "STARTTLS before sending"),
    Option("notifier.email_user", str, "", "SMTP login ('' = no auth)"),
    Option("notifier.email_password", str, "", "SMTP password", secret=True),
    Option("notifier.alert_routes", str, "",
           "severity→sink routing for alert-engine notifications, e.g. "
           "'critical:webhook,email;warning:webhook;info:log' "
           "('' = every severity to every sink; restart required)"),
    Option("groups.max_concurrency", int, 64,
           "upper bound on a sweep's concurrency setting"),
    Option("restarts.max_allowed", int, 10,
           "upper bound on restart_policy.max_restarts"),
    Option("logs.retention_days", float, 30.0, "activity/log cleanup horizon"),
    Option("cleaning.archives_ttl_days", float, 7.0,
           "archived runs older than this are purged by the cron"),
    Option("api.page_size", int, 100, "default list page size"),
    Option("tracker.endpoint", str, "",
           "anonymized usage-event publish URL ('' = off; restart required)"),
    Option("stats.backend", str, "memory",
           "operational metrics sink (restart required)",
           choices=("memory", "statsd", "noop")),
    Option("stats.statsd_host", str, "127.0.0.1", "statsd UDP host"),
    Option("stats.statsd_port", int, 8125, "statsd UDP port"),
]

OPTIONS: Dict[str, Option] = {o.key: o for o in _ALL}


def option_by_key(key: str) -> Optional[Option]:
    return OPTIONS.get(key)


def display_value(opt: Option, value: Any) -> Any:
    """What a read surface may show for this option's value."""
    return "***" if opt.secret else value


def options_payload(conf) -> list:
    """The option listing every surface serves (API and local CLI share
    this so the payloads can never drift)."""
    return [
        {
            "key": opt.key,
            "value": display_value(opt, conf.get(opt.key)),
            "default": display_value(opt, opt.default),
            "description": opt.description,
        }
        for opt in OPTIONS.values()
    ]
