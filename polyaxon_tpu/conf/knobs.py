"""Central ``POLYAXON_TPU_*`` env-knob catalog + typed accessors.

Every process-level env knob the platform reads lives here: name, type,
default, and one line of doc.  Before this module the ~40 knobs were
scattered across 18 files, each with its own ad-hoc ``_env_float``
helper — and a typo'd knob name silently no-oped forever.  Now:

- call sites read through the typed accessors (:func:`knob_bool` /
  :func:`knob_int` / :func:`knob_float` / :func:`knob_str`), which
  raise ``KeyError`` on a name the catalog doesn't know — a typo fails
  loudly at import/construction time instead of silently returning the
  hardcoded default;
- graft-lint rule **GL005** (``polyaxon_tpu/analysis``) closes the loop
  statically: every ``POLYAXON_TPU_*`` string literal in the package
  must resolve to a catalog entry, and every catalog entry must be
  referenced somewhere — no dead knobs, no phantom knobs;
- :func:`reference_table` renders the catalog as the markdown knob
  table in ``docs/observability.md`` (kept in sync by
  ``tests/test_analysis/test_knobs.py``).

Two kinds of entry:

- plain knobs — one env var, one default (the common case);
- *families* (``prefix=True``) — a declared prefix with dynamic
  suffixes, e.g. ``POLYAXON_TPU_ALERT_<RULE>_<PARAM>``; read through
  the ``family_*`` accessors which validate the prefix is declared.

This module imports nothing from the package (stdlib only) so every
layer — including pre-jax worker boot — can use it without cycles.
The cluster-editable *option* store (``conf/options.py``) is a separate
namespace: options are DB-backed and resolve DB → env → default; knobs
are env-only process configuration.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = [
    "Knob",
    "KNOBS",
    "FAMILIES",
    "knob_bool",
    "knob_int",
    "knob_float",
    "knob_str",
    "knob_default",
    "family_prefix",
    "family_value",
    "family_float",
    "reference_table",
]

#: Values (lowercased) that read as False for bool knobs.  An *empty*
#: string also reads as False — matching the historical call sites
#: (``POLYAXON_TPU_SERVING_WARMUP=""`` disables warmup).
_FALSY = ("0", "false", "off", "no", "")


@dataclass(frozen=True)
class Knob:
    name: str
    kind: str  # "bool" | "int" | "float" | "str"
    default: Any
    doc: str
    group: str = "misc"
    #: True = a declared prefix family with dynamic suffixes
    #: (``POLYAXON_TPU_ALERT_<RULE>_<PARAM>``), not a single env var.
    prefix: bool = False


_ALL: List[Knob] = [
    # -- gang rendezvous contract (spawner-written, worker-read) -----------
    Knob("POLYAXON_TPU_RUN_ID", "int", None,
         "run id of the gang this process belongs to", "gang-env"),
    Knob("POLYAXON_TPU_RUN_UUID", "str", None, "run uuid", "gang-env"),
    Knob("POLYAXON_TPU_RUN_DIR", "str", None,
         "the run's store directory", "gang-env"),
    Knob("POLYAXON_TPU_SPEC_PATH", "str", None,
         "path to the materialized run spec", "gang-env"),
    Knob("POLYAXON_TPU_PROCESS_ID", "int", None,
         "this process's gang rank", "gang-env"),
    Knob("POLYAXON_TPU_NUM_PROCESSES", "int", None,
         "gang size (hosts)", "gang-env"),
    Knob("POLYAXON_TPU_COORDINATOR", "str", "",
         "jax.distributed coordinator address ('' = single-host)",
         "gang-env"),
    Knob("POLYAXON_TPU_DEVICES_PER_HOST", "int", 1,
         "local device count per host", "gang-env"),
    Knob("POLYAXON_TPU_ACCELERATOR", "str", "cpu",
         "accelerator backend (cpu/tpu)", "gang-env"),
    Knob("POLYAXON_TPU_MESH", "str", "{}",
         "JSON mesh axes ({axis: size})", "gang-env"),
    Knob("POLYAXON_TPU_MESH_DCN", "str", "{}",
         "JSON subset of mesh axes spanning slices (DCN)", "gang-env"),
    Knob("POLYAXON_TPU_STRATEGY", "str", "ddp",
         "parallelism strategy template name", "gang-env"),
    Knob("POLYAXON_TPU_STRATEGY_OPTIONS", "str", "{}",
         "JSON strategy options", "gang-env"),
    Knob("POLYAXON_TPU_HEARTBEAT_INTERVAL", "float", 5.0,
         "reporter heartbeat cadence (s)", "gang-env"),
    Knob("POLYAXON_TPU_SEED", "int", None,
         "deterministic seed ('' = unseeded)", "gang-env"),
    Knob("POLYAXON_TPU_DATA_DIR", "str", "",
         "store layout's shared data/ dir (registered datasets)",
         "gang-env"),
    Knob("POLYAXON_TPU_SERVICE_PORT", "str", "",
         "dispatch-time allocated port for kind:service gangs",
         "gang-env"),
    # -- persistent XLA compile cache --------------------------------------
    Knob("POLYAXON_TPU_COMPILE_CACHE", "bool", True,
         "persistent XLA compile cache master switch", "compile-cache"),
    Knob("POLYAXON_TPU_COMPILE_CACHE_DIR", "str", "",
         "compile cache directory (spawner-resolved from the store "
         "layout; also part of the gang env contract)", "compile-cache"),
    Knob("POLYAXON_TPU_COMPILE_CACHE_MIN_COMPILE_S", "float", 0.0,
         "only persist compiles at least this slow (0 = everything)",
         "compile-cache"),
    # -- tracing / ledger ---------------------------------------------------
    Knob("POLYAXON_TPU_TRACE_SAMPLE", "float", 1.0,
         "span sampling rate for normal spans", "tracing"),
    Knob("POLYAXON_TPU_TRACE_HOT_SAMPLE", "float", 0.05,
         "span sampling rate for hot-path spans", "tracing"),
    Knob("POLYAXON_TPU_LEDGER_INTERVAL_S", "float", 30.0,
         "min spacing of cumulative utilization-ledger rows", "tracing"),
    Knob("POLYAXON_TPU_TRACE_REQUESTS", "bool", True,
         "request-scoped distributed tracing across router → replica → "
         "engine (waterfalls, /v1/trace exports, exemplars)", "tracing"),
    Knob("POLYAXON_TPU_TRACE_EXEMPLARS", "int", 5,
         "slowest fully-traced requests kept per exemplar window "
         "(0 = exemplars off)", "tracing"),
    Knob("POLYAXON_TPU_TRACE_EXEMPLAR_WINDOW_S", "float", 300.0,
         "sliding window for the slow-request exemplar ring (s)",
         "tracing"),
    # -- stall watchdog (worker side) --------------------------------------
    Knob("POLYAXON_TPU_WATCHDOG_K", "float", 8.0,
         "stall deadline = k x rolling median step dt", "watchdog"),
    Knob("POLYAXON_TPU_WATCHDOG_FLOOR_S", "float", 30.0,
         "stall deadline lower clamp (s)", "watchdog"),
    Knob("POLYAXON_TPU_WATCHDOG_CEILING_S", "float", 600.0,
         "stall deadline upper clamp, and the deadline before any dt "
         "sample exists (s)", "watchdog"),
    Knob("POLYAXON_TPU_WATCHDOG_INTERVAL_S", "float", 1.0,
         "watchdog poll period (s); <= 0 disables the thread", "watchdog"),
    Knob("POLYAXON_TPU_PROGRESS_INTERVAL_S", "float", 2.0,
         "min spacing of typed progress report lines (s)", "watchdog"),
    # -- gang watcher / anomaly detection (control plane) ------------------
    Knob("POLYAXON_TPU_WATCHER_POLL_BYTES", "int", 4 * 1024 * 1024,
         "per-poll read budget per process report file", "watcher"),
    Knob("POLYAXON_TPU_STALL_AFTER_S", "float", 60.0,
         "gang declared stalled when the newest beat is older than this "
         "but heartbeats stay fresh", "watcher"),
    Knob("POLYAXON_TPU_STRAGGLER_LAG_STEPS", "float", 50.0,
         "host straggler threshold vs the gang median step", "watcher"),
    Knob("POLYAXON_TPU_STALL_HEARTBEAT_FRESH_S", "float", 30.0,
         "heartbeat freshness window for the stall predicate", "watcher"),
    # -- alert engine -------------------------------------------------------
    Knob("POLYAXON_TPU_ALERT_INTERVAL_S", "float", 1.0,
         "per-run alert rule evaluation throttle (s)", "alerts"),
    Knob("POLYAXON_TPU_ALERT_", "float", None,
         "per-rule parameter family: POLYAXON_TPU_ALERT_<RULE>_<PARAM> "
         "(e.g. _GOODPUT_LOW_FLOOR) and _<RULE>_ENABLED", "alerts",
         prefix=True),
    # -- remediation engine -------------------------------------------------
    Knob("POLYAXON_TPU_REMEDIATION_ENABLED", "bool", True,
         "remediation master switch (off = legacy blind restart)",
         "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_BUDGET", "int", 16,
         "max non-skipped remediation actions per run", "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_BACKOFF_BASE_S", "str", "",
         "relaunch backoff base seconds ('' = the plan's "
         "backoff_seconds)", "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_BACKOFF_MAX_S", "float", 300.0,
         "relaunch backoff cap (s)", "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_CHECKPOINT_ALERTS", "str", "run_stalled",
         "comma-separated alert rules whose firing edge triggers "
         "checkpoint-now", "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_EVICT", "bool", False,
         "opt-in straggler eviction + elastic gang re-form", "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_COMMAND_TIMEOUT_S", "float", 30.0,
         "how long an issued command may stay unresolved before the "
         "action fails", "remediation"),
    Knob("POLYAXON_TPU_REMEDIATION_DRAIN_ALERTS", "str",
         "serving_ttft_p99,heartbeat_stale",
         "comma-separated alert rules whose firing edge triggers "
         "drain+replace on a serving-fleet replica", "remediation"),
    # -- serving ------------------------------------------------------------
    Knob("POLYAXON_TPU_SERVING_WARMUP", "bool", True,
         "pre-compile the whole serving fn family behind the readiness "
         "gate before traffic", "serving"),
    Knob("POLYAXON_TPU_SERVING_SPEC_DECODE", "bool", False,
         "speculative decoding: self-draft multi-token steps on the "
         "paged engine (greedy requests only)", "serving"),
    Knob("POLYAXON_TPU_SERVING_SPEC_K", "int", 4,
         "max drafted tokens per lane per verify step", "serving"),
    Knob("POLYAXON_TPU_SERVING_SPEC_MIN_NGRAM", "int", 2,
         "n-gram length the prompt-lookup drafter matches against the "
         "request's own context", "serving"),
    Knob("POLYAXON_TPU_SERVING_STATS_WINDOW_S", "float", 60.0,
         "trailing window (s) for the *_window variants of /v1/stats "
         "lifetime ratios (prefix_cache_hit_rate_window, "
         "spec_accept_rate_window)", "serving"),
    # -- hierarchical KV (host offload tier + persistent prefix store) -----
    Knob("POLYAXON_TPU_KV_OFFLOAD", "bool", False,
         "host-memory KV tier: parked sequences spill their private "
         "blocks to host and cold prefixes demote instead of evicting",
         "kv-tier"),
    Knob("POLYAXON_TPU_KV_OFFLOAD_BLOCKS", "int", 0,
         "host-tier budget for DEMOTED prefix blocks (0 = unbounded; "
         "parked-sequence spills are pinned and never count)", "kv-tier"),
    Knob("POLYAXON_TPU_KV_PERSIST_DIR", "str", "",
         "prefix-store directory ('' = persistence off); normally the "
         "store layout's kv_cache/ dir so every replica shares it",
         "kv-tier"),
    Knob("POLYAXON_TPU_KV_PERSIST_BLOCKS", "int", 64,
         "max prefix blocks per persisted snapshot (hottest-first with "
         "chain closure)", "kv-tier"),
    Knob("POLYAXON_TPU_KV_PERSIST_INTERVAL_S", "float", 60.0,
         "min spacing of idle-time prefix-store snapshots (stop() "
         "always writes a final one)", "kv-tier"),
    # -- fleet router (control-plane request routing) ----------------------
    Knob("POLYAXON_TPU_ROUTER_PROBE_INTERVAL_S", "float", 1.0,
         "health/stats probe cadence per replica (s)", "router"),
    Knob("POLYAXON_TPU_ROUTER_PROBE_TIMEOUT_S", "float", 2.0,
         "per-probe HTTP timeout (s)", "router"),
    Knob("POLYAXON_TPU_ROUTER_REQUEST_TIMEOUT_S", "float", 600.0,
         "proxied /generate timeout per attempt (s)", "router"),
    Knob("POLYAXON_TPU_ROUTER_SHED_OCCUPANCY", "float", 0.95,
         "fleet-mean occupancy ceiling; at/above it new requests are "
         "shed with a typed 429 + Retry-After", "router"),
    Knob("POLYAXON_TPU_ROUTER_RETRY_AFTER_S", "float", 1.0,
         "Retry-After seconds advertised on shed (429) responses",
         "router"),
    Knob("POLYAXON_TPU_ROUTER_RETRY_LIMIT", "int", 2,
         "max failover retries per request on connection error/replica "
         "death (admission is idempotent before the first token)",
         "router"),
    Knob("POLYAXON_TPU_ROUTER_EJECT_FAILURES", "int", 2,
         "consecutive probe/request failures before a replica is "
         "ejected from the rotation", "router"),
    Knob("POLYAXON_TPU_ROUTER_EJECT_BACKOFF_S", "float", 1.0,
         "first re-admission probe delay after ejection (s); doubles "
         "per consecutive failed re-admission", "router"),
    Knob("POLYAXON_TPU_ROUTER_EJECT_BACKOFF_MAX_S", "float", 30.0,
         "re-admission backoff cap (s)", "router"),
    Knob("POLYAXON_TPU_ROUTER_AFFINITY_TOKENS", "int", 16,
         "prompt-prefix length hashed for replica affinity (0 = no "
         "affinity, pure least-loaded)", "router"),
    Knob("POLYAXON_TPU_ROUTER_AFFINITY_SLACK", "float", 0.25,
         "base load excess (affine minus least-loaded, per-slot) the "
         "affine replica may carry before affinity yields", "router"),
    Knob("POLYAXON_TPU_ROUTER_AFFINITY_HIT_SLACK", "float", 0.75,
         "extra affinity slack earned per unit of the affine replica's "
         "prefix_cache_hit_rate (warm caches justify routing into a "
         "busier replica)", "router"),
    # -- serving fleet (replica gang lifecycle) ----------------------------
    Knob("POLYAXON_TPU_FLEET_REPLICAS", "int", 2,
         "default replica count for a serving fleet", "fleet"),
    Knob("POLYAXON_TPU_FLEET_DRAIN_DEADLINE_S", "float", 30.0,
         "max time a draining replica may hold in-flight requests "
         "before it is replaced anyway", "fleet"),
    Knob("POLYAXON_TPU_FLEET_READY_TIMEOUT_S", "float", 120.0,
         "how long a replacement replica may take to reach ready "
         "before the drain/replace action fails", "fleet"),
    # -- fleet autoscaler (shed/occupancy-driven N resizing) ---------------
    Knob("POLYAXON_TPU_AUTOSCALER_ENABLED", "bool", True,
         "fleet autoscaler master switch (an attached autoscaler still "
         "tracks signals when off, but never resizes)", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_SHED_RATE", "float", 0.05,
         "windowed shed fraction (sheds/requests per tick) at/above "
         "which sustained overload triggers scale-up", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_IDLE_OCCUPANCY", "float", 0.1,
         "fleet-mean occupancy floor; sustained occupancy below it "
         "(with zero sheds) triggers drain-down", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_MIN_REPLICAS", "int", 1,
         "scale-down floor — the fleet never drains below this",
         "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_MAX_REPLICAS", "int", 4,
         "scale-up ceiling", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_UP_HOLD_S", "float", 5.0,
         "hysteresis: the shed signal must hold this long before a "
         "scale-up fires", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_DOWN_HOLD_S", "float", 30.0,
         "hysteresis: the idle signal must hold this long before a "
         "drain-down fires", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_UP_COOLDOWN_S", "float", 15.0,
         "min spacing between scale-up decisions", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_DOWN_COOLDOWN_S", "float", 60.0,
         "min spacing between scale-down decisions; a completed "
         "scale-UP also re-arms it (flap suppression)", "autoscaler"),
    Knob("POLYAXON_TPU_AUTOSCALER_BUDGET", "int", 0,
         "hard cap on autoscaler decisions per fleet (0 = inherit "
         "POLYAXON_TPU_REMEDIATION_BUDGET)", "autoscaler"),
    # -- worker / monitoring ------------------------------------------------
    Knob("POLYAXON_TPU_RESOURCE_INTERVAL", "float", 10.0,
         "host/device resource sampler cadence (s)", "worker"),
    # -- control-plane self-telemetry --------------------------------------
    Knob("POLYAXON_TPU_METRICS_MAX_SERIES", "int", 1024,
         "per-metric cap on distinct label sets in MemoryStats; overflow "
         "folds into one {...=\"other\"} series (+ one warning)",
         "cp-telemetry"),
    Knob("POLYAXON_TPU_RETENTION_SWEEP_ROWS", "int", 20000,
         "per-tick row budget for the registry retention sweep (one "
         "transaction per tick; leftovers age out on later ticks)",
         "cp-telemetry"),
    Knob("POLYAXON_TPU_WS_TAIL_MAX_BATCH", "int", 500,
         "max rows a WS tail sends per poll; the remainder is deferred "
         "to the next poll and exported as ws_tail_backlog_rows",
         "cp-telemetry"),
    # -- metric history (in-process TSDB + scrape phase) -------------------
    Knob("POLYAXON_TPU_TSDB_ENABLED", "bool", True,
         "metric-history master switch: the monitor tick's scrape phase, "
         "the registry metric_samples write-behind, and the query API",
         "tsdb"),
    Knob("POLYAXON_TPU_TSDB_SCRAPE_INTERVAL_S", "float", 5.0,
         "scrape cadence (s) — the phase runs every monitor tick but "
         "only samples when due, so tick rate doesn't multiply cost",
         "tsdb"),
    Knob("POLYAXON_TPU_TSDB_RAW_POINTS", "int", 720,
         "raw ring length per series (at the default 5s cadence: 1h)",
         "tsdb"),
    Knob("POLYAXON_TPU_TSDB_ROLLUP_POINTS", "int", 360,
         "rollup ring length per series per stage (10s stage: 1h; "
         "1m stage: 6h of min/max/sum/count buckets)", "tsdb"),
    Knob("POLYAXON_TPU_TSDB_MAX_SERIES", "int", 2048,
         "per-base-name cap on distinct label sets in the MetricStore; "
         "overflow folds into one {...=\"other\"} series", "tsdb"),
    Knob("POLYAXON_TPU_TSDB_FLUSH_ROWS", "int", 512,
         "max metric_samples rows flushed to the registry per scrape "
         "(write-behind batch size)", "tsdb"),
    Knob("POLYAXON_TPU_TSDB_PENDING_MAX", "int", 8192,
         "bound on samples queued for the registry flush; overflow "
         "drops the oldest (in-memory history is unaffected)", "tsdb"),
    Knob("POLYAXON_TPU_TSDB_QUERY_MAX_POINTS", "int", 2000,
         "max points one /api/v1/metrics/query response returns "
         "(the newest win)", "tsdb"),
    Knob("POLYAXON_TPU_BASELINE_ALPHA", "float", 0.3,
         "EWMA weight for folding a completed run's summary series into "
         "its (project, kind) regression baseline", "tsdb"),
    # -- control plane / CLI ------------------------------------------------
    Knob("POLYAXON_TPU_HOME", "str", "~/.polyaxon_tpu",
         "platform state dir for the local CLI and tooling state",
         "control-plane"),
    Knob("POLYAXON_TPU_AUTH_TOKEN", "str", "",
         "API bearer token ('' = auth off locally)", "control-plane"),
    Knob("POLYAXON_TPU_SECRET_KEY", "str", "",
         "Fernet key for secret-option encryption at rest ('' = "
         "per-deployment keyfile)", "control-plane"),
    Knob("POLYAXON_TPU_WEBHOOK_URL", "str", "",
         "legacy env fallback for the notifier.webhook_url option",
         "control-plane"),
    Knob("POLYAXON_TPU_WEBHOOK_KIND", "str", "",
         "legacy env fallback for the notifier.webhook_kind option",
         "control-plane"),
    # -- static analysis (graft-lint) --------------------------------------
    Knob("POLYAXON_TPU_LINT_STATE", "str", "",
         "graft-lint state-file path override ('' = "
         "<POLYAXON_TPU_HOME>/analysis/last_run.json)", "analysis"),
    Knob("POLYAXON_TPU_LINT_STALE_S", "float", 7 * 86400.0,
         "age after which the /status probe calls the last graft-lint "
         "run stale", "analysis"),
    # -- option-store root prefix ------------------------------------------
    # conf/options.py builds option env vars as POLYAXON_TPU_ + the
    # dotted option key; the bare prefix is a declared family so GL005
    # can account for the builder's literal.
    Knob("POLYAXON_TPU_", "str", None,
         "root prefix family: cluster options resolve env overrides as "
         "POLYAXON_TPU_<OPTION_KEY> (see conf/options.py)", "options",
         prefix=True),
]

KNOBS: Dict[str, Knob] = {k.name: k for k in _ALL}
FAMILIES: Dict[str, Knob] = {k.name: k for k in _ALL if k.prefix}


def _knob(name: str) -> Knob:
    try:
        knob = KNOBS[name]
    except KeyError:
        raise KeyError(
            f"Unknown knob {name!r} — declare it in conf/knobs.py "
            "(graft-lint GL005 enforces the catalog)"
        ) from None
    if knob.prefix:
        raise KeyError(
            f"{name!r} is a prefix family — read it through the "
            "family_* accessors"
        )
    return knob


def knob_default(name: str) -> Any:
    """The catalog default for ``name`` (single source of truth for
    call sites that also expose the value as a module constant)."""
    return _knob(name).default


def knob_str(name: str, default: Optional[str] = None) -> str:
    knob = _knob(name)
    raw = os.environ.get(name)
    if raw is None:
        return default if default is not None else knob.default
    return raw


def knob_bool(name: str, default: Optional[bool] = None) -> bool:
    knob = _knob(name)
    raw = os.environ.get(name)
    if raw is None:
        return default if default is not None else bool(knob.default)
    return raw.strip().lower() not in _FALSY


def knob_int(name: str, default: Optional[int] = None) -> int:
    knob = _knob(name)
    fallback = default if default is not None else knob.default
    raw = os.environ.get(name)
    if raw is None:
        return fallback
    try:
        return int(float(raw))
    except (TypeError, ValueError):
        return fallback


def knob_float(name: str, default: Optional[float] = None) -> float:
    knob = _knob(name)
    fallback = default if default is not None else knob.default
    raw = os.environ.get(name)
    if raw is None:
        return fallback
    try:
        return float(raw)
    except (TypeError, ValueError):
        return fallback


# -- prefix families ---------------------------------------------------------

def family_prefix(prefix: str) -> str:
    """Validate ``prefix`` is a declared family and return it (call
    sites build dynamic names as ``family_prefix(P) + suffix``)."""
    if prefix not in FAMILIES:
        raise KeyError(
            f"Unknown knob family {prefix!r} — declare it (prefix=True) "
            "in conf/knobs.py"
        )
    return prefix


def family_value(prefix: str, suffix: str) -> Optional[str]:
    """Raw env read of a dynamic family member (None when unset)."""
    return os.environ.get(family_prefix(prefix) + suffix)


def family_float(prefix: str, suffix: str, default: float) -> float:
    raw = family_value(prefix, suffix)
    if raw is None:
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


# -- documentation -----------------------------------------------------------

def reference_table() -> str:
    """The catalog as a grouped markdown table (the knob reference in
    ``docs/observability.md`` is generated from this)."""
    lines = [
        "| Knob | Type | Default | What it does |",
        "| --- | --- | --- | --- |",
    ]
    for knob in _ALL:
        name = f"`{knob.name}<...>`" if knob.prefix else f"`{knob.name}`"
        default = "—" if knob.default is None else f"`{knob.default}`"
        kind = f"{knob.kind} family" if knob.prefix else knob.kind
        lines.append(f"| {name} | {kind} | {default} | {knob.doc} |")
    return "\n".join(lines)
