"""Secret-option encryption at rest.

Parity: reference ``encryptor/`` (its ``polyaxon/encryptor`` app wrapped
values with a Fernet token under a settings key).  Here: options declared
``secret=True`` are Fernet-encrypted before they land in the sqlite
options table, so a copied registry file (or a backup of it) does not leak
credentials.  Secrets were already write-only over every API/CLI surface;
this closes the at-rest gap.

Key resolution order:

1. ``POLYAXON_TPU_SECRET_KEY`` env var (a Fernet key — urlsafe base64);
2. ``<base_dir>/.secret_key``, generated on first use with mode 0600.

Stored values carry an ``enc:v1:`` prefix; values without it (written
before this module existed) read back as-is, so enabling encryption never
bricks an existing deployment — the next write upgrades them.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional, Union

from polyaxon_tpu.conf.knobs import knob_str
from polyaxon_tpu.exceptions import PolyaxonTPUError

_PREFIX = "enc:v1:"
_KEY_ENV = "POLYAXON_TPU_SECRET_KEY"
_KEY_FILE = ".secret_key"


class EncryptionError(PolyaxonTPUError):
    pass


class Encryptor:
    def __init__(self, key: bytes) -> None:
        from cryptography.fernet import Fernet

        try:
            self._fernet = Fernet(key)
        except (ValueError, TypeError) as e:
            raise EncryptionError(f"Invalid secret key: {e}") from e

    @classmethod
    def from_base_dir(cls, base_dir: Union[str, Path]) -> "Encryptor":
        """Env key wins; otherwise a per-deployment keyfile (created 0600)."""
        env = knob_str(_KEY_ENV)
        if env:
            return cls(env.encode())
        from cryptography.fernet import Fernet

        path = Path(base_dir) / _KEY_FILE
        if path.exists():
            return cls(path.read_bytes().strip())
        path.parent.mkdir(parents=True, exist_ok=True)
        key = Fernet.generate_key()
        # Write-then-link: the key is FULLY written to a private temp file
        # before it becomes visible at the final name, so a process racing
        # first use (server + CLI over a shared base dir) either wins the
        # link or reads a complete key — never a partial/empty one.
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".secret_key.")
        try:
            os.write(fd, key)
            os.fchmod(fd, 0o600)
            os.close(fd)
            try:
                os.link(tmp, path)
            except FileExistsError:
                return cls(path.read_bytes().strip())
            return cls(key)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def encrypt(self, value: str) -> str:
        return _PREFIX + self._fernet.encrypt(str(value).encode()).decode()

    def decrypt(self, stored: Optional[str]) -> Optional[str]:
        """Decrypt an ``enc:v1:`` value; legacy plaintext passes through."""
        if stored is None or not isinstance(stored, str):
            return stored
        if not stored.startswith(_PREFIX):
            return stored
        from cryptography.fernet import InvalidToken

        try:
            return self._fernet.decrypt(stored[len(_PREFIX):].encode()).decode()
        except InvalidToken as e:
            # Loud by design: a wrong key silently yielding None would look
            # like "option unset" and e.g. disable SMTP auth.
            raise EncryptionError(
                "Cannot decrypt stored secret (wrong POLYAXON_TPU_SECRET_KEY "
                "or .secret_key?)"
            ) from e

    @staticmethod
    def is_encrypted(stored: Optional[str]) -> bool:
        return isinstance(stored, str) and stored.startswith(_PREFIX)
