"""Layered config resolution with a TTL cache.

Parity: reference ``conf/service.py:6-18`` + ``conf/handlers/`` — options
resolve through their store order (DB option table → env var → default),
DB writes take effect cluster-wide at runtime, reads are cached with a TTL.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, Optional, Tuple

from polyaxon_tpu.conf.options import Option, OptionStores, option_by_key
from polyaxon_tpu.exceptions import PolyaxonTPUError

logger = logging.getLogger(__name__)


class ConfError(PolyaxonTPUError):
    pass


class ConfService:
    def __init__(
        self, registry=None, cache_ttl: float = 60.0, encryptor=None
    ) -> None:
        #: RunRegistry (for the DB store) — optional so schema-only tools
        #: can resolve env/default options without a database.
        self.registry = registry
        self.cache_ttl = cache_ttl
        #: conf.encryptor.Encryptor — secret=True options encrypt at rest
        #: (reference ``encryptor/``); None = store/read plaintext (tests,
        #: schema-only tools).
        self.encryptor = encryptor
        self._cache: Dict[str, Tuple[float, Any]] = {}

    def _option(self, key: str) -> Option:
        opt = option_by_key(key)
        if opt is None:
            raise ConfError(f"Unknown option {key!r}")
        return opt

    def get(self, key: str) -> Any:
        hit = self._cache.get(key)
        if hit is not None and time.time() - hit[0] < self.cache_ttl:
            return hit[1]
        opt = self._option(key)
        value: Any = None
        for store in opt.stores:
            raw = None
            if store == OptionStores.DB and self.registry is not None:
                raw = self.registry.get_option(opt.key)
                if opt.secret and self.encryptor is not None:
                    raw = self.encryptor.decrypt(raw)
            elif store == OptionStores.ENV:
                raw = os.environ.get(opt.env_var)
            elif store == OptionStores.DEFAULT:
                value = opt.default
                break
            if raw is not None:
                try:
                    value = opt.coerce(raw)
                except (TypeError, ValueError) as e:
                    # A stale/invalid stored value (pre-validation DB row,
                    # typo'd env var) must not brick startup or the options
                    # listing — reads fall through to the next store; only
                    # WRITES (set()) reject invalid values loudly.
                    logger.warning(
                        "Ignoring invalid %s value for %s: %s", store, key, e
                    )
                    continue
                break
        self._cache[key] = (time.time(), value)
        return value

    def set(self, key: str, value: Any) -> None:
        """Write to the DB store (runtime-mutable, like the reference's
        cluster options)."""
        opt = self._option(key)
        if self.registry is None:
            raise ConfError("No registry attached; cannot persist options")
        value = opt.coerce(value)
        if opt.secret and self.encryptor is not None and value:
            value = self.encryptor.encrypt(str(value))
        self.registry.set_option(opt.key, value)
        self._cache.pop(key, None)

    def unset(self, key: str) -> None:
        opt = self._option(key)
        if self.registry is not None:
            self.registry.delete_option(opt.key)
        self._cache.pop(key, None)

    def invalidate(self) -> None:
        self._cache.clear()
