from polyaxon_tpu.conf.options import Option, OptionStores, OPTIONS, option_by_key
from polyaxon_tpu.conf.service import ConfService

__all__ = ["ConfService", "Option", "OptionStores", "OPTIONS", "option_by_key"]
