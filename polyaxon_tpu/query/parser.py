"""Search/filter query DSL parser.

Parity: reference ``query/parser.py`` + condition types
(``query/builder.py:18-31``) — the same user-facing grammar:

- comma-separated conditions: ``status:running, metric.loss:<0.5``
- value-in: ``status:running|starting``
- negation: ``status:~failed``
- comparison: ``metric.acc:>0.9``, ``created_at:>=2020-01-01``
- range: ``id:1..10``
- nested fields: ``metric.<name>``, ``declarations.<name>`` (JSON payloads)
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from datetime import datetime
from typing import Any, List, Optional, Tuple

from polyaxon_tpu.exceptions import PolyaxonTPUError


class QueryError(PolyaxonTPUError):
    pass


#: op ∈ {"eq", "in", "gt", "gte", "lt", "lte", "range"}
@dataclass(frozen=True)
class Condition:
    field: str
    op: str
    value: Any
    negated: bool = False


_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}([T ].+)?$")


def _coerce(raw: str) -> Any:
    raw = raw.strip()
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    lowered = raw.lower()
    if lowered in ("true", "false"):
        return lowered == "true"
    if _DATE_RE.match(raw):
        # Date comparisons target epoch-float columns (created_at, ...).
        try:
            return datetime.fromisoformat(raw).timestamp()
        except ValueError:
            pass
    return raw


def _parse_value(field: str, raw: str) -> Tuple[str, Any]:
    raw = raw.strip()
    if not raw:
        raise QueryError(f"Empty value for field {field!r}")
    if ".." in raw:
        lo, hi = raw.split("..", 1)
        return "range", (_coerce(lo), _coerce(hi))
    for prefix, op in (
        (">=", "gte"),
        ("<=", "lte"),
        (">", "gt"),
        ("<", "lt"),
    ):
        if raw.startswith(prefix):
            return op, _coerce(raw[len(prefix):])
    if "|" in raw:
        return "in", [_coerce(v) for v in raw.split("|") if v.strip()]
    return "eq", _coerce(raw)


def parse_query(query: Optional[str]) -> List[Condition]:
    """``"a:1, b:~x|y"`` → conditions. Empty/None → no conditions."""
    if not query or not query.strip():
        return []
    conditions = []
    for part in query.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise QueryError(f"Condition {part!r} is not of the form field:value")
        field, raw = part.split(":", 1)
        field = field.strip()
        raw = raw.strip()
        negated = raw.startswith("~")
        if negated:
            raw = raw[1:]
        op, value = _parse_value(field, raw)
        conditions.append(Condition(field=field, op=op, value=value, negated=negated))
    return conditions
