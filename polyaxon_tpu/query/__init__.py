from polyaxon_tpu.query.parser import Condition, QueryError, parse_query
from polyaxon_tpu.query.builder import apply_query

__all__ = ["Condition", "QueryError", "apply_query", "parse_query"]
