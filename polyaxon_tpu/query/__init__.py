from polyaxon_tpu.query.builder import apply_query, compile_to_sql, filters_archived
from polyaxon_tpu.query.parser import Condition, QueryError, parse_query

__all__ = ["Condition", "QueryError", "apply_query", "compile_to_sql", "filters_archived", "parse_query"]
