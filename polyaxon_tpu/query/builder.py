"""Apply parsed query conditions to registry runs.

Parity: reference ``QueryBuilder.build`` (``query/builder.py:18-31``) and
the per-entity query managers — there conditions compile to Django ORM
filters; here the registry's polymorphic run rows (with JSON
``last_metric``/``declarations``/``tags`` payloads) are filtered in
process, which keeps one code path for plain columns and JSON fields.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence

from polyaxon_tpu.db.registry import Run
from polyaxon_tpu.query.parser import Condition, QueryError, parse_query

#: plain run attributes addressable in queries
_FIELDS = {
    "id", "uuid", "kind", "name", "project", "status", "group_id",
    "pipeline_id", "original_id", "restarts", "created_at", "started_at",
    "finished_at",
}


def _validate_archived(cond: Condition) -> None:
    """`archived:` is a derived boolean; both query paths (SQL pushdown
    and in-process) must reject the same malformed shapes — a condition
    that 400s on one surface must not silently 'work' on another."""
    if cond.op != "eq" or not isinstance(cond.value, bool):
        raise QueryError("archived expects true or false")


def _resolve(run: Run, field: str) -> Any:
    if field == "archived":
        # Derived boolean over archived_at — `archived:true` surfaces the
        # reference's archived-manager split inside the query DSL.
        return run.archived_at is not None
    if field in _FIELDS:
        return getattr(run, field)
    if field.startswith("metric."):
        return run.last_metric.get(field.split(".", 1)[1])
    if field.startswith("declarations.") or field.startswith("params."):
        return run.spec_data.get("declarations", {}).get(field.split(".", 1)[1])
    if field == "tags":
        return run.tags
    raise QueryError(
        f"Unknown query field {field!r} (plain fields: "
        f"{sorted(_FIELDS) + ['archived']}; JSON fields: metric.<name>, "
        "declarations.<name>, tags)"
    )


def _matches(run: Run, cond: Condition) -> bool:
    actual = _resolve(run, cond.field)
    if cond.field == "tags":
        values = cond.value if isinstance(cond.value, list) else [cond.value]
        result = any(v in (actual or []) for v in values)
    elif actual is None:
        result = False
    elif cond.op == "eq":
        result = actual == cond.value
    elif cond.op == "in":
        result = actual in cond.value
    elif cond.op == "range":
        lo, hi = cond.value
        try:
            result = lo <= actual <= hi
        except TypeError:
            result = False
    else:
        try:
            result = {
                "gt": actual > cond.value,
                "gte": actual >= cond.value,
                "lt": actual < cond.value,
                "lte": actual <= cond.value,
            }[cond.op]
        except TypeError:
            result = False
    return not result if cond.negated else result


def filters_archived(conditions: Sequence[Condition]) -> bool:
    """Does this query take over the archived dimension?  Listing
    surfaces default to live-only (``list_runs(archived=False)``); a
    query filtering on ``archived:`` must see BOTH populations or its
    clause contradicts the default and silently returns nothing."""
    return any(c.field == "archived" for c in conditions)


def apply_query(
    runs: Iterable[Run], query: Optional[str] = None, conditions: Optional[Sequence[Condition]] = None
) -> List[Run]:
    """Filter runs by a query string (AND of all its conditions)."""
    conds = list(conditions or []) or parse_query(query)
    # Validate ONCE up front, not per-run: a malformed condition must
    # error identically on an empty result set and a full one (and match
    # compile_to_sql's validation exactly).
    for c in conds:
        if c.field == "archived":
            _validate_archived(c)
    return [r for r in runs if all(_matches(r, c) for c in conds)]


def compile_to_sql(
    conditions: Sequence[Condition],
) -> tuple:
    """Split conditions into (sql_clauses, params, residual_conditions).

    Conditions on real ``runs`` columns compile to WHERE fragments (the
    reference's queryset pushdown); JSON-payload fields (``metric.*``,
    ``declarations.*``, ``tags``) stay residual for the in-process filter.
    NULL handling mirrors the Python semantics exactly: a NULL column never
    matches a positive condition and always matches a negated one.
    """
    clauses: List[str] = []
    params: List[Any] = []
    residual: List[Condition] = []
    for cond in conditions:
        if cond.field == "archived":
            # Derived boolean: pushes down as a NULL check on archived_at.
            _validate_archived(cond)
            want = cond.value != cond.negated
            clauses.append(
                "archived_at IS NOT NULL" if want else "archived_at IS NULL"
            )
            continue
        if cond.field not in _FIELDS:
            if not (
                cond.field.startswith(("metric.", "declarations.", "params."))
                or cond.field == "tags"
            ):
                # Same validation the in-process path gives — unknown
                # fields must 400, not silently match everything.
                raise QueryError(
                    f"Unknown query field {cond.field!r} (plain fields: "
                    f"{sorted(_FIELDS) + ['archived']}; JSON fields: "
                    "metric.<name>, declarations.<name>, tags)"
                )
            residual.append(cond)
            continue
        col = cond.field  # _FIELDS is a fixed allowlist — never user text
        if cond.op == "eq":
            frag, ps = f"{col} = ?", [cond.value]
        elif cond.op == "in":
            frag = f"{col} IN ({','.join('?' * len(cond.value))})"
            ps = list(cond.value)
        elif cond.op == "range":
            frag, ps = f"{col} BETWEEN ? AND ?", list(cond.value)
        elif cond.op in ("gt", "gte", "lt", "lte"):
            sym = {"gt": ">", "gte": ">=", "lt": "<", "lte": "<="}[cond.op]
            frag, ps = f"{col} {sym} ?", [cond.value]
        else:  # pragma: no cover - parser emits only the ops above
            residual.append(cond)
            continue
        if cond.negated:
            frag = f"(NOT ({frag}) OR {col} IS NULL)"
        clauses.append(frag)
        params.extend(ps)
    return clauses, params, residual
