"""Per-project CI: new code snapshot → run the project's CI spec.

Parity: the reference CI app — per-project toggle (``api/ci/views.py``),
code-ref sync + trigger (``ci/service.py:15-117``), fired from its
repo-upload views (``api/repos/views.py:162``).  TPU-native framing: the
repo/commit machinery collapses into the content-addressed snapshot store
(``stores/snapshots.py``) — a snapshot hash IS a commit, so CI fires
whenever a project sees a hash it hasn't run yet, from either source:

- automatically, when any non-CI run's build step snapshots new code
  (``scheduler/tasks.py::_maybe_trigger_ci``);
- explicitly, via ``POST /projects/{name}/ci/trigger`` / ``ptpu ci
  trigger`` with a context directory (the push-equivalent for local mode).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from polyaxon_tpu.events import EventTypes, created_event_for_kind
from polyaxon_tpu.schemas import PolyaxonFile
from polyaxon_tpu.schemas.specifications import BaseSpecification

logger = logging.getLogger(__name__)


def submit_ci_run(
    registry,
    auditor,
    project: str,
    ci_spec: "Dict[str, Any] | BaseSpecification",
    code_ref: str,
    actor: Optional[str] = None,
):
    """Create the CI run for ``code_ref`` and announce it (the executor
    chains build→start off the created event).  The run reuses the
    triggering snapshot — same code hash, no second build walk.  Callers
    must already have won ``advance_ci_code_ref``'s check-and-set.
    ``ci_spec`` may arrive pre-parsed (manual trigger already validated
    it to read the build section) or as the stored dict."""
    spec = (
        ci_spec
        if isinstance(ci_spec, BaseSpecification)
        else PolyaxonFile.load(ci_spec).specification
    )
    run = registry.create_run(
        spec,
        project=project,
        name=f"ci-{code_ref[:12]}",
        tags=["ci"],
    )
    registry.update_run(run.id, code_ref=code_ref)
    event_type, key = created_event_for_kind(run.kind)
    extra = {"actor": actor} if actor else {}
    auditor.record(event_type, **{key: run.id}, code_ref=code_ref, **extra)
    auditor.record(
        EventTypes.CI_TRIGGERED,
        project=project,
        run_id=run.id,
        code_ref=code_ref,
        **extra,
    )
    logger.info("CI: code %s in %s -> run %s", code_ref[:12], project, run.id)
    return registry.get_run(run.id)
