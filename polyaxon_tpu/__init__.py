"""polyaxon-tpu: a TPU-native deep-learning experimentation platform.

A ground-up re-design of the capability set of Polyaxon (reference:
``/root/reference``, v0.5.6 — a Kubernetes/Django/Celery control plane) as a
TPU-first framework:

- declarative experiment specs compile to *sharding plans* (``jax.sharding.Mesh``
  + ``PartitionSpec`` templates: DP/FSDP/TP/PP/SP-ring/Ulysses/EP) instead of
  TF_CONFIG / mpirun / DMLC env recipes (reference ``polyaxon/polypod/``),
- a single-process asyncio control plane with a durable sqlite run registry
  replaces Django + Postgres + Redis + RabbitMQ + Celery,
- the gang spawner launches ``jax.distributed`` process gangs on TPU-VM slices
  (local-subprocess backend for dev/test) instead of Kubernetes pods,
- hyperparameter search (grid/random/hyperband/Bayesian) is a first-class
  subsystem (reference ``polyaxon/hpsearch/``), gang-aware over TPU slices,
- the runtime layer (checkpointing via orbax, per-step profiling, ring
  attention for long context, MoE expert parallelism) is new: the reference
  delegated all compute to user containers.
"""

from polyaxon_tpu.version import __version__  # noqa: F401
