"""Sharded checkpoint/restore for training state.

The reference manages only *outputs directories* and delegates model
checkpointing to user frameworks (SURVEY §5: TF ``model_dir`` pointed at
the outputs path via TF_CONFIG, ``polypod/tensorflow.py:197-200``).  Here
checkpointing is first-class: orbax-backed, sharding-aware (each host
writes its shards, restore honors the target mesh), integrated with the
run layout's ``checkpoints/`` dir — which the clone strategies
(resume/copy) duplicate, so a resumed run restores step + optimizer state
automatically.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Union

#: Dot-named so orbax's digit-dir step scan never mistakes it for a step.
_COMPLETE_DIR = ".complete"


#: Shared jitted identity for :func:`_fresh_leaf` — one function object
#: so each (shape, dtype, sharding) compiles once per process.
_detach_jit = None


def _fresh_leaf(restored_leaf: Any, sharding: Any) -> Any:
    """Re-place a restored leaf onto ``sharding`` as XLA-owned buffers.

    Two hazards in one pass.  Placement: orbax restores scalar leaves
    onto the default device, poisoning the jitted step with mixed device
    sets.  Provenance: buffers staged by the restore (or zero-copied from
    host memory by ``device_put``) must never be *donated* into an
    executable deserialized from the persistent XLA compile cache — the
    CPU client's inflight-computation semaphore underflows
    (``xla/pjrt/semaphore.cc`` check failure, heap corruption).  A jitted
    identity detaches the leaf: without donation XLA cannot alias input
    to output, so the result is a freshly-allocated buffer the runtime
    owns, safe to donate.
    """
    global _detach_jit
    import jax

    placed = restored_leaf
    if not isinstance(placed, jax.Array) or placed.sharding != sharding:
        placed = jax.device_put(placed, sharding)
    if _detach_jit is None:
        _detach_jit = jax.jit(lambda x: x)
    return _detach_jit(placed)


def latest_complete_step(directory: Union[str, Path]) -> Optional[int]:
    """Latest step with a finalize marker — pure filesystem, no orbax/jax
    import, so the control plane can answer "where can this run resume
    from" without touching the accelerator runtime.

    Checkpoint dirs written before finalize markers existed (no
    ``.complete/``) fall back to trusting the digit-named step dirs, the
    pre-marker behavior.
    """
    directory = Path(directory)
    if not directory.is_dir():
        return None
    steps = {int(p.name) for p in directory.iterdir() if p.name.isdigit()}
    if not steps:
        return None
    marks_dir = directory / _COMPLETE_DIR
    if marks_dir.is_dir():
        marked = {int(p.name) for p in marks_dir.iterdir() if p.name.isdigit()}
        steps &= marked
    return max(steps) if steps else None


class CheckpointManager:
    """Thin, typed wrapper over ``orbax.checkpoint.CheckpointManager``.

    Saves are ASYNC by default (``enable_async=True``): :meth:`save`
    returns once the device→host copy is staged — safe against the train
    step's donated buffers — and serialization to disk overlaps the steps
    that follow.  The fences are explicit and all inside this class:
    every restore path waits for in-flight saves first (a restore issued
    right after a save must see that step), and :meth:`close` drains
    before shutdown so no checkpoint is ever torn.  Orbax sequences
    eviction (``max_to_keep``) behind the in-flight save internally.

    :attr:`save_block_s` accumulates the wall seconds :meth:`save` blocked
    the caller — the hot loop's ``ckpt_block_s``.  With async on, that's
    the staging copy plus any wait for a still-running previous save; with
    async off it's the full serialization.
    """

    def __init__(
        self,
        directory: Union[str, Path],
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        enable_async: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        # Eager, so a crash before the first marker leaves an EMPTY marker
        # dir (torn step dirs rejected) rather than no dir (legacy-trust).
        (self.directory / _COMPLETE_DIR).mkdir(exist_ok=True)
        self.save_block_s = 0.0
        self.saves = 0
        #: Steps this process staged whose finalize marker isn't written
        #: yet.  Markers are only ever written for steps saved BY THIS
        #: process — a fresh process must never bless a torn step dir a
        #: crashed predecessor left behind.
        self._pending_marks: Set[int] = set()
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
                enable_async_checkpointing=enable_async,
            ),
        )

    def save(
        self,
        step: int,
        params: Any,
        opt_state: Any,
        force: bool = False,
    ) -> bool:
        """Save training state at ``step``; returns whether a save happened.

        Params and optimizer state are separate orbax ITEMS so inference
        consumers (``lm_generate``) can restore weights without knowing —
        or paying the memory/IO for — the training optimizer.
        """
        import orbax.checkpoint as ocp

        t0 = time.perf_counter()
        saved = self._mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
            force=force,
        )
        self.save_block_s += time.perf_counter() - t0
        if saved:
            # Earlier async saves have committed by now (orbax sequences a
            # new save behind the in-flight one), so their markers can be
            # written without blocking; ``step`` itself stays pending.
            self._mark_committed(exclude=step)
            self._pending_marks.add(step)
            self.saves += 1
        return saved

    def _write_marker(self, step: int) -> None:
        """Atomic finalize marker: a committed save is only trusted by
        restore once this rename lands (tmp+rename, so a crash leaves
        either a valid marker or none — never a torn one)."""
        marks = self.directory / _COMPLETE_DIR
        marks.mkdir(exist_ok=True)
        tmp = marks / f".tmp.{step}"
        tmp.write_text("")
        tmp.rename(marks / str(step))

    def _mark_committed(self, exclude: Optional[int] = None) -> None:
        if not self._pending_marks:
            return
        # orbax's all_steps() only lists FINALIZED step dirs (in-flight
        # saves live under tmp names), so membership proves commit.
        committed = set(self._mgr.all_steps())
        for s in sorted(self._pending_marks):
            if s == exclude or s not in committed:
                continue
            self._write_marker(s)
            self._pending_marks.discard(s)

    def _fence(self) -> None:
        """Drain in-flight saves, then finalize their markers and GC
        markers whose step dirs ``max_to_keep`` pruned away."""
        self._mgr.wait_until_finished()
        self._mark_committed()
        marks = self.directory / _COMPLETE_DIR
        if marks.is_dir():
            committed = set(self._mgr.all_steps())
            for p in marks.iterdir():
                if p.name.isdigit() and int(p.name) not in committed:
                    p.unlink(missing_ok=True)

    def _complete_steps(self) -> List[int]:
        committed = set(self._mgr.all_steps())
        marks = self.directory / _COMPLETE_DIR
        if not marks.is_dir():
            # Legacy (pre-marker) checkpoint dir: trust orbax's view.
            return sorted(committed)
        marked = {int(p.name) for p in marks.iterdir() if p.name.isdigit()}
        return sorted(committed & marked)

    def latest_step(self) -> Optional[int]:
        # Fence: an in-flight async save's step must be visible to whoever
        # asks "where are we" (restore-after-save ordering) — and only
        # steps with a finalize marker count: a torn dir left by a crashed
        # process must never answer.
        self._fence()
        steps = self._complete_steps()
        return steps[-1] if steps else None

    def restore_params(
        self, params_template: Any, step: Optional[int] = None
    ) -> Optional[Dict[str, Any]]:
        """Restore ONLY the model weights (inference path): no optimizer
        template needed, no optimizer IO paid.  Raises ``ValueError`` for
        pre-round-4 single-item checkpoints (callers may fall back to
        :meth:`restore` with an optimizer template for those)."""
        import orbax.checkpoint as ocp

        self._fence()  # fence against in-flight saves
        step = step if step is not None else self._latest_complete()
        if step is None:
            return None
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(params_template)
            ),
        )
        import jax

        params = jax.tree.map(
            lambda t, r: _fresh_leaf(r, t.sharding)
            if hasattr(t, "sharding")
            else r,
            params_template,
            restored["params"],
        )
        return {"params": params, "step": step}

    def restore(
        self,
        params_template: Any,
        opt_state_template: Any,
        step: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """Restore onto the templates' shardings; None if no checkpoint.

        Templates are the freshly-initialized (sharded) state — orbax
        restores each leaf with the template's sharding, so a checkpoint
        written under one mesh restores correctly onto another.  Reads
        both the composite (round-4+) and legacy single-item layouts.
        """
        import orbax.checkpoint as ocp

        self._fence()  # fence against in-flight saves
        step = step if step is not None else self._latest_complete()
        if step is None:
            return None
        target = {"params": params_template, "opt_state": opt_state_template}
        try:
            composite = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(params_template),
                    opt_state=ocp.args.StandardRestore(opt_state_template),
                ),
            )
            restored = {
                "params": composite["params"],
                "opt_state": composite["opt_state"],
            }
        except (ValueError, KeyError, TypeError):
            # Legacy layout: one StandardSave dict holding both halves.
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(target)
            )
        # Re-place every leaf onto its template's sharding as FRESH
        # buffers: orbax restores scalar leaves (e.g. optax's step count)
        # onto the default device, which poisons the jitted step with
        # mixed device sets on a mesh — and its tensorstore-staged
        # buffers must never be donated directly (see _fresh_leaf).
        import jax

        def _place(template_leaf, restored_leaf):
            if hasattr(template_leaf, "sharding"):
                return _fresh_leaf(restored_leaf, template_leaf.sharding)
            return restored_leaf

        restored = jax.tree.map(_place, target, restored)
        restored["step"] = step
        return restored

    def _latest_complete(self) -> Optional[int]:
        steps = self._complete_steps()
        return steps[-1] if steps else None

    def wait_until_finished(self) -> None:
        """Block until every async save has committed to disk — and its
        finalize marker is durable (a caller who fenced may rely on the
        fenced step surviving a crash)."""
        self._fence()

    def close(self) -> None:
        # Shutdown fence: close() must never truncate an in-flight save.
        self._fence()
        self._mgr.close()


class CheckpointNowService:
    """Worker-side ``checkpoint-now`` command handler: the bridge between
    the command bus (reporter heartbeat thread) and the train loop.

    The bus handler only QUEUES — checkpointing touches donated device
    buffers, so the save must run on the loop thread between steps.  The
    train loop calls :meth:`maybe_save` once per step; when commands are
    pending it forces a save, fences it (marker durable — the point of
    checkpoint-now is surviving what comes next), and acks each command
    ``complete`` with the saved step in its attrs.
    """

    def __init__(self, ckpt: CheckpointManager, agent: Any) -> None:
        self._ckpt = ckpt
        self._agent = agent
        self._lock = threading.Lock()
        self._pending: List[str] = []
        agent.register_handler("checkpoint-now", self._on_command)

    def _on_command(self, cmd: Dict[str, Any]) -> None:
        # Heartbeat thread: just enqueue (the "acked" event is already out).
        with self._lock:
            self._pending.append(str(cmd.get("uuid") or ""))

    def maybe_save(self, step: int, params: Any, opt_state: Any) -> bool:
        """Train-loop hook; near-free when nothing is pending."""
        if not self._pending:
            return False
        with self._lock:
            uuids, self._pending = self._pending, []
        try:
            try:
                self._ckpt.save(step, params, opt_state, force=True)
            except Exception:
                # Step already saved by the interval policy — fencing the
                # existing save below is all the command asked for.
                pass
            self._ckpt.wait_until_finished()
            saved = self._ckpt.latest_step()
        except Exception as exc:  # keep training alive; fail the command
            for uuid in uuids:
                if uuid:
                    self._agent.command_event(
                        uuid, "failed", message=f"checkpoint-now: {exc}"
                    )
            return False
        for uuid in uuids:
            if uuid:
                self._agent.command_event(uuid, "complete", step=saved)
        return True
