"""Sharded checkpoint/restore for training state.

The reference manages only *outputs directories* and delegates model
checkpointing to user frameworks (SURVEY §5: TF ``model_dir`` pointed at
the outputs path via TF_CONFIG, ``polypod/tensorflow.py:197-200``).  Here
checkpointing is first-class: orbax-backed, sharding-aware (each host
writes its shards, restore honors the target mesh), integrated with the
run layout's ``checkpoints/`` dir — which the clone strategies
(resume/copy) duplicate, so a resumed run restores step + optimizer state
automatically.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Optional, Union


class CheckpointManager:
    """Thin, typed wrapper over ``orbax.checkpoint.CheckpointManager``.

    Saves are ASYNC by default (``enable_async=True``): :meth:`save`
    returns once the device→host copy is staged — safe against the train
    step's donated buffers — and serialization to disk overlaps the steps
    that follow.  The fences are explicit and all inside this class:
    every restore path waits for in-flight saves first (a restore issued
    right after a save must see that step), and :meth:`close` drains
    before shutdown so no checkpoint is ever torn.  Orbax sequences
    eviction (``max_to_keep``) behind the in-flight save internally.

    :attr:`save_block_s` accumulates the wall seconds :meth:`save` blocked
    the caller — the hot loop's ``ckpt_block_s``.  With async on, that's
    the staging copy plus any wait for a still-running previous save; with
    async off it's the full serialization.
    """

    def __init__(
        self,
        directory: Union[str, Path],
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 1,
        enable_async: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self.save_block_s = 0.0
        self.saves = 0
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
                enable_async_checkpointing=enable_async,
            ),
        )

    def save(
        self,
        step: int,
        params: Any,
        opt_state: Any,
        force: bool = False,
    ) -> bool:
        """Save training state at ``step``; returns whether a save happened.

        Params and optimizer state are separate orbax ITEMS so inference
        consumers (``lm_generate``) can restore weights without knowing —
        or paying the memory/IO for — the training optimizer.
        """
        import orbax.checkpoint as ocp

        t0 = time.perf_counter()
        saved = self._mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
            force=force,
        )
        self.save_block_s += time.perf_counter() - t0
        if saved:
            self.saves += 1
        return saved

    def latest_step(self) -> Optional[int]:
        # Fence: an in-flight async save's step must be visible to whoever
        # asks "where are we" (restore-after-save ordering).
        self._mgr.wait_until_finished()
        return self._mgr.latest_step()

    def restore_params(
        self, params_template: Any, step: Optional[int] = None
    ) -> Optional[Dict[str, Any]]:
        """Restore ONLY the model weights (inference path): no optimizer
        template needed, no optimizer IO paid.  Raises ``ValueError`` for
        pre-round-4 single-item checkpoints (callers may fall back to
        :meth:`restore` with an optimizer template for those)."""
        import orbax.checkpoint as ocp

        self._mgr.wait_until_finished()  # fence against in-flight saves
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(params_template)
            ),
        )
        import jax

        params = jax.tree.map(
            lambda t, r: jax.device_put(r, t.sharding)
            if hasattr(t, "sharding")
            else r,
            params_template,
            restored["params"],
        )
        return {"params": params, "step": step}

    def restore(
        self,
        params_template: Any,
        opt_state_template: Any,
        step: Optional[int] = None,
    ) -> Optional[Dict[str, Any]]:
        """Restore onto the templates' shardings; None if no checkpoint.

        Templates are the freshly-initialized (sharded) state — orbax
        restores each leaf with the template's sharding, so a checkpoint
        written under one mesh restores correctly onto another.  Reads
        both the composite (round-4+) and legacy single-item layouts.
        """
        import orbax.checkpoint as ocp

        self._mgr.wait_until_finished()  # fence against in-flight saves
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        target = {"params": params_template, "opt_state": opt_state_template}
        try:
            composite = self._mgr.restore(
                step,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(params_template),
                    opt_state=ocp.args.StandardRestore(opt_state_template),
                ),
            )
            restored = {
                "params": composite["params"],
                "opt_state": composite["opt_state"],
            }
        except (ValueError, KeyError, TypeError):
            # Legacy layout: one StandardSave dict holding both halves.
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(target)
            )
        # Re-place every leaf onto its template's sharding: orbax restores
        # scalar leaves (e.g. optax's step count) onto the default device,
        # which poisons the jitted step with mixed device sets on a mesh.
        import jax

        def _place(template_leaf, restored_leaf):
            if hasattr(template_leaf, "sharding"):
                return jax.device_put(restored_leaf, template_leaf.sharding)
            return restored_leaf

        restored = jax.tree.map(_place, target, restored)
        restored["step"] = step
        return restored

    def wait_until_finished(self) -> None:
        """Block until every async save has committed to disk."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        # Shutdown fence: close() must never truncate an in-flight save.
        self._mgr.wait_until_finished()
        self._mgr.close()
