"""Gang-process entrypoint: ``python -m polyaxon_tpu.runtime.worker``.

This is what runs inside every gang member — the TPU-native fusion of the
reference's user container + sidecar + init container
(``polypod/experiment.py:160-244`` pod anatomy): it bootstraps the
distributed world (``jax.distributed.initialize`` — replacing TF_CONFIG /
MASTER_ADDR rendezvous), builds the device mesh, runs the spec's command or
python entrypoint with a tracking :class:`Context`, heartbeats, and reports
statuses/metrics/logs through the run-dir reporting channel.

Env knobs are set *before* importing jax: for the ``cpu`` accelerator the
worker forces ``JAX_PLATFORMS=cpu`` and a virtual device count, which is how
tests and the driver's multichip dry-run exercise real sharding without TPU
hardware.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

# stdlib-only import — safe before the deferred jax imports below
from polyaxon_tpu.conf.knobs import knob_float, knob_str


def _configure_jax_env(info) -> None:
    """Force the jax platform to match the plan's accelerator.

    Env-var only — jax itself is NOT imported here.  The jax import is
    the dominant cost of a gang member's boot (~2s of CPU), and plenty of
    gang workloads (metric probes, shell services, notebooks) never touch
    it; deferring it to first real use is what makes hpsearch waves
    orchestration-bound instead of import-bound.  If something imported
    jax before us (the TPU PJRT sitecustomize pins ``jax_platforms`` at
    interpreter start — env vars alone are ignored then), the explicit
    config override still runs, via :func:`_force_cpu_config`.
    """
    if info.accelerator.startswith("cpu"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        # The plan's device count wins over any inherited flag value.
        flags = [
            f
            for f in os.environ.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        flags.append(f"--xla_force_host_platform_device_count={info.devices_per_host}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
        if info.num_processes > 1:
            # Cross-process CPU collectives need an explicit backend; gloo
            # plays the role ICI/DCN transports play on real slices.
            os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    # Deterministic partitionable PRNG across meshes (same key → same stream
    # regardless of sharding).
    os.environ.setdefault("JAX_THREEFRY_PARTITIONABLE", "1")
    if info.accelerator.startswith("cpu") and "jax" in sys.modules:
        _force_cpu_config(info)


def _force_cpu_config(info) -> None:
    """Pin jax to CPU through the config API (needed when a site plugin
    already imported jax and env vars can no longer take effect)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    if info.num_processes > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")


def _init_distributed(info) -> bool:
    """Join the jax.distributed world. Returns True if initialized."""
    if info.num_processes <= 1 or not info.coordinator:
        return False
    if info.accelerator.startswith("cpu"):
        _force_cpu_config(info)
    import jax

    jax.distributed.initialize(
        coordinator_address=info.coordinator,
        num_processes=info.num_processes,
        process_id=info.process_id,
    )
    return True


def _run_cmd(cmd: str, env: dict, cwd: str, sampler=None) -> int:
    proc = subprocess.Popen(cmd, shell=True, env=env, cwd=cwd)
    if sampler is not None:
        # Telemetry must describe the workload, not this idle wrapper.
        sampler.pid = proc.pid
        sampler.start()
    return proc.wait()


def main() -> int:
    from polyaxon_tpu.runtime.env import GangInfo
    from polyaxon_tpu.stores.layout import RunPaths
    from polyaxon_tpu.tracking import Context, Reporter

    info = GangInfo.from_env()
    paths = RunPaths(Path(info.run_dir)).ensure()
    reporter = Reporter(paths.report_file(info.process_id), info.process_id)
    # Route this process's tracer spans through the report channel: the
    # watcher ingests them and the control plane assembles the
    # cross-process timeline (GET /api/v1/runs/<id>/timeline).
    from polyaxon_tpu.tracking import trace

    tracer = trace.configure(
        sink=reporter.span,
        process_id=info.process_id,
        trace_id=info.run_uuid or None,
    )
    # Same wiring for the utilization ledger: workloads that feed it
    # (trainers, serving engine) get their goodput/MFU rows shipped as
    # typed ``ledger`` report lines.  Imports no jax.
    from polyaxon_tpu.tracking import ledger as ledger_mod

    ledger_mod.configure(sink=reporter.ledger, process_id=info.process_id)
    # Command-bus receiver: the control plane drops command files into this
    # process's mailbox; the agent's poll rides the heartbeat thread (no
    # extra thread, near-zero idle cost) and on-demand profile captures
    # hook the workload step loops via get_capture_agent().on_step.
    from polyaxon_tpu.tracking import capture as capture_mod

    mailbox = paths.command_dir(info.process_id)
    mailbox.mkdir(parents=True, exist_ok=True)
    capture_agent = capture_mod.configure(
        reporter=reporter,
        mailbox=mailbox,
        profiles_root=paths.profiles,
        process_id=info.process_id,
    )
    reporter.add_beat_hook(capture_agent.poll)
    reporter.status("starting")
    reporter.start_heartbeat(info.heartbeat_interval)
    from polyaxon_tpu.tracking.flightrec import FlightRecorder, get_progress

    # Stall watchdog + crash forensics: trainers/serving beat the shared
    # progress beacon; no beat within the adaptive deadline → forensic
    # dump to reports/flightrec-<proc>-<n>.json + typed anomaly line.
    recorder = FlightRecorder(
        get_progress(),
        reporter=reporter,
        out_dir=paths.reports,
        process_id=info.process_id,
    )
    recorder.start()
    from polyaxon_tpu.monitor.resources import ResourceSampler

    # NOT started yet: the sampler thread touches jax.local_devices(),
    # which would initialize the backend and race jax.distributed below.
    sampler = ResourceSampler(
        reporter,
        interval=knob_float("POLYAXON_TPU_RESOURCE_INTERVAL"),
    )

    try:
        _configure_jax_env(info)
        # Persistent XLA compile cache: env-armed here (before any jax
        # import) so gang members and warm restarts share executables.
        # Spawner-resolved dir wins; hand-launched workers fall back to
        # the layout-conventional path next to runs/.
        from polyaxon_tpu.runtime.compilecache import enable_compile_cache

        enable_compile_cache(
            info.compile_cache_dir
            or str(paths.root.parent.parent / "compile_cache")
        )

        spec_data = json.loads(Path(info.spec_path).read_text())
        from polyaxon_tpu.schemas.specifications import specification_for_kind

        spec = specification_for_kind(spec_data["kind"]).model_validate(spec_data)
        service_port = knob_str("POLYAXON_TPU_SERVICE_PORT") or None
        if service_port is not None:
            # The dispatch-time port allocation reaches the workload both as
            # a template variable ({{service_port}} in cmd/kwargs) and as a
            # Context param for python entrypoints.
            spec.declarations.setdefault("service_port", int(service_port))
        run_cfg = spec.resolved_run() if hasattr(spec, "resolved_run") else spec.run

        # Code snapshot (if the build step materialized one) takes import
        # precedence — the init-container equivalent.
        code_dir = paths.code
        if code_dir.exists():
            sys.path.insert(0, str(code_dir))

        if run_cfg.cmd is not None:
            # Shell command path: the distributed bootstrap belongs to the
            # command itself (it can read the same env contract).
            reporter.status("running")
            with tracer.span("worker.cmd"):
                rc = _run_cmd(
                    run_cfg.cmd,
                    env=dict(os.environ),
                    cwd=str(code_dir if code_dir.exists() else paths.root),
                    sampler=sampler,
                )
            if rc == 0:
                reporter.status("succeeded")
                return 0
            reporter.status("failed", message=f"command exited {rc}")
            return 1

        # Python entrypoint path: managed distributed world + mesh.
        with tracer.span("worker.distributed_init", hosts=info.num_processes):
            distributed = _init_distributed(info)
        sampler.start()

        # The mesh is a THUNK: entrypoints that never read ctx.mesh (metric
        # probes, services) never pay the jax import it pulls in.
        mesh = None
        if info.mesh_axes:
            def mesh(axes=info.mesh_axes, dcn=info.dcn_axes):
                from polyaxon_tpu.runtime.mesh import build_mesh

                return build_mesh(axes, dcn_axes=dcn)

        params = dict(spec.declarations)
        params.update(run_cfg.kwargs)
        ctx = Context(
            params=params,
            process_id=info.process_id,
            num_processes=info.num_processes,
            mesh=mesh,
            strategy=info.strategy,
            strategy_options=info.strategy_options,
            outputs_path=str(paths.outputs),
            checkpoints_path=str(paths.checkpoints),
            # Spawner-resolved (layout knowledge stays in StoreLayout);
            # parent-walk only as a fallback for hand-launched workers.
            data_path=info.data_dir or str(paths.root.parent.parent / "data"),
            runs_root=str(paths.root.parent),
            reporter=reporter,
            seed=info.seed,
            run_uuid=info.run_uuid,
        )

        module_name, fn_name = run_cfg.entrypoint.split(":")
        import importlib

        module = importlib.import_module(module_name)
        fn = getattr(module, fn_name)

        reporter.status("running")
        with tracer.span("worker.entrypoint", entrypoint=run_cfg.entrypoint):
            fn(ctx)

        if distributed:
            import jax

            jax.distributed.shutdown()
        reporter.status("succeeded")
        return 0
    except BaseException as e:  # noqa: BLE001 — report, then die loudly
        # Postmortem first (thread stacks, span tail, HBM stats) so every
        # FAILED run leaves a flight-recorder dump next to its reports.
        recorder.crash_dump(e)
        reporter.error(e)
        raise
    finally:
        recorder.stop()
        sampler.stop()
        # A capture the gang is mid-way through must resolve (failed) —
        # an exiting worker must not leave its command hanging ACKED.
        try:
            capture_agent.close()
        except Exception:
            pass
        # Final ledger row (no-op if the workload never armed it): the
        # run's last cumulative truth, flagged final for consumers.
        try:
            ledger_mod.get_ledger().flush(final=True)
        except Exception:
            pass
        reporter.close()


if __name__ == "__main__":
    sys.exit(main())
