"""Device-mesh construction from a resolved axis map.

The TPU-native core the reference has no analogue for (SURVEY §2.8): where
polyaxon emitted per-framework cluster_defs, we build one
``jax.sharding.Mesh`` whose axes the sharding templates
(``polyaxon_tpu.parallel``) consume.  Axis order follows the spec's mesh
declaration: outermost (DCN/data-friendly) first, innermost (ICI-bandwidth-
hungry, e.g. ``tensor``) last, so ``mesh_utils.create_device_mesh`` places
the inner axes on physically adjacent chips.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from polyaxon_tpu.exceptions import RuntimeLayerError


def build_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` over all (or the given) devices."""
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape)) if shape else 1
    if n != len(devices):
        raise RuntimeLayerError(
            f"Mesh axes {axes} need {n} devices, have {len(devices)}"
        )
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except (ValueError, AssertionError, NotImplementedError):
        # Virtual/CPU devices or shapes the topology solver rejects: fall
        # back to a plain reshape (correct, just not physically optimal).
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def local_batch_slice(global_batch: int, num_processes: int, process_id: int) -> slice:
    """The per-process shard of a leading batch axis (data loading helper)."""
    if global_batch % num_processes != 0:
        raise RuntimeLayerError(
            f"Global batch {global_batch} not divisible by {num_processes} processes"
        )
    per = global_batch // num_processes
    return slice(process_id * per, (process_id + 1) * per)
