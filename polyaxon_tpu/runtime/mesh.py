"""Device-mesh construction from a resolved axis map.

The TPU-native core the reference has no analogue for (SURVEY §2.8): where
polyaxon emitted per-framework cluster_defs, we build one
``jax.sharding.Mesh`` whose axes the sharding templates
(``polyaxon_tpu.parallel``) consume.  Axis order follows the spec's mesh
declaration: outermost (DCN/data-friendly) first, innermost (ICI-bandwidth-
hungry, e.g. ``tensor``) last, so ``mesh_utils.create_device_mesh`` places
the inner axes on physically adjacent chips.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from polyaxon_tpu.exceptions import RuntimeLayerError


def build_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
    dcn_axes: Optional[Dict[str, int]] = None,
):
    """Build a ``jax.sharding.Mesh`` over all (or the given) devices.

    ``dcn_axes`` (a subset of ``axes``, by name) marks axes spanning
    SLICES: the hybrid builder assigns them across slice boundaries (slow
    DCN links) and lays the remaining ICI axes within each slice — the
    multi-slice/megascale recipe (data-like parallelism over DCN, tensor/
    sequence/pipeline over ICI).
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    shape = tuple(axes.values())
    n = int(np.prod(shape)) if shape else 1
    if n != len(devices):
        raise RuntimeLayerError(
            f"Mesh axes {axes} need {n} devices, have {len(devices)}"
        )
    dcn_axes = dcn_axes or {}
    if dcn_axes:
        unknown = set(dcn_axes) - set(axes)
        if unknown:
            raise RuntimeLayerError(f"dcn axes {unknown} not in mesh axes {axes}")
        mismatched = {a for a, size in dcn_axes.items() if axes[a] != size}
        if mismatched:
            raise RuntimeLayerError(
                f"dcn axis sizes disagree with mesh axes for {sorted(mismatched)}: "
                f"dcn={dcn_axes} mesh={axes}"
            )
        # Reorder: DCN axes lead, ICI axes follow (the spec compiler already
        # emits this order; re-assert it here for direct callers).
        names = tuple(dcn_axes) + tuple(a for a in axes if a not in dcn_axes)
        sizes = {**axes}
        shape = tuple(sizes[a] for a in names)
        # create_hybrid_device_mesh wants same-rank shapes with elementwise
        # product = axis size: a pure-DCN axis is 1 on the ICI side and
        # vice versa.
        ici_shape = tuple(1 if a in dcn_axes else sizes[a] for a in names)
        dcn_shape = tuple(sizes[a] if a in dcn_axes else 1 for a in names)
        # Route on real slice metadata: the hybrid builder only when the
        # devices genuinely span that many slices; a mismatch on hardware
        # is a misconfiguration that must surface (a naive reshape would
        # silently put ICI axes across DCN); CPU/virtual meshes (single or
        # absent slice id) reshape with process-contiguous blocks playing
        # the slices.
        n_slices = int(np.prod(tuple(dcn_axes.values())))
        slice_ids = {getattr(d, "slice_index", None) for d in devices}
        if None in slice_ids or len(slice_ids) == 1:
            dev_array = np.asarray(list(devices)).reshape(shape)
        elif len(slice_ids) == n_slices:
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=list(devices)
            )
        else:
            raise RuntimeLayerError(
                f"Topology declares {n_slices} slices over {dcn_axes} but the "
                f"devices span {len(slice_ids)} slices"
            )
        return Mesh(dev_array, names)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=list(devices))
    except (ValueError, AssertionError, NotImplementedError):
        # Virtual/CPU devices or shapes the topology solver rejects: fall
        # back to a plain reshape (correct, just not physically optimal).
        dev_array = np.asarray(list(devices)).reshape(shape)
    return Mesh(dev_array, tuple(axes.keys()))


def local_batch_slice(global_batch: int, num_processes: int, process_id: int) -> slice:
    """The per-process shard of a leading batch axis (data loading helper)."""
    if global_batch % num_processes != 0:
        raise RuntimeLayerError(
            f"Global batch {global_batch} not divisible by {num_processes} processes"
        )
    per = global_batch // num_processes
    return slice(process_id * per, (process_id + 1) * per)
