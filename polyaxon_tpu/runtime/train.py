"""Generic sharded training loop: strategy template → jitted train step.

The runtime core the reference delegates to user containers (SURVEY §2.8):
given a mesh, a strategy template, and a loss function, build the fully
sharded (init, step) pair.  Param/optimizer placement comes from the
template's logical rules; batch placement from its batch spec; everything
else XLA propagates.  The step is one compiled program — gradient, update,
metric — with donated state so params update in place in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from polyaxon_tpu.parallel.axes import tree_shardings, tree_specs
from polyaxon_tpu.parallel.templates import StrategyTemplate


@dataclass
class TrainStep:
    """A compiled sharded train step plus its placement helpers."""

    step: Callable  # (params, opt_state, batch, rng) -> (params, opt_state, metrics)
    init: Callable  # (rng) -> (params, opt_state)
    param_shardings: Any
    batch_sharding: Any
    mesh: Any

    def place_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import jax

        return jax.tree.map(
            lambda x: jax.device_put(x, self.batch_sharding), batch
        )


def build_train_step(
    *,
    loss_fn: Callable,
    init_fn: Callable,
    axes_tree: Any,
    optimizer: Any,
    mesh,
    template: StrategyTemplate,
    extra_metrics: Optional[Callable] = None,
) -> TrainStep:
    """Wire a loss/init pair into a sharded, jitted training step.

    ``loss_fn(params, batch) -> scalar`` and ``init_fn(rng) -> params`` are
    closures over the model config; ``axes_tree`` names every param's
    logical axes (same tree structure as params).
    """
    import jax
    from jax.sharding import NamedSharding

    mesh_axes = dict(mesh.shape)
    param_specs = tree_specs(axes_tree, template.rules, mesh_axes)
    param_shardings = tree_shardings(mesh, param_specs)
    batch_sharding = NamedSharding(mesh, template.batch_spec())

    jit_init = jax.jit(init_fn, out_shardings=param_shardings)

    def _opt_state_shardings(params):
        """Shardings for the optimizer state: any sub-tree that mirrors the
        param tree (optax's mu/nu/trace) gets the param shardings leaf for
        leaf; everything else (step counts, empty states) replicates.

        ``jax.jit(optimizer.init)`` alone gets this wrong in both
        directions — leaves with no data dependence on params (the count)
        land on device 0, and without out_shardings nothing forces mu/nu
        onto the params' placement."""
        from jax.sharding import PartitionSpec

        replicated = NamedSharding(mesh, PartitionSpec())
        param_treedef = jax.tree.structure(params)

        def rec(node):
            if jax.tree.structure(node) == param_treedef:
                return param_shardings
            if hasattr(node, "_fields"):  # optax's namedtuple states
                return type(node)(*(rec(c) for c in node))
            if isinstance(node, (tuple, list)):
                return type(node)(rec(c) for c in node)
            return replicated

        abstract = jax.eval_shape(optimizer.init, params)
        return rec(abstract)

    def init(rng):
        params = jit_init(rng)
        opt_state = jax.jit(
            optimizer.init, out_shardings=_opt_state_shardings(params)
        )(params)
        return params, opt_state

    def _step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        gnorm = jax.tree.reduce(
            lambda a, b: a + b,
            jax.tree.map(lambda g: (g.astype("float32") ** 2).sum(), grads),
        ) ** 0.5
        metrics = {"loss": loss, "grad_norm": gnorm}
        if extra_metrics is not None:
            metrics.update(extra_metrics(params, batch))
        return params, opt_state, metrics

    step = jax.jit(_step, donate_argnums=(0, 1))
    return TrainStep(
        step=step,
        init=init,
        param_shardings=param_shardings,
        batch_sharding=batch_sharding,
        mesh=mesh,
    )
