"""Generic sharded training loop: strategy template → jitted train step.

The runtime core the reference delegates to user containers (SURVEY §2.8):
given a mesh, a strategy template, and a loss function, build the fully
sharded (init, step) pair.  Param/optimizer placement comes from the
template's logical rules; batch placement from its batch spec; everything
else XLA propagates.  The step is one compiled program — gradient, update,
metric — with donated state so params update in place in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

from polyaxon_tpu.exceptions import RuntimeLayerError
from polyaxon_tpu.parallel.axes import tree_shardings, tree_specs
from polyaxon_tpu.parallel.templates import StrategyTemplate


def _validate_param_shapes(init_fn, param_specs, mesh_axes) -> None:
    """Every sharded param dim must divide by its mesh axes — checked up
    front so a config/mesh mismatch (e.g. 2 GQA KV heads tensor-sharded
    4 ways) reads as a one-line config error naming the parameter, not a
    pjit internals traceback out of jit_init."""
    import jax
    from jax.sharding import PartitionSpec

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    flat_shapes, _ = jax.tree.flatten(abstract)
    flat_specs, _ = jax.tree.flatten(
        param_specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
    )
    paths = [
        "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        # tree_flatten_with_path lives in tree_util on the older jax line;
        # jax.tree.flatten_with_path only arrived later.
        for path, _ in jax.tree_util.tree_flatten_with_path(abstract)[0]
    ]
    for name, leaf, spec in zip(paths, flat_shapes, flat_specs):
        for dim, entry in zip(leaf.shape, spec):
            if entry is None:
                continue
            axes = (entry,) if isinstance(entry, str) else entry
            size = 1
            for a in axes:
                size *= mesh_axes.get(a, 1)
            if size > 1 and dim % size:
                raise RuntimeLayerError(
                    f"Parameter {name!r} dim of size {dim} cannot shard over "
                    f"mesh axes {axes} (total {size}) — adjust the model "
                    f"config or the mesh (e.g. GQA kv heads vs tensor "
                    f"parallelism)"
                )


@dataclass
class TrainStep:
    """A compiled sharded train step plus its placement helpers."""

    step: Callable  # (params, opt_state, batch, rng) -> (params, opt_state, metrics)
    init: Callable  # (rng) -> (params, opt_state)
    param_shardings: Any
    batch_sharding: Any
    mesh: Any

    def place_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        import jax

        return jax.tree.map(
            lambda x: jax.device_put(x, self.batch_sharding), batch
        )

    def step_flops(self, *args: Any) -> Optional[float]:
        """Total FLOPs of one step from XLA's cost analysis, or None where
        the backend exposes none — the utilization ledger's measured path
        (callers fall back to analytic estimates).  Costs one extra
        compile: ``lower().compile()`` does not populate the jit cache."""
        from polyaxon_tpu.tracking.ledger import compiled_flops

        return compiled_flops(self.step, *args)


def build_train_step(
    *,
    loss_fn: Callable,
    init_fn: Callable,
    axes_tree: Any,
    optimizer: Any,
    mesh,
    template: StrategyTemplate,
    extra_metrics: Optional[Callable] = None,
) -> TrainStep:
    """Wire a loss/init pair into a sharded, jitted training step.

    ``loss_fn(params, batch) -> scalar`` and ``init_fn(rng) -> params`` are
    closures over the model config; ``axes_tree`` names every param's
    logical axes (same tree structure as params).
    """
    import jax
    from jax.sharding import NamedSharding

    mesh_axes = dict(mesh.shape)
    param_specs = tree_specs(axes_tree, template.rules, mesh_axes)
    param_shardings = tree_shardings(mesh, param_specs)
    batch_sharding = NamedSharding(mesh, template.batch_spec())

    _validate_param_shapes(init_fn, param_specs, mesh_axes)
    jit_init = jax.jit(init_fn, out_shardings=param_shardings)

    def _opt_state_shardings(params):
        """Shardings for the optimizer state: any sub-tree that mirrors the
        param tree (optax's mu/nu/trace) gets the param shardings leaf for
        leaf; everything else (step counts, empty states) replicates.

        ``jax.jit(optimizer.init)`` alone gets this wrong in both
        directions — leaves with no data dependence on params (the count)
        land on device 0, and without out_shardings nothing forces mu/nu
        onto the params' placement."""
        from jax.sharding import PartitionSpec

        replicated = NamedSharding(mesh, PartitionSpec())
        param_treedef = jax.tree.structure(params)

        def rec(node):
            if jax.tree.structure(node) == param_treedef:
                return param_shardings
            if hasattr(node, "_fields"):  # optax's namedtuple states
                return type(node)(*(rec(c) for c in node))
            if isinstance(node, (tuple, list)):
                return type(node)(rec(c) for c in node)
            return replicated

        abstract = jax.eval_shape(optimizer.init, params)
        return rec(abstract)

    def init(rng):
        params = jit_init(rng)
        opt_state = jax.jit(
            optimizer.init, out_shardings=_opt_state_shardings(params)
        )(params)
        return params, opt_state

    def _step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        gnorm = jax.tree.reduce(
            lambda a, b: a + b,
            jax.tree.map(lambda g: (g.astype("float32") ** 2).sum(), grads),
        ) ** 0.5
        metrics = {"loss": loss, "grad_norm": gnorm}
        if extra_metrics is not None:
            metrics.update(extra_metrics(params, batch))
        return params, opt_state, metrics

    step = jax.jit(_step, donate_argnums=(0, 1))
    return TrainStep(
        step=step,
        init=init,
        param_shardings=param_shardings,
        batch_sharding=batch_sharding,
        mesh=mesh,
    )
