"""Store-resident datasets: registration + host-sharded reading.

Parity: the reference mounts data volumes into pods and points TF at them
(``stores/managers`` data-path resolution; the CIFAR-10 guide,
``docs/guides/training-cifar10.md``).  TPU-native: datasets live under the
store layout's ``data/`` dir as numpy shard files, and the read path is
host-sharded by contract — each gang process reads ONLY the example range
it will contribute to the global batch, then
:func:`~polyaxon_tpu.runtime.data.global_batch_from_host_data` assembles
the global ``jax.Array`` with zero cross-host traffic at load time.

On-disk format (one dir per dataset):

    data/<name>/meta.json               {"num_examples", "shards", "arrays",
                                         "format", "shard_sizes"}
    data/<name>/shard-00000.images.npy  [n,H,W,C]
    data/<name>/shard-00000.labels.npy  [n]
    ...

Per-array raw ``.npy`` shards so the read path can ``np.load(...,
mmap_mode="r")``: the reader materializes only the ROWS each batch
gathers, so datasets far larger than host RAM stream at ImageNet/LM-token
scale (the reference streamed from mounted volumes; an in-RAM concat was
this module's own acknowledged limit through round 3).  Pre-round-4
``shard-*.npz`` datasets still read via the legacy in-RAM path.

Any array names work; arrays must share a leading dim per shard.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from polyaxon_tpu.exceptions import PolyaxonTPUError


def register_dataset(
    data_dir: Union[str, Path],
    name: str,
    shards: Sequence[Dict[str, np.ndarray]],
) -> Dict[str, Any]:
    """Write ``shards`` (list of array dicts) as a named dataset.

    Returns the meta dict. Overwrites an existing registration of the same
    name (datasets are immutable-by-convention; re-register to replace).
    """
    if not shards:
        raise PolyaxonTPUError(f"Dataset {name!r} needs at least one shard")
    root = Path(data_dir) / name
    root.mkdir(parents=True, exist_ok=True)
    arrays = sorted(shards[0].keys())
    shard_sizes: List[int] = []
    for i, shard in enumerate(shards):
        if sorted(shard.keys()) != arrays:
            raise PolyaxonTPUError(
                f"Shard {i} arrays {sorted(shard)} != shard 0 arrays {arrays}"
            )
        sizes = {len(v) for v in shard.values()}
        if len(sizes) != 1:
            raise PolyaxonTPUError(f"Shard {i} arrays disagree on length: {sizes}")
        # Raw .npy per array: mmap-able on read (npz is a zip — it isn't).
        for a, v in shard.items():
            np.save(root / f"shard-{i:05d}.{a}.npy", np.asarray(v))
        shard_sizes.append(sizes.pop())
    meta = {
        "num_examples": sum(shard_sizes),
        "shards": len(shards),
        "arrays": arrays,
        "format": "npy",
        "shard_sizes": shard_sizes,
    }
    # meta.json is the commit record: it's written LAST (shards already on
    # disk) and renamed into place atomically, so an interrupted
    # registration leaves either no meta (unregistered, shard files are
    # garbage) or a complete one — never a truncated json that readers
    # half-accept.
    tmp = root / "meta.json.tmp"
    tmp.write_text(json.dumps(meta))
    os.replace(tmp, root / "meta.json")
    return meta


def dataset_meta(data_dir: Union[str, Path], name: str) -> Dict[str, Any]:
    meta_path = Path(data_dir) / name / "meta.json"
    if not meta_path.exists():
        raise PolyaxonTPUError(
            f"Dataset {name!r} not registered under {data_dir} "
            f"(expected {meta_path})"
        )
    try:
        return json.loads(meta_path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise PolyaxonTPUError(
            f"Dataset {name!r} has an unreadable meta.json ({exc}) — "
            f"re-register it"
        ) from exc


def list_datasets(data_dir: Union[str, Path]) -> List[Dict[str, Any]]:
    root = Path(data_dir)
    out = []
    if root.is_dir():
        for d in sorted(root.iterdir()):
            if (d / "meta.json").exists():
                try:
                    out.append({"name": d.name, **dataset_meta(root, d.name)})
                except PolyaxonTPUError:
                    # A corrupt registration must not take down the whole
                    # listing — skip it (dataset_meta still reports it
                    # loudly to anyone addressing it by name).
                    continue
    return out


class DatasetReader:
    """Host-sharded batch iterator over a registered dataset.

    Process ``process_id`` of ``num_processes`` materializes only its own
    rows of every global batch: the global epoch permutation is derived
    deterministically from ``seed`` + epoch (identical on every host, no
    coordination), then each host takes its contiguous slice of each batch.
    Partial trailing batches are dropped (static shapes — XLA recompiles on
    shape change, so the step only ever sees ``[B/hosts, ...]``).
    """

    def __init__(
        self,
        data_dir: Union[str, Path],
        name: str,
        *,
        global_batch: int,
        seed: int = 0,
        num_processes: int = 1,
        process_id: int = 0,
        dtype_overrides: Optional[Dict[str, Any]] = None,
    ) -> None:
        if global_batch % num_processes:
            raise PolyaxonTPUError(
                f"Global batch {global_batch} not divisible by {num_processes} hosts"
            )
        self.meta = dataset_meta(data_dir, name)
        self.root = Path(data_dir) / name
        self.global_batch = global_batch
        self.seed = seed
        self.num_processes = num_processes
        self.process_id = process_id
        self.dtype_overrides = dtype_overrides or {}
        self.num_examples = self.meta["num_examples"]
        if self.meta.get("format") == "npy":
            # Streaming path: every shard is an mmap; a batch gather
            # touches only its rows' pages, so RSS stays O(batch) no
            # matter how large the dataset is.
            self.arrays = None
            self._shards: Dict[str, List[np.ndarray]] = {
                a: [
                    np.load(
                        self.root / f"shard-{i:05d}.{a}.npy", mmap_mode="r"
                    )
                    for i in range(self.meta["shards"])
                ]
                for a in self.meta["arrays"]
            }
            sizes = self.meta.get("shard_sizes") or [
                len(s) for s in next(iter(self._shards.values()))
            ]
            self._starts = np.concatenate([[0], np.cumsum(sizes)])
        else:
            # Legacy npz datasets (pre-round-4): zip members can't mmap;
            # load once, serve many epochs.
            arrays: Dict[str, List[np.ndarray]] = {
                a: [] for a in self.meta["arrays"]
            }
            for i in range(self.meta["shards"]):
                with np.load(self.root / f"shard-{i:05d}.npz") as z:
                    for a in self.meta["arrays"]:
                        arrays[a].append(z[a])
            self.arrays = {a: np.concatenate(v) for a, v in arrays.items()}

    @property
    def batches_per_epoch(self) -> int:
        return self.num_examples // self.global_batch

    def _epoch_tasks(
        self, epoch: int, start_batch: int = 0
    ) -> Iterator[Callable[[], Dict[str, np.ndarray]]]:
        """Zero-arg gather thunks for each batch of ``epoch``.

        The cheap index arithmetic (permutation slice) runs here, on the
        iterating thread; the expensive row gather runs when the thunk is
        CALLED — which is what lets a prefetcher execute gathers on worker
        threads while preserving this iterator's order.  Gathers are
        read-only over the mmaps, so concurrent thunk calls are safe."""
        rng = np.random.default_rng((self.seed, epoch))
        perm = rng.permutation(self.num_examples)
        per_host = self.global_batch // self.num_processes
        lo = self.process_id * per_host
        for b in range(start_batch, self.batches_per_epoch):
            batch_idx = perm[b * self.global_batch : (b + 1) * self.global_batch]
            local_idx = batch_idx[lo : lo + per_host]

            def task(idx: np.ndarray = local_idx) -> Dict[str, np.ndarray]:
                return {
                    a: self._cast(a, self._gather(a, idx))
                    for a in self.meta["arrays"]
                }

            yield task

    def epoch(
        self, epoch: int, start_batch: int = 0
    ) -> Iterator[Dict[str, np.ndarray]]:
        """This host's slice of each global batch, from ``start_batch`` on.

        Skipped batches cost only the (already computed) permutation — no
        row gathers, so a deep resume is O(1) per skipped batch."""
        for task in self._epoch_tasks(epoch, start_batch):
            yield task()

    def _gather(self, name: str, idx: np.ndarray) -> np.ndarray:
        """Rows ``idx`` (global order = shard order) of array ``name``.

        Streaming format: indices are grouped per shard and fancy-indexed
        out of the mmap — only the gathered rows materialize."""
        if self.arrays is not None:
            return self.arrays[name][idx]
        shard_of = np.searchsorted(self._starts, idx, side="right") - 1
        shards = self._shards[name]
        first = shards[0]
        out = np.empty((len(idx), *first.shape[1:]), dtype=first.dtype)
        for s in np.unique(shard_of):
            mask = shard_of == s
            out[mask] = shards[s][idx[mask] - self._starts[s]]
        return out

    def batch_tasks(
        self, start_step: int = 0
    ) -> Iterator[Callable[[], Dict[str, np.ndarray]]]:
        """Endless resumable stream of gather thunks (see
        :meth:`_epoch_tasks`) — the source a :class:`~polyaxon_tpu.runtime
        .pipeline.HostPrefetcher` consumes.  Same epoch/step arithmetic as
        :meth:`batches`, so prefetched and synchronous streams are
        byte-identical, including a mid-epoch resume."""
        bpe = self.batches_per_epoch
        if bpe == 0:
            raise PolyaxonTPUError(
                f"Dataset has {self.num_examples} examples < global batch "
                f"{self.global_batch}"
            )
        epoch, skip = divmod(start_step, bpe)
        while True:
            yield from self._epoch_tasks(epoch, start_batch=skip)
            skip = 0
            epoch += 1

    def batches(self, start_step: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        """Endless stream, resumable: ``start_step`` fast-forwards the
        epoch/batch position without materializing skipped batches — a
        resumed run sees exactly the data it would have seen."""
        for task in self.batch_tasks(start_step):
            yield task()

    def _cast(self, name: str, arr: np.ndarray) -> np.ndarray:
        want = self.dtype_overrides.get(name)
        return arr.astype(want) if want is not None else arr


# -- CIFAR-10 -----------------------------------------------------------------


def load_cifar10_python(batches_dir: Union[str, Path]) -> Dict[str, Dict[str, np.ndarray]]:
    """Parse the standard ``cifar-10-batches-py`` pickles into train/test
    arrays (NHWC uint8 images + int labels).  The archive itself must be
    fetched out-of-band (zero-egress platforms mount it)."""
    import pickle

    root = Path(batches_dir)

    def _load(fname: str):
        with open(root / fname, "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        images = (
            np.asarray(d[b"data"], dtype=np.uint8)
            .reshape(-1, 3, 32, 32)
            .transpose(0, 2, 3, 1)  # NCHW → NHWC (TPU-native layout)
        )
        labels = np.asarray(d[b"labels"], dtype=np.int32)
        return images, labels

    train = [_load(f"data_batch_{i}") for i in range(1, 6)]
    test_images, test_labels = _load("test_batch")
    return {
        "train": {
            "images": np.concatenate([t[0] for t in train]),
            "labels": np.concatenate([t[1] for t in train]),
        },
        "test": {"images": test_images, "labels": test_labels},
    }


def register_cifar10(
    data_dir: Union[str, Path],
    batches_dir: Union[str, Path],
    *,
    shard_size: int = 10000,
) -> Dict[str, Any]:
    """Register CIFAR-10 train/test splits from the standard archive dir."""
    splits = load_cifar10_python(batches_dir)
    out = {}
    for split, arrays in splits.items():
        n = len(arrays["labels"])
        shards = [
            {a: v[i : i + shard_size] for a, v in arrays.items()}
            for i in range(0, n, shard_size)
        ]
        out[split] = register_dataset(data_dir, f"cifar10-{split}", shards)
    return out


def synthetic_class_images(
    rng: np.random.Generator,
    num_examples: int,
    image_size: int,
    n_classes: int,
) -> tuple:
    """Class-conditional noisy-template images, uint8 NHWC.

    THE synthetic image recipe — shared by the fixture dataset and
    ``cnn_train``'s no-dataset benchmark branch so the two can never
    diverge. Per-example noise keeps the learnability check honest (without
    it a batch holds only ``n_classes`` distinct images)."""
    templates = rng.normal(size=(n_classes, image_size, image_size, 3))
    labels = rng.integers(0, n_classes, num_examples)
    noisy = templates[labels] + 0.3 * rng.normal(
        size=(num_examples, image_size, image_size, 3)
    )
    images = np.clip(noisy * 32 + 128, 0, 255).astype(np.uint8)
    return images, labels.astype(np.int32)


def make_image_fixture(
    data_dir: Union[str, Path],
    name: str,
    *,
    num_examples: int = 512,
    image_size: int = 32,
    n_classes: int = 10,
    shards: int = 2,
    seed: int = 0,
) -> Dict[str, Any]:
    """A CIFAR-shaped learnable fixture dataset (class-conditional noisy
    templates) — CI-sized stand-in for the real archive, same read path."""
    rng = np.random.default_rng(seed)
    images, labels = synthetic_class_images(
        rng, num_examples, image_size, n_classes
    )
    per = num_examples // shards
    shard_list = [
        {
            "images": images[i * per : (i + 1) * per],
            "labels": labels[i * per : (i + 1) * per].astype(np.int32),
        }
        for i in range(shards)
    ]
    return register_dataset(data_dir, name, shard_list)
