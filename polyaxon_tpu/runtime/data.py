"""Host-sharded data loading helpers.

The reference mounts data volumes into pods and leaves loading to user
code (``stores/managers``); on TPU slices the load path is part of the
runtime contract: each host process reads only its shard of the global
batch, and the shards are assembled into one global jax.Array.  This is
the multi-host-correct (and bandwidth-optimal) alternative to
``device_put``-ing a replicated global batch.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, Optional


def host_shard_bounds(
    global_batch: int, num_processes: int, process_id: int
) -> tuple:
    """[start, stop) rows of the global batch this host should load."""
    if global_batch % num_processes:
        raise ValueError(
            f"Global batch {global_batch} not divisible by {num_processes} hosts"
        )
    per = global_batch // num_processes
    return process_id * per, (process_id + 1) * per


def global_batch_from_host_data(local_batch: Dict[str, Any], sharding) -> Dict[str, Any]:
    """Per-host numpy shards → one global jax.Array pytree.

    ``local_batch`` holds THIS host's rows only (shape ``[B/num_hosts, ...]``);
    ``sharding`` is the batch NamedSharding (e.g. ``TrainStep.batch_sharding``).
    Uses ``jax.make_array_from_process_local_data``, so nothing is
    replicated across hosts and no cross-host transfer happens at load time.
    """
    import jax

    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), local_batch
    )


def synthetic_token_batches(
    *,
    vocab_size: int,
    global_batch: int,
    seq: int,
    sharding,
    seed: int = 0,
    num_processes: int = 1,
    process_id: int = 0,
) -> Iterator[Dict[str, Any]]:
    """Endless deterministic LM batches, host-sharded.

    Every host generates the full batch stream from the shared seed but
    materializes only its own rows — the pattern a real sharded data
    loader follows (per-host file shards), with no IO dependency.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    lo, hi = host_shard_bounds(global_batch, num_processes, process_id)
    while True:
        tokens = rng.integers(0, vocab_size, (global_batch, seq + 1))
        local = tokens[lo:hi]
        yield global_batch_from_host_data(
            {
                "tokens": local[:, :-1].astype(np.int32),
                "targets": local[:, 1:].astype(np.int32),
            },
            sharding,
        )
