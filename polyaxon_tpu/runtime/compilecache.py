"""Persistent XLA compile cache: cold-start elimination for workers.

The goodput ledger (PR 5) attributes ``xla_compile_s`` per run, and it
shows every gang member, serving replica, and hpsearch trial paying the
full XLA compile bill fresh — pure overhead, and for short trials the
dominant cost.  This module wires JAX's persistent compilation cache
into worker startup, rooted at a per-:class:`StoreLayout` shared
directory (``<base_dir>/compile_cache``) so gang members and successive
runs of the same store share compiled executables: a restarted run comes
back warm.

Knobs (all env, spawner-propagated like ``POLYAXON_TPU_DATA_DIR``):

- ``POLYAXON_TPU_COMPILE_CACHE`` — ``0``/``false``/``off`` disables
  (default on).
- ``POLYAXON_TPU_COMPILE_CACHE_DIR`` — cache directory; the spawner
  resolves it from the store layout, hand-launched workers derive it
  from the run dir.
- ``POLYAXON_TPU_COMPILE_CACHE_MIN_COMPILE_S`` — only persist compiles
  that took at least this long (default 0: persist everything; the CPU
  smoke configs compile in milliseconds and cross-process reuse is the
  point).

Same graceful-degradation contract as the ledger's ``jax.monitoring``
hooks: on JAX versions/backends without the persistent-cache API,
:func:`enable_compile_cache` returns a no-op status carrying the reason
(surfaced by ``checks/health.py:check_compile_cache``) and never raises.
Never imports jax itself when it isn't already loaded — the worker
defers the jax import deliberately, so the pre-import path arms the
cache through env vars that jax's config reads at import time.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from polyaxon_tpu.conf.knobs import knob_bool, knob_float, knob_str

__all__ = [
    "CacheStatus",
    "enable_compile_cache",
    "cache_status",
    "aot_compile",
]

# Knob names as module constants (tests and callers reference these).
ENV_ENABLE = "POLYAXON_TPU_COMPILE_CACHE"
ENV_DIR = "POLYAXON_TPU_COMPILE_CACHE_DIR"
ENV_MIN_COMPILE_S = "POLYAXON_TPU_COMPILE_CACHE_MIN_COMPILE_S"


@dataclass(frozen=True)
class CacheStatus:
    """Outcome of the most recent :func:`enable_compile_cache` attempt."""

    enabled: bool
    cache_dir: Optional[str]
    reason: str
    min_compile_s: float = 0.0


_lock = threading.Lock()
_status: Optional[CacheStatus] = None


def enable_compile_cache(
    cache_dir: Optional[str] = None,
    *,
    min_compile_s: Optional[float] = None,
) -> CacheStatus:
    """Enable JAX's persistent compilation cache for this process.

    ``POLYAXON_TPU_COMPILE_CACHE_DIR`` wins over the ``cache_dir``
    argument (callers pass their layout-derived fallback).  Idempotent:
    re-enabling with the same directory returns the cached status.
    Never raises — failures come back as a disabled status with the
    reason.
    """
    global _status
    with _lock:
        if not knob_bool(ENV_ENABLE):
            _status = CacheStatus(
                False, None, f"disabled by {ENV_ENABLE}"
            )
            return _status
        resolved = knob_str(ENV_DIR) or cache_dir
        if not resolved:
            _status = CacheStatus(
                False,
                None,
                f"no cache dir (set {ENV_DIR} or pass cache_dir)",
            )
            return _status
        resolved = str(resolved)
        if (
            _status is not None
            and _status.enabled
            and _status.cache_dir == resolved
        ):
            return _status
        if min_compile_s is None:
            min_compile_s = knob_float(ENV_MIN_COMPILE_S)
        try:
            os.makedirs(resolved, exist_ok=True)
            if not os.access(resolved, os.W_OK):
                raise OSError("not writable")
        except OSError as e:
            _status = CacheStatus(
                False, resolved, f"cache dir {resolved} unusable: {e}"
            )
            return _status

        # Arm through env first: jax reads these at import, so workers
        # that haven't paid the jax import yet (the common boot path)
        # get the cache for free on first use.  min_entry_size -1 means
        # "persist regardless of size" — the compile-time threshold is
        # the only gate we expose.
        os.environ["JAX_COMPILATION_CACHE_DIR"] = resolved
        os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = str(
            min_compile_s
        )
        os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"] = "-1"

        if "jax" in sys.modules:
            # Already-imported jax ignores env: go through the config
            # API, then reset the cache singleton — is_cache_used() and
            # the backing LRUCache latch on first compile, so without
            # the reset a process that compiled anything pre-enable
            # would silently never read or write the cache.
            try:
                import jax
                from jax._src import compilation_cache as _cc

                jax.config.update("jax_compilation_cache_dir", resolved)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs",
                    float(min_compile_s),
                )
                jax.config.update(
                    "jax_persistent_cache_min_entry_size_bytes", -1
                )
                _cc.reset_cache()
            except Exception as e:
                _status = CacheStatus(
                    False,
                    resolved,
                    f"jax persistent-cache API unavailable: {e!r}",
                    float(min_compile_s),
                )
                return _status
            # Hit/miss counters ride the same monitoring channel as the
            # ledger's compile-seconds attribution.
            try:
                from polyaxon_tpu.tracking.ledger import install_compile_hooks

                install_compile_hooks()
            except Exception:
                pass
            reason = "enabled (config API)"
        else:
            reason = "armed via env (jax not imported yet)"
        _status = CacheStatus(True, resolved, reason, float(min_compile_s))
        return _status


def cache_status() -> CacheStatus:
    """The last :func:`enable_compile_cache` outcome for this process
    (a disabled placeholder when it was never called — e.g. the control
    plane, which never compiles)."""
    with _lock:
        if _status is not None:
            return _status
        return CacheStatus(False, None, "not enabled in this process")


def _reset_for_tests() -> None:
    global _status
    with _lock:
        _status = None


def aot_compile(jitted: Callable, *args: Any) -> Tuple[Callable, float]:
    """AOT-compile a jitted fn: ``(executable, compile_seconds)``.

    The returned executable must be *called directly* — ``lower().
    compile()`` does not populate the jit dispatch cache, so calling the
    original ``jitted`` afterwards would compile a second time.  Falls
    back to ``(jitted, 0.0)`` wherever lowering is unavailable, so
    callers can use the result unconditionally.  Donation declared on
    the jit is preserved through the AOT path.
    """
    t0 = time.perf_counter()
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:
        return jitted, 0.0
    return compiled, time.perf_counter() - t0
