"""Overlapped training input pipeline: host prefetch, device prefetch,
async metrics drain.

The reference delegates the input pipeline to user frameworks (tf.data
inside the training container); here the runtime owns the hot loop, so the
overlap tf.data-style prefetching buys is part of the runtime contract:

- :class:`HostPrefetcher` — a bounded-queue background prefetcher that
  gathers batch *i+1*'s rows on worker threads while step *i* runs on
  device, preserving the exact resumable stream order.
- :func:`device_prefetch` — double-buffered placement: the ``device_put``
  for the next batch is dispatched before the current one is consumed, so
  the host→HBM transfer overlaps compute (jax transfers are async).
- :class:`TrainPipeline` — the two composed behind one iterator, with
  ``prefetch=0`` degrading to the fully synchronous path (byte-identical
  stream — the A/B baseline and the fallback).
- :class:`MetricsDrain` — keeps per-step metrics as device arrays and
  fetches them to host on a background thread, so logging never inserts a
  device→host sync into the dispatch path.

Everything host-side here is numpy/threading only; jax is touched only on
the consumer thread (placement), so gang workers stay single-jax-threaded.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

from polyaxon_tpu.tracking.trace import get_tracer


class _Done:
    """Queue sentinel: source exhausted (or raised — carries the error)."""

    def __init__(self, error: Optional[BaseException] = None) -> None:
        self.error = error


class HostPrefetcher:
    """Bounded-queue background prefetcher preserving stream order.

    ``source`` yields zero-arg *tasks* (``tasks=True``, e.g.
    :meth:`DatasetReader.batch_tasks`) or plain items.  A dispatcher thread
    walks the source strictly in order, submits each task to a worker pool,
    and enqueues the resulting future into a bounded queue; the consumer
    pops futures in submission order — so the delivered stream is exactly
    the source's order no matter how many workers gather concurrently.

    Backpressure: the queue holds at most ``depth`` futures, so the
    dispatcher runs at most ``depth + 1`` items ahead of the consumer —
    memory stays O(depth) batches however slow the training step is.

    A task that raises delivers its exception at its position in the
    stream (the consumer's ``next()`` raises); ``close()`` always unblocks
    and joins the dispatcher, so a crashing trainer can't leak threads.
    """

    def __init__(
        self,
        source: Iterable[Any],
        *,
        depth: int = 2,
        workers: int = 1,
        tasks: bool = True,
    ) -> None:
        self._source = iter(source)
        self._tasks = tasks
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._done = False
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers), thread_name_prefix="prefetch"
        )
        #: Cumulative seconds the consumer spent blocked waiting for data.
        self.wait_s = 0.0
        self._dispatcher = threading.Thread(
            target=self._dispatch, name="prefetch-dispatch", daemon=True
        )
        self._dispatcher.start()

    # -- producer side --------------------------------------------------------
    def _put(self, item: Any) -> bool:
        """Enqueue, but never deadlock against a vanished consumer."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _dispatch(self) -> None:
        try:
            for item in self._source:
                if self._stop.is_set():
                    return
                if self._tasks:
                    fut = self._pool.submit(self._traced(item))
                else:
                    fut = Future()
                    fut.set_result(item)
                if not self._put(fut):
                    fut.cancel()
                    return
            self._put(_Done())
        except BaseException as exc:  # source itself raised mid-iteration
            self._put(_Done(error=exc))

    @staticmethod
    def _traced(task: Callable[[], Any]) -> Callable[[], Any]:
        """Wrap a gather task in a (hot-rate-sampled) tracer span."""
        tracer = get_tracer()

        def run() -> Any:
            with tracer.span("pipeline.gather", sample=tracer.hot_sample):
                return task()

        return run

    # -- consumer side --------------------------------------------------------
    def __iter__(self) -> "HostPrefetcher":
        return self

    def __next__(self) -> Any:
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        got = self._q.get()
        if isinstance(got, _Done):
            self._done = True
            self.wait_s += time.perf_counter() - t0
            if got.error is not None:
                raise got.error
            raise StopIteration
        out = got.result()  # blocks until the worker finishes; re-raises
        self.wait_s += time.perf_counter() - t0
        return out

    def close(self) -> None:
        """Stop the dispatcher and workers; idempotent, exception-safe."""
        self._stop.set()
        # Drain so a dispatcher blocked in put() can observe the stop flag.
        while self._dispatcher.is_alive():
            try:
                while True:
                    self._q.get_nowait()
            except queue.Empty:
                pass
            self._dispatcher.join(timeout=0.05)
        self._pool.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "HostPrefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def device_prefetch(
    host_iter: Iterable[Any],
    place: Callable[[Any], Any],
    depth: int = 1,
) -> Iterator[Any]:
    """Keep ``depth`` placed batches in flight ahead of the consumer.

    ``place`` (e.g. ``device_put`` onto ``TrainStep.batch_sharding``) is
    dispatched for batch *i+1* before batch *i* is yielded; jax transfers
    are asynchronous, so the H2D copy proceeds while step *i* computes.
    Must run on the consumer (jax) thread — only the host gather is
    delegated to workers.
    """
    buf: deque = deque()
    for item in host_iter:
        buf.append(place(item))
        if len(buf) > depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


class TrainPipeline:
    """Host prefetch → device prefetch behind one iterator.

    ``prefetch`` is the host-side queue depth (0 disables all overlap:
    tasks run inline on the consumer thread, placement is synchronous —
    the stream stays byte-identical either way).  ``workers`` is the
    gather thread count.  ``data_wait_s`` accumulates the seconds the hot
    loop spent blocked inside ``next()`` — the number that should go to
    ~0 when overlap is winning.
    """

    def __init__(
        self,
        source: Iterable[Any],
        place: Optional[Callable[[Any], Any]] = None,
        *,
        prefetch: int = 2,
        workers: int = 2,
        tasks: bool = True,
        device_depth: int = 1,
    ) -> None:
        self.place = place if place is not None else (lambda x: x)
        self.data_wait_s = 0.0
        self._last_wait_mark = 0.0
        self._prefetcher: Optional[HostPrefetcher] = None
        if prefetch > 0:
            self._prefetcher = HostPrefetcher(
                source, depth=prefetch, workers=workers, tasks=tasks
            )
            self._it = device_prefetch(
                self._prefetcher, self.place, depth=max(0, device_depth)
            )
        else:
            self._it = self._sync_iter(source, tasks)

    def _sync_iter(self, source: Iterable[Any], tasks: bool) -> Iterator[Any]:
        for item in source:
            yield self.place(item() if tasks else item)

    def __iter__(self) -> "TrainPipeline":
        return self

    def __next__(self) -> Any:
        t0 = time.perf_counter()
        batch = next(self._it)
        self.data_wait_s += time.perf_counter() - t0
        return batch

    def pop_data_wait_s(self) -> float:
        """Seconds blocked on data since the previous call (per-interval)."""
        now, last = self.data_wait_s, self._last_wait_mark
        self._last_wait_mark = now
        return now - last

    def close(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.close()
        if hasattr(self._it, "close"):
            self._it.close()

    def __enter__(self) -> "TrainPipeline":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class MetricsDrain:
    """Fetch per-step device metrics off the hot loop.

    ``push(step, values)`` stores the (step, device-array dict) and
    returns immediately; a daemon thread performs the device→host reads
    and hands ``{name: float}`` to ``emit`` in push order.  The hot loop
    never pays a ``float(metrics[...])`` sync just to log — the classic
    every-N-steps logging stall.

    The queue is bounded (holding device arrays pins their buffers): if
    the host falls ``depth`` fetches behind, ``push`` blocks — visible
    backpressure instead of unbounded memory growth.  ``close()`` drains
    everything still queued, so no pushed metric is ever lost; an ``emit``
    or fetch error is re-raised there rather than swallowed.
    """

    _DONE = object()

    def __init__(
        self,
        emit: Callable[[Optional[int], Dict[str, float]], None],
        *,
        depth: int = 8,
    ) -> None:
        self._emit = emit
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, depth))
        self._error: Optional[BaseException] = None
        #: Last drained values / step (host floats) — for end-of-run logs.
        self.last: Dict[str, float] = {}
        self.last_step: Optional[int] = None
        #: Seconds ``close()`` spent draining the backlog — the run's
        #: "metric-drain" ledger bucket (in-loop drains overlap compute).
        self.close_wait_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="metrics-drain", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        import numpy as np

        while True:
            got = self._q.get()
            if got is self._DONE:
                return
            step, values = got
            try:
                tracer = get_tracer()
                with tracer.span("pipeline.drain", sample=tracer.hot_sample):
                    host = {k: float(np.asarray(v)) for k, v in values.items()}
                    self._emit(step, host)
                self.last, self.last_step = host, step
            except BaseException as exc:
                if self._error is None:
                    self._error = exc

    def push(self, step: Optional[int], values: Dict[str, Any]) -> None:
        self._q.put((step, values))

    def close(self) -> None:
        """Drain everything queued, join the thread, surface any error."""
        t0 = time.perf_counter()
        try:
            self._q.put(self._DONE)
            self._thread.join()
        finally:
            self.close_wait_s += time.perf_counter() - t0
        if self._error is not None:
            raise self._error
