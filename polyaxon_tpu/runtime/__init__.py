from polyaxon_tpu.runtime.env import EnvVars
from polyaxon_tpu.runtime.mesh import build_mesh

__all__ = ["EnvVars", "build_mesh"]
