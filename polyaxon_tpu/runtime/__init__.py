from polyaxon_tpu.runtime.env import EnvVars
from polyaxon_tpu.runtime.mesh import build_mesh
from polyaxon_tpu.runtime.pipeline import (
    HostPrefetcher,
    MetricsDrain,
    TrainPipeline,
    device_prefetch,
)

__all__ = [
    "EnvVars",
    "build_mesh",
    "HostPrefetcher",
    "MetricsDrain",
    "TrainPipeline",
    "device_prefetch",
]
