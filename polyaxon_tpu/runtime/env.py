"""The gang rendezvous env contract: the TF_CONFIG equivalent.

Parity: the reference injects framework-specific rendezvous env into every
pod — ``TF_CONFIG`` (``polypod/tensorflow.py:193-203``), ``MASTER_ADDR/RANK``
(``polypod/pytorch.py:139-157``), DMLC vars (``polypod/mxnet.py:19-35``).
TPU-native: one dialect for every strategy — coordinator address +
process id + mesh shape — consumed by ``jax.distributed.initialize`` and the
mesh builder.  The spawner writes these; the worker reads them.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional


class EnvVars:
    RUN_ID = "POLYAXON_TPU_RUN_ID"
    RUN_UUID = "POLYAXON_TPU_RUN_UUID"
    RUN_DIR = "POLYAXON_TPU_RUN_DIR"
    SPEC_PATH = "POLYAXON_TPU_SPEC_PATH"
    PROCESS_ID = "POLYAXON_TPU_PROCESS_ID"
    NUM_PROCESSES = "POLYAXON_TPU_NUM_PROCESSES"
    COORDINATOR = "POLYAXON_TPU_COORDINATOR"
    DEVICES_PER_HOST = "POLYAXON_TPU_DEVICES_PER_HOST"
    ACCELERATOR = "POLYAXON_TPU_ACCELERATOR"
    MESH = "POLYAXON_TPU_MESH"
    MESH_DCN = "POLYAXON_TPU_MESH_DCN"
    STRATEGY = "POLYAXON_TPU_STRATEGY"
    STRATEGY_OPTIONS = "POLYAXON_TPU_STRATEGY_OPTIONS"
    HEARTBEAT_INTERVAL = "POLYAXON_TPU_HEARTBEAT_INTERVAL"
    SEED = "POLYAXON_TPU_SEED"
    DATA_DIR = "POLYAXON_TPU_DATA_DIR"
    #: doubles as the runtime/compilecache.py knob — the spawner writing
    #: it IS the enablement channel, no separate plumbing.
    COMPILE_CACHE_DIR = "POLYAXON_TPU_COMPILE_CACHE_DIR"


@dataclass
class GangInfo:
    """Decoded worker-side view of the rendezvous contract."""

    run_id: int
    run_uuid: str
    run_dir: str
    spec_path: str
    process_id: int
    num_processes: int
    coordinator: Optional[str]
    devices_per_host: int
    accelerator: str
    mesh_axes: Dict[str, int]
    #: subset of mesh_axes spanning slices (DCN); empty for single-slice
    dcn_axes: Dict[str, int]
    strategy: str
    strategy_options: Dict[str, Any]
    heartbeat_interval: float
    seed: Optional[int]
    #: The store layout's shared data/ dir (registered datasets); the
    #: spawner resolves it so workers never re-derive layout structure.
    data_dir: Optional[str] = None
    #: The store layout's shared compile_cache/ dir (persistent XLA
    #: compile cache); same spawner-resolved contract as data_dir.
    compile_cache_dir: Optional[str] = None

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "GangInfo":
        e = env if env is not None else os.environ
        seed = e.get(EnvVars.SEED)
        return cls(
            run_id=int(e[EnvVars.RUN_ID]),
            run_uuid=e[EnvVars.RUN_UUID],
            run_dir=e[EnvVars.RUN_DIR],
            spec_path=e[EnvVars.SPEC_PATH],
            process_id=int(e[EnvVars.PROCESS_ID]),
            num_processes=int(e[EnvVars.NUM_PROCESSES]),
            coordinator=e.get(EnvVars.COORDINATOR) or None,
            devices_per_host=int(e.get(EnvVars.DEVICES_PER_HOST, "1")),
            accelerator=e.get(EnvVars.ACCELERATOR, "cpu"),
            mesh_axes=json.loads(e.get(EnvVars.MESH, "{}")),
            dcn_axes=json.loads(e.get(EnvVars.MESH_DCN, "{}")),
            strategy=e.get(EnvVars.STRATEGY, "ddp"),
            strategy_options=json.loads(e.get(EnvVars.STRATEGY_OPTIONS, "{}")),
            heartbeat_interval=float(e.get(EnvVars.HEARTBEAT_INTERVAL, "5.0")),
            seed=int(seed) if seed not in (None, "") else None,
            data_dir=e.get(EnvVars.DATA_DIR) or None,
            compile_cache_dir=e.get(EnvVars.COMPILE_CACHE_DIR) or None,
        )


def gang_env(
    *,
    run_id: int,
    run_uuid: str,
    run_dir: str,
    spec_path: str,
    process_id: int,
    num_processes: int,
    coordinator: Optional[str],
    devices_per_host: int,
    accelerator: str,
    mesh_axes: Dict[str, int],
    strategy: str,
    dcn_axes: Optional[Dict[str, int]] = None,
    strategy_options: Dict[str, Any],
    heartbeat_interval: float = 5.0,
    seed: Optional[int] = None,
    data_dir: Optional[str] = None,
    compile_cache_dir: Optional[str] = None,
) -> Dict[str, str]:
    """Spawner-side encoder (inverse of ``GangInfo.from_env``)."""
    env = {
        EnvVars.RUN_ID: str(run_id),
        EnvVars.RUN_UUID: run_uuid,
        EnvVars.RUN_DIR: run_dir,
        EnvVars.SPEC_PATH: spec_path,
        EnvVars.PROCESS_ID: str(process_id),
        EnvVars.NUM_PROCESSES: str(num_processes),
        EnvVars.DEVICES_PER_HOST: str(devices_per_host),
        EnvVars.ACCELERATOR: accelerator,
        EnvVars.MESH: json.dumps(mesh_axes),
        EnvVars.MESH_DCN: json.dumps(dcn_axes or {}),
        EnvVars.STRATEGY: strategy,
        EnvVars.STRATEGY_OPTIONS: json.dumps(strategy_options),
        EnvVars.HEARTBEAT_INTERVAL: str(heartbeat_interval),
    }
    if coordinator:
        env[EnvVars.COORDINATOR] = coordinator
    if seed is not None:
        env[EnvVars.SEED] = str(seed)
    if data_dir:
        env[EnvVars.DATA_DIR] = data_dir
    if compile_cache_dir:
        env[EnvVars.COMPILE_CACHE_DIR] = compile_cache_dir
    return env
