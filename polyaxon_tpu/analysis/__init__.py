"""graft-lint — the project-native static-analysis pass.

See ``docs/analysis.md`` for the rule catalog and rationale; run with
``python -m polyaxon_tpu.analysis`` or ``make lint``.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

from polyaxon_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    load_project,
    run_rules,
)
from polyaxon_tpu.analysis.rules import ALL_RULES, default_rules, rule_by_id

__all__ = [
    "ALL_RULES",
    "Finding",
    "ModuleInfo",
    "Project",
    "Rule",
    "default_rules",
    "load_project",
    "package_root",
    "rule_by_id",
    "run_analysis",
    "run_rules",
]


def package_root() -> Path:
    """The ``polyaxon_tpu/`` package directory (the default lint target)."""
    return Path(__file__).resolve().parent.parent


def run_analysis(
    paths: Optional[Sequence[Path]] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Finding]:
    """Load + run in one call (the API used by tests, bench, and the
    health probe).  Does **not** write the state file — only the CLI
    persists state, so hermetic callers stay hermetic."""
    if paths is None:
        paths = [package_root()]
    project = load_project(paths)
    return run_rules(project, list(rules) if rules else default_rules())
