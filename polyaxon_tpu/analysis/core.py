"""graft-lint core: module loading, suppression parsing, rule driving.

The platform's hard-won invariants — zero steady-state recompiles,
donation discipline on the paged pool, registry writes inside the write
lock, non-blocking tick paths, the central knob catalog — were enforced
at runtime (bench budgets, monitored counters) or not at all.  This
package enforces them *statically*, at review time: an AST pass over
``polyaxon_tpu/`` with one rule per bug class (see ``rules.py`` for the
catalog and ``docs/analysis.md`` for the rationale of each).

Suppression syntax (every suppression should carry a justification —
the self-clean test asserts it)::

    do_thing()  # graft-lint: disable=GL004 -- bounded by the 5s deadline

    # graft-lint: disable=GL003 -- caller holds _lock (see _delete_tree)
    conn.execute("DELETE ...")

    # graft-lint: disable-file=GL005 -- generated knob fixtures

A standalone suppression comment applies to the next line; a trailing
one to its own line; ``disable-file`` to the whole file.  ``disable=all``
suppresses every rule.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "ModuleInfo",
    "Project",
    "Rule",
    "load_module",
    "load_project",
    "run_rules",
]

_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint:\s*(disable|disable-file)=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(.*))?$"
)


@dataclass
class Finding:
    """One rule violation at one location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


@dataclass
class Suppression:
    rules: Set[str]  # rule ids, or {"all"}
    reason: str


@dataclass
class ModuleInfo:
    """One parsed source file plus its suppression map."""

    path: Path
    rel: str
    source: str
    tree: ast.Module
    #: line -> suppression active on that line
    line_suppressions: Dict[int, Suppression] = field(default_factory=dict)
    #: file-wide suppressions
    file_suppressions: Dict[str, Suppression] = field(default_factory=dict)

    def suppression_for(self, rule_id: str, line: int) -> Optional[Suppression]:
        sup = self.file_suppressions.get(rule_id) or self.file_suppressions.get(
            "all"
        )
        if sup is not None:
            return sup
        sup = self.line_suppressions.get(line)
        if sup is not None and (rule_id in sup.rules or "all" in sup.rules):
            return sup
        return None


@dataclass
class Project:
    """Every module under analysis (rules needing global state — the
    knob catalog cross-check, callback registration resolution — read
    from here)."""

    modules: List[ModuleInfo]
    root: Path

    def by_rel(self, rel: str) -> Optional[ModuleInfo]:
        for mod in self.modules:
            if mod.rel == rel or mod.rel.endswith(rel):
                return mod
        return None


class Rule:
    """One checker.  Subclasses set the class attributes and implement
    :meth:`check_module` (per-file findings) and optionally
    :meth:`prepare` / :meth:`finalize` (project-wide passes)."""

    id: str = "GL000"
    name: str = "base"
    version: str = "1"
    doc: str = ""

    def prepare(self, project: Project) -> None:  # pragma: no cover - hook
        pass

    def check_module(
        self, mod: ModuleInfo, project: Project
    ) -> Iterable[Finding]:  # pragma: no cover - hook
        return ()

    def finalize(self, project: Project) -> Iterable[Finding]:
        return ()

    def finding(
        self, mod: ModuleInfo, node: ast.AST, message: str
    ) -> Finding:
        return Finding(
            rule=self.id,
            path=mod.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


# -- parsing -----------------------------------------------------------------

def _parse_suppressions(
    source: str,
) -> Tuple[Dict[int, Suppression], Dict[str, Suppression]]:
    line_sup: Dict[int, Suppression] = {}
    file_sup: Dict[str, Suppression] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m is None:
            continue
        kind, raw_rules, reason = m.group(1), m.group(2), m.group(3) or ""
        rules = {r.strip() for r in raw_rules.split(",") if r.strip()}
        sup = Suppression(rules=rules, reason=reason.strip())
        if kind == "disable-file":
            for rule in rules:
                file_sup[rule] = sup
            continue
        line_sup[i] = sup
        # A standalone comment line suppresses the next line too.
        if text.lstrip().startswith("#"):
            line_sup[i + 1] = sup
    return line_sup, file_sup


def attach_parents(tree: ast.AST) -> None:
    """Give every node a ``.parent`` pointer (rules walk ancestry for
    lexical checks like with-block membership)."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def load_module(path: Path, root: Path) -> Optional[ModuleInfo]:
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, SyntaxError, ValueError):
        return None
    attach_parents(tree)
    try:
        rel = str(path.relative_to(root))
    except ValueError:
        rel = str(path)
    line_sup, file_sup = _parse_suppressions(source)
    return ModuleInfo(
        path=path,
        rel=rel,
        source=source,
        tree=tree,
        line_suppressions=line_sup,
        file_suppressions=file_sup,
    )


def iter_py_files(paths: Sequence[Path]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out


def load_project(paths: Sequence[Path], root: Optional[Path] = None) -> Project:
    paths = [Path(p) for p in paths]
    if root is None:
        root = paths[0] if paths[0].is_dir() else paths[0].parent
    modules = [
        m for f in iter_py_files(paths) if (m := load_module(f, root))
    ]
    return Project(modules=modules, root=root)


# -- driving -----------------------------------------------------------------

def run_rules(project: Project, rules: Sequence[Rule]) -> List[Finding]:
    """Run every rule over every module; returns findings (suppressed
    ones included, marked) sorted by location."""
    findings: List[Finding] = []
    for rule in rules:
        rule.prepare(project)
    for rule in rules:
        for mod in project.modules:
            for f in rule.check_module(mod, project):
                _apply_suppression(mod, f)
                findings.append(f)
        for f in rule.finalize(project):
            mod = next((m for m in project.modules if m.rel == f.path), None)
            if mod is not None:
                _apply_suppression(mod, f)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _apply_suppression(mod: ModuleInfo, f: Finding) -> None:
    sup = mod.suppression_for(f.rule, f.line)
    if sup is not None:
        f.suppressed = True
        f.suppress_reason = sup.reason


# -- shared AST helpers ------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains ('' for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        # e.g. ``socket.socket().connect`` — keep the attribute tail.
        parts.append("()")
    else:
        return ""
    return ".".join(reversed(parts))


def call_keywords(node: ast.Call) -> Set[str]:
    return {kw.arg for kw in node.keywords if kw.arg is not None}


def string_constants(tree: ast.AST) -> Iterable[Tuple[str, ast.AST]]:
    """Every string constant in the tree, f-string fragments included."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node
