"""The graft-lint rule catalog.

Each rule guards a bug class this codebase actually shipped (and fixed)
— the rule ids are stable, referenced from suppression comments and from
``docs/analysis.md``:

- **GL001 jit-purity** — host syncs inside traced functions (the zero
  steady-state-recompile / no-host-round-trip discipline of PR 7).
- **GL002 donation** — jit call sites that rebind an argument from
  their own result without declaring donation (the whole-pool-copy bug
  PR 6 fixed on the paged KV pool).
- **GL003 lock-discipline** — SQL writes outside ``with self._lock``
  in lock-carrying classes (the PR-1 archival-walk bug class).
- **GL004 tick-path blocking** — blocking calls in beat hooks, command
  handlers, and bus tasks (the ~4us bus poll and 92us alert tick are
  budgets because these paths ride every heartbeat).
- **GL005 knob-registry** — every ``POLYAXON_TPU_*`` literal resolves
  to the ``conf/knobs.py`` catalog and vice versa (a typo'd knob used
  to silently no-op).
- **GL006 net-timeout** — network I/O anywhere without an explicit
  timeout (the webhook/CLI hang class PR 9 hardened the notifier
  against).
- **GL007 metric-labels** — ``labeled_key`` label keys come from a
  closed catalog and label values are never built by interpolation
  (an unbounded identifier in a label mints one series per value —
  the cardinality-explosion class the MemoryStats series cap only
  *bounds*, never prevents).

All rules are heuristic *and lexical* — they see one module at a time
(GL004/GL005 add a project-wide index) and do not chase cross-module
call graphs.  That is the point: the invariants are local disciplines;
where code is legitimately outside a rule's shape, suppress with a
justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from polyaxon_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Project,
    Rule,
    call_keywords,
    dotted_name,
)
from polyaxon_tpu.conf.knobs import FAMILIES, KNOBS

__all__ = ["ALL_RULES", "default_rules", "rule_by_id"]


# ---------------------------------------------------------------------------
# GL001 — jit purity
# ---------------------------------------------------------------------------

#: Callables whose first positional argument is traced.
_TRACE_ENTRYPOINTS = {
    "jax.jit": 0,
    "jit": 0,
    "jax.pjit": 0,
    "pjit": 0,
    "shard_map": 0,
    "jax.shard_map": 0,
    "lax.scan": 0,
    "jax.lax.scan": 0,
    "jax.checkpoint": 0,
    "jax.remat": 0,
}

#: Dotted call names that force a host round-trip or host I/O.
_HOST_SYNC_PREFIXES = ("time.",)
_HOST_SYNC_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "onp.asarray", "onp.array",
    "jax.device_get", "jax.block_until_ready",
}
_HOST_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
_HOST_SYNC_BUILTINS = {"print", "input", "open", "breakpoint"}


def _function_defs(tree: ast.AST) -> Dict[str, List[ast.FunctionDef]]:
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _param_names(fn: ast.AST) -> Set[str]:
    args = fn.args
    names = {a.arg for a in args.args + args.posonlyargs + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


_JIT_NAMES = ("jax.jit", "jit", "jax.pjit", "pjit")


def _jit_decorator(fn: ast.AST) -> Optional[Tuple[bool, int]]:
    """(donated, lineno) if ``fn`` carries a jit decorator — plain
    ``@jax.jit``, ``@jax.jit(...)``, or ``@partial(jax.jit, ...)``."""
    for dec in getattr(fn, "decorator_list", ()):
        if dotted_name(dec) in _JIT_NAMES:
            return False, dec.lineno
        if isinstance(dec, ast.Call):
            name = dotted_name(dec.func)
            donated = bool(
                call_keywords(dec) & {"donate_argnums", "donate_argnames"}
            )
            if name in _JIT_NAMES:
                return donated, dec.lineno
            if (
                name in ("partial", "functools.partial")
                and dec.args
                and dotted_name(dec.args[0]) in _JIT_NAMES
            ):
                return donated, dec.lineno
    return None


class JitPurityRule(Rule):
    id = "GL001"
    name = "jit-purity"
    version = "1"
    doc = (
        "functions handed to jax.jit/shard_map/lax.scan must not contain "
        "host syncs (.item()/np.asarray/float(arg)), I/O (print/open), or "
        "time.* calls — each is a host round-trip or a silent recompile "
        "hazard inside the traced hot path"
    )

    def check_module(self, mod: ModuleInfo, project: Project):
        defs = _function_defs(mod.tree)
        seen: Set[int] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _jit_decorator(node) is not None and id(node) not in seen:
                    seen.add(id(node))
                    yield from self._scan_traced(mod, node)
                continue
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name not in _TRACE_ENTRYPOINTS:
                continue
            pos = _TRACE_ENTRYPOINTS[name]
            if len(node.args) <= pos:
                continue
            target = node.args[pos]
            for fn in self._resolve(target, defs):
                if id(fn) in seen:
                    continue
                seen.add(id(fn))
                yield from self._scan_traced(mod, fn)

    def _resolve(
        self, target: ast.AST, defs: Dict[str, List[ast.FunctionDef]]
    ) -> List[ast.AST]:
        if isinstance(target, ast.Lambda):
            return [target]
        if isinstance(target, ast.Name):
            return list(defs.get(target.id, ()))
        return []

    def _scan_traced(self, mod: ModuleInfo, fn: ast.AST):
        params = _param_names(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                label = getattr(fn, "name", "<lambda>")
                if name in _HOST_SYNC_BUILTINS:
                    yield self.finding(
                        mod,
                        node,
                        f"host I/O `{name}(...)` inside traced function "
                        f"`{label}`",
                    )
                elif name in _HOST_SYNC_CALLS or any(
                    name.startswith(p) for p in _HOST_SYNC_PREFIXES
                ):
                    yield self.finding(
                        mod,
                        node,
                        f"host sync `{name}(...)` inside traced function "
                        f"`{label}` — forces a device round-trip per call",
                    )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_SYNC_METHODS
                    and not node.args
                ):
                    yield self.finding(
                        mod,
                        node,
                        f"`.{node.func.attr}()` inside traced function "
                        f"`{label}` — blocks on device transfer",
                    )
                elif (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "int", "bool")
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                ):
                    yield self.finding(
                        mod,
                        node,
                        f"`{node.func.id}({node.args[0].id})` on a traced "
                        f"argument of `{label}` — concretizes the tracer "
                        "(host sync, or a trace error at runtime)",
                    )


# ---------------------------------------------------------------------------
# GL002 — donation discipline
# ---------------------------------------------------------------------------

def _target_exprs(target: ast.AST) -> List[str]:
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(_target_exprs(elt))
        return out
    name = dotted_name(target)
    return [name] if name else []


class DonationRule(Rule):
    id = "GL002"
    name = "donation"
    version = "1"
    doc = (
        "a jax.jit call site that rebinds one of its own arguments from "
        "the result (x = fn(x, ...)) must declare donate_argnums/"
        "donate_argnames on the jit — without donation XLA copies the "
        "whole buffer on every call (the paged-pool CPU-copy bug)"
    )

    def check_module(self, mod: ModuleInfo, project: Project):
        # Pass 1: names bound to jax.jit(...) results (assignment or
        # decorator form), with donation flag.
        jitted: Dict[str, Tuple[bool, int]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                dec = _jit_decorator(node)
                if dec is not None:
                    jitted[node.name] = dec
                continue
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            if dotted_name(value.func) not in _JIT_NAMES:
                continue
            tname = dotted_name(node.targets[0])
            if not tname:
                continue
            donated = bool(
                call_keywords(value) & {"donate_argnums", "donate_argnames"}
            )
            jitted[tname] = (donated, node.lineno)
        if not jitted:
            return
        # Pass 2: call sites that rebind an argument from the result.
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            fname = dotted_name(value.func)
            if fname not in jitted:
                continue
            donated, jit_line = jitted[fname]
            if donated:
                continue
            targets: List[str] = []
            for t in node.targets:
                targets.extend(_target_exprs(t))
            args = [dotted_name(a) for a in value.args]
            rebound = sorted(set(targets) & {a for a in args if a})
            if rebound:
                yield self.finding(
                    mod,
                    node,
                    f"`{fname}` (jitted at line {jit_line} without "
                    f"donate_argnums) rebinds its own argument(s) "
                    f"{', '.join(rebound)} from its result — the buffer "
                    "is copied on every call; declare donation",
                )


# ---------------------------------------------------------------------------
# GL003 — registry lock discipline
# ---------------------------------------------------------------------------

_WRITE_SQL = ("INSERT", "UPDATE", "DELETE", "REPLACE")


def _first_sql_fragment(node: ast.Call) -> Optional[str]:
    if not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr) and arg.values:
        first = arg.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


def _inside_lock_with(node: ast.AST) -> bool:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                if dotted_name(item.context_expr).endswith("._lock"):
                    return True
        cur = getattr(cur, "parent", None)
    return False


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "parent", None)
    return None


class LockDisciplineRule(Rule):
    id = "GL003"
    name = "lock-discipline"
    version = "1"
    doc = (
        "in classes that own a `self._lock`, every INSERT/UPDATE/DELETE "
        "execute() must be lexically inside `with self._lock` — a write "
        "outside the lock races concurrent writers (the archival-walk "
        "bug class); helpers called with the lock already held use the "
        "`*_locked` naming convention"
    )

    def check_module(self, mod: ModuleInfo, project: Project):
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if not self._owns_lock(cls):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Call):
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                if node.func.attr not in ("execute", "executemany"):
                    continue
                sql = _first_sql_fragment(node)
                if sql is None:
                    continue
                head = sql.lstrip().upper()
                if not head.startswith(_WRITE_SQL):
                    continue
                if _inside_lock_with(node):
                    continue
                fn = _enclosing_function(node)
                fn_name = getattr(fn, "name", "<module>")
                # Convention: *_locked helpers run with the lock held by
                # the caller — the name is the contract.
                if fn_name.endswith("_locked"):
                    continue
                verb = head.split(None, 1)[0]
                yield self.finding(
                    mod,
                    node,
                    f"{verb} executed in `{cls.name}.{fn_name}` outside a "
                    "`with self._lock` block — registry writes must hold "
                    "the write lock (rename to *_locked if the caller "
                    "holds it)",
                )

    def _owns_lock(self, cls: ast.ClassDef) -> bool:
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if dotted_name(t) == "self._lock":
                        return True
        return False


# ---------------------------------------------------------------------------
# GL004 — tick-path blocking
# ---------------------------------------------------------------------------

_REGISTRARS = {"add_beat_hook": 0, "register_handler": 1}
_TASK_DECORATORS = ("bus.register",)


def _blocking_calls(fn: ast.AST) -> Iterable[Tuple[ast.Call, str]]:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        kws = call_keywords(node)
        if name == "time.sleep":
            yield node, "time.sleep() blocks the tick thread"
        elif name.endswith("urlopen") and "timeout" not in kws:
            yield node, "urlopen() without an explicit timeout"
        elif (
            name in ("smtplib.SMTP", "smtplib.SMTP_SSL")
            and "timeout" not in kws
        ):
            yield node, f"{name}() without an explicit timeout"
        elif (
            name.startswith("subprocess.")
            and name.split(".")[-1]
            in ("run", "call", "check_call", "check_output")
            and "timeout" not in kws
        ):
            yield node, f"{name}() without an explicit timeout"
        elif name.endswith("create_connection") and "timeout" not in kws:
            yield node, f"{name}() without an explicit timeout"
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in ("wait", "join")
            and not node.args
            and "timeout" not in kws
            and dotted_name(node.func).startswith("self._thread")
        ):
            yield node, "unbounded thread wait"


class TickPathRule(Rule):
    id = "GL004"
    name = "tick-path"
    version = "1"
    doc = (
        "functions registered as reporter beat hooks, command-bus "
        "handlers (register_handler), or scheduler bus tasks ride the "
        "heartbeat/monitor tick — they must not sleep, do network I/O "
        "without a timeout, or run un-timeboxed subprocesses"
    )

    def prepare(self, project: Project) -> None:
        # Project-wide class index: `x = ClassName(...)` registrations
        # resolve methods across modules (worker.py registers
        # capture_agent.poll; CaptureAgent lives in tracking/).
        self._classes: Dict[str, Tuple[ModuleInfo, ast.ClassDef]] = {}
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self._classes.setdefault(node.name, (mod, node))
        #: (module rel, function node) resolved tick-path callables
        self._targets: List[Tuple[ModuleInfo, ast.AST, str]] = []
        for mod in project.modules:
            self._collect_targets(mod)

    def _collect_targets(self, mod: ModuleInfo) -> None:
        # Local constructor assignments: name -> class name.
        ctor_types: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tname = dotted_name(node.targets[0])
                if (
                    tname
                    and isinstance(node.value, ast.Call)
                    and dotted_name(node.value.func) in self._classes
                ):
                    ctor_types[tname] = dotted_name(node.value.func)
                elif (
                    tname
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "configure"
                ):
                    # tracking.capture.configure(...) returns the agent.
                    ctor_types.setdefault(tname, "CaptureAgent")
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func).rsplit(".", 1)[-1]
                if fname in _REGISTRARS:
                    pos = _REGISTRARS[fname]
                    if len(node.args) > pos:
                        self._resolve_target(
                            mod, node.args[pos], ctor_types, fname
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dec_call = dec.func if isinstance(dec, ast.Call) else dec
                    if dotted_name(dec_call) in _TASK_DECORATORS:
                        self._targets.append((mod, node, "bus task"))

    def _resolve_target(
        self,
        mod: ModuleInfo,
        arg: ast.AST,
        ctor_types: Dict[str, str],
        registrar: str,
    ) -> None:
        how = f"registered via {registrar}"
        if isinstance(arg, ast.Lambda):
            self._targets.append((mod, arg, how))
            return
        if isinstance(arg, ast.Name):
            for fn in _function_defs(mod.tree).get(arg.id, ()):
                self._targets.append((mod, fn, how))
            return
        if not isinstance(arg, ast.Attribute):
            return
        method = arg.attr
        base = dotted_name(arg.value)
        cls_name: Optional[str] = None
        if base == "self":
            cur = getattr(arg, "parent", None)
            while cur is not None and not isinstance(cur, ast.ClassDef):
                cur = getattr(cur, "parent", None)
            if cur is not None:
                cls_name = cur.name
        else:
            cls_name = ctor_types.get(base)
        if cls_name is None or cls_name not in self._classes:
            return
        cls_mod, cls_node = self._classes[cls_name]
        for node in cls_node.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == method
            ):
                self._targets.append(
                    (cls_mod, node, f"{how} ({cls_name}.{method})")
                )

    def check_module(self, mod: ModuleInfo, project: Project):
        for target_mod, fn, how in self._targets:
            if target_mod is not mod:
                continue
            label = getattr(fn, "name", "<lambda>")
            for call, why in _blocking_calls(fn):
                yield self.finding(
                    mod,
                    call,
                    f"blocking call in tick-path function `{label}` "
                    f"({how}): {why}",
                )


# ---------------------------------------------------------------------------
# GL005 — knob registry
# ---------------------------------------------------------------------------

import re as _re

_KNOB_TOKEN = _re.compile(r"POLYAXON_TPU_[A-Z0-9_]*")
_CATALOG_REL = "conf/knobs.py"


class KnobRegistryRule(Rule):
    id = "GL005"
    name = "knob-registry"
    version = "1"
    doc = (
        "every POLYAXON_TPU_* string literal must resolve to an entry in "
        "the conf/knobs.py catalog (exact name, declared family prefix, "
        "or family member), and every catalog entry must be referenced "
        "somewhere — a typo'd knob silently no-ops, a dead entry "
        "documents a knob that does nothing"
    )

    def prepare(self, project: Project) -> None:
        self._used: Set[str] = set()
        self._family_used: Set[str] = set()
        for mod in project.modules:
            if mod.rel.endswith(_CATALOG_REL):
                continue
            for token, _ in self._tokens(mod):
                if token in KNOBS and not KNOBS[token].prefix:
                    self._used.add(token)
                if token in FAMILIES:
                    self._family_used.add(token)
                else:
                    for fam in FAMILIES:
                        if fam != "POLYAXON_TPU_" and token.startswith(fam):
                            self._family_used.add(fam)

    def _tokens(self, mod: ModuleInfo):
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for token in _KNOB_TOKEN.findall(node.value):
                    yield token, node

    def check_module(self, mod: ModuleInfo, project: Project):
        if mod.rel.endswith(_CATALOG_REL):
            return
        for token, node in self._tokens(mod):
            if self._known(token):
                continue
            yield self.finding(
                mod,
                node,
                f"`{token}` is not in the conf/knobs.py catalog — a "
                "typo'd knob silently no-ops; declare it (or fix the "
                "name)",
            )

    def _known(self, token: str) -> bool:
        if token in KNOBS:
            return True
        if token.endswith("_"):
            # A prefix mention (docstrings: POLYAXON_TPU_WATCHDOG_*).
            return token in FAMILIES or any(
                name.startswith(token) for name in KNOBS
            )
        # Dynamic family member (POLYAXON_TPU_ALERT_MFU_LOW_FLOOR).
        return any(
            fam != "POLYAXON_TPU_" and token.startswith(fam)
            for fam in FAMILIES
        )

    def finalize(self, project: Project):
        catalog_mod = next(
            (m for m in project.modules if m.rel.endswith(_CATALOG_REL)), None
        )
        if catalog_mod is None:
            return
        for name, knob in KNOBS.items():
            used = (
                name in self._family_used if knob.prefix
                else name in self._used
            )
            if used:
                continue
            line = 1
            for i, text in enumerate(catalog_mod.source.splitlines(), 1):
                if f'"{name}"' in text:
                    line = i
                    break
            yield Finding(
                rule=self.id,
                path=catalog_mod.rel,
                line=line,
                col=0,
                message=(
                    f"dead catalog entry `{name}` — no module references "
                    "it; delete it or wire the call site through a knob "
                    "accessor"
                ),
            )


# ---------------------------------------------------------------------------
# GL006 — network timeouts, package-wide
# ---------------------------------------------------------------------------

class NetTimeoutRule(Rule):
    id = "GL006"
    name = "net-timeout"
    version = "1"
    doc = (
        "network I/O (urlopen, smtplib.SMTP, socket.create_connection, "
        "requests.*) must pass an explicit timeout everywhere — a hung "
        "endpoint must never hang the caller (CLI included: the control "
        "plane being down should error, not freeze the terminal)"
    )

    _REQUESTS = {
        "requests.get", "requests.post", "requests.put",
        "requests.delete", "requests.head", "requests.request",
    }

    def check_module(self, mod: ModuleInfo, project: Project):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            kws = call_keywords(node)
            if "timeout" in kws:
                continue
            if name.endswith("urlopen"):
                yield self.finding(
                    mod, node,
                    "urlopen() without an explicit timeout — a silent "
                    "endpoint hangs the caller forever",
                )
            elif name in ("smtplib.SMTP", "smtplib.SMTP_SSL"):
                yield self.finding(
                    mod, node,
                    f"{name}() without an explicit timeout",
                )
            elif name.endswith("socket.create_connection") or name == (
                "create_connection"
            ):
                yield self.finding(
                    mod, node,
                    "socket.create_connection() without an explicit "
                    "timeout",
                )
            elif name in self._REQUESTS:
                yield self.finding(
                    mod, node,
                    f"{name}() without an explicit timeout",
                )


# ---------------------------------------------------------------------------
# GL007 — metric label hygiene
# ---------------------------------------------------------------------------

#: The mechanism module — ``labeled_key`` itself and ``fold_labeled_key``
#: (which legitimately re-emits arbitrary label-key sets via ``**``).
_METRICS_MECHANISM_REL = "stats/metrics.py"

#: The closed label-key vocabulary.  A new label key is a schema decision
#: — every dashboard/alert joins on it — so adding one here should be a
#: deliberate, reviewed act, with a bounded value vocabulary to match.
_ALLOWED_LABEL_KEYS = {
    # control-plane self-telemetry (registry ops, tick phases, API)
    "op", "phase", "route", "method", "code",
    # alert lifecycle
    "rule", "run", "severity",
    # remediation / notifier / autoscaler
    "action", "outcome", "direction",
    # serving fleet
    "replica", "fleet",
    # metric history / burn-rate SLOs (slo_burn_* / slo_budget_remaining
    # gauges — value bounded by the per-run declared SLO names)
    "slo",
    # renderer-owned exposition labels
    "le", "component", "process", "version", "kind",
}


def _is_stringy(node: ast.AST) -> bool:
    return isinstance(node, ast.JoinedStr) or (
        isinstance(node, ast.Constant) and isinstance(node.value, str)
    )


def _interpolation_kind(value: ast.AST) -> Optional[str]:
    """How a label-value expression interpolates, or None if it doesn't.

    Lexical: flags the construction *shapes* (f-string, ``.format``,
    %-format, string concatenation) that splice an identifier into the
    value at the call site.  A plain variable passes — the cardinality
    cap is the runtime backstop for those.
    """
    if isinstance(value, ast.JoinedStr):
        return "an f-string"
    if (
        isinstance(value, ast.Call)
        and isinstance(value.func, ast.Attribute)
        and value.func.attr == "format"
    ):
        return "a .format() call"
    if isinstance(value, ast.BinOp):
        if isinstance(value.op, ast.Mod) and _is_stringy(value.left):
            return "%-formatting"
        if isinstance(value.op, ast.Add) and (
            _is_stringy(value.left) or _is_stringy(value.right)
        ):
            return "string concatenation"
    return None


class MetricLabelRule(Rule):
    id = "GL007"
    name = "metric-labels"
    version = "1"
    doc = (
        "labeled_key() label keys must come from the allowed-label "
        "catalog, and label values must not be built by interpolation "
        "(f-string/.format/%-format/concatenation) — a spliced unbounded "
        "identifier mints one series per value, growing /metrics and "
        "every snapshot without limit"
    )

    def check_module(self, mod: ModuleInfo, project: Project):
        if mod.rel.endswith(_METRICS_MECHANISM_REL):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name != "labeled_key" and not name.endswith(".labeled_key"):
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    yield self.finding(
                        mod,
                        node,
                        "labeled_key() called with a **kwargs label set — "
                        "the label keys can't be reviewed against the "
                        "allowed catalog; pass explicit keywords",
                    )
                    continue
                if kw.arg not in _ALLOWED_LABEL_KEYS:
                    yield self.finding(
                        mod,
                        kw.value,
                        f"label key `{kw.arg}` is not in the allowed "
                        "label-key catalog (analysis/rules.py:"
                        "_ALLOWED_LABEL_KEYS) — new label keys are a "
                        "metrics-schema decision; add it deliberately "
                        "with a bounded value vocabulary",
                    )
                kind = _interpolation_kind(kw.value)
                if kind is not None:
                    yield self.finding(
                        mod,
                        kw.value,
                        f"label value for `{kw.arg}` built via {kind} — "
                        "interpolating an identifier mints one series per "
                        "value; map it through a closed vocabulary first",
                    )


# ---------------------------------------------------------------------------
# GL008 — span-name hygiene
# ---------------------------------------------------------------------------

#: The tracing mechanism module — ``Tracer.record_span`` legitimately
#: re-emits whatever name a ``_Span`` carried.
_TRACE_MECHANISM_REL = "tracking/trace.py"

#: Forwarding wrappers: the ``name`` parameter flows through verbatim,
#: so the literal check applies at THEIR call sites, not inside them.
_SPAN_FORWARDERS = {"_trace_span", "_trace_hot"}

#: The closed span-name catalog.  A span name is a Perfetto track and a
#: cross-process join key — interpolating per-request/per-task values
#: into it mints one track per value; new names are a schema decision,
#: added here deliberately (the GL007 label-key pattern, applied to
#: trace spans).
_SPAN_NAMES = {
    # worker lifecycle
    "worker.cmd", "worker.distributed_init", "worker.entrypoint",
    # control plane
    "gang.spawn", "task.execute", "watcher.observe",
    # training + input pipeline
    "train.aot_compile", "train.loop", "train.step",
    "pipeline.drain", "pipeline.gather",
    # serving engine lifecycle + request phases
    "engine.compile", "serving.warmup", "serving.step", "serving.prefill",
    "serving.request", "serving.generate", "serving.admit",
    "serving.queue_wait", "serving.prefill.chunk", "serving.first_token",
    "serving.prefix_cache.hit", "serving.decode.step",
    "serving.spec.draft", "serving.spec.verify",
    "serving.park", "serving.spill", "serving.restore", "serving.finish",
    # fleet router
    "router.request", "router.attempt",
}

#: Literal shape: lowercase dot-delimited segments, at least two deep —
#: the convention every catalogued name follows.
_SPAN_NAME_SHAPE = _re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")


class SpanNameRule(Rule):
    id = "GL008"
    name = "span-names"
    version = "1"
    doc = (
        "Tracer.span()/record_span() names must be literal dot-delimited "
        "strings from the span-name catalog (analysis/rules.py:"
        "_SPAN_NAMES) — an interpolated name mints one Perfetto track "
        "per value and breaks cross-process trace merging; variable "
        "parts belong in span attributes"
    )

    def check_module(self, mod: ModuleInfo, project: Project):
        if mod.rel.endswith(_TRACE_MECHANISM_REL):
            return
        # Map every Call to its enclosing function, so the forwarding
        # wrappers' own pass-through emission is exempt.
        enclosing: Dict[ast.AST, str] = {}
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call):
                        enclosing[sub] = fn.name
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = dotted_name(node.func).rsplit(".", 1)[-1]
            if tail in ("span", "record_span"):
                arg_idx = 0
            elif tail in _SPAN_FORWARDERS:
                arg_idx = 1  # (req, name, ...)
            else:
                continue
            if len(node.args) <= arg_idx:
                continue  # keyword-form or unrelated zero-arg .span()
            arg = node.args[arg_idx]
            if isinstance(arg, ast.Constant) and not isinstance(
                arg.value, str
            ):
                continue  # e.g. re.Match.span(group)
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                if enclosing.get(node) in _SPAN_FORWARDERS:
                    continue  # the wrapper forwarding its name param
                yield self.finding(
                    mod,
                    arg,
                    f"span name passed to {tail}() is not a string "
                    "literal — interpolated names mint one Perfetto "
                    "track per value; put the variable part in a span "
                    "attribute",
                )
                continue
            value = arg.value
            if not _SPAN_NAME_SHAPE.match(value):
                yield self.finding(
                    mod,
                    arg,
                    f"span name {value!r} is not dot-delimited "
                    "(`component.phase`) — names are cross-process "
                    "join keys and follow one convention",
                )
            elif value not in _SPAN_NAMES:
                yield self.finding(
                    mod,
                    arg,
                    f"span name {value!r} is not in the span-name "
                    "catalog (analysis/rules.py:_SPAN_NAMES) — new "
                    "span names are a tracing-schema decision; add it "
                    "deliberately",
                )


# ---------------------------------------------------------------------------

ALL_RULES = [
    JitPurityRule,
    DonationRule,
    LockDisciplineRule,
    TickPathRule,
    KnobRegistryRule,
    NetTimeoutRule,
    MetricLabelRule,
    SpanNameRule,
]


def default_rules() -> List[Rule]:
    return [cls() for cls in ALL_RULES]


def rule_by_id(rule_id: str) -> Optional[type]:
    for cls in ALL_RULES:
        if cls.id == rule_id:
            return cls
    return None
