"""CLI: ``python -m polyaxon_tpu.analysis [paths] [--rules GL001,GL003]
[--format json] [--show-suppressed] [--list-rules] [--no-state]``.

Exit status 1 when any unsuppressed finding remains (``make lint`` and
CI key off this).  A successful CLI run also records a state file that
the ``check_static_analysis`` /status probe reports from; pass
``--no-state`` to skip that.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from polyaxon_tpu.analysis import default_rules, package_root, rule_by_id
from polyaxon_tpu.analysis.core import load_project, run_rules
from polyaxon_tpu.analysis.reporter import (
    render_json,
    render_text,
    write_state,
)


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m polyaxon_tpu.analysis",
        description="graft-lint: the platform's static-analysis pass",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/dirs to lint (default: the polyaxon_tpu package)",
    )
    parser.add_argument(
        "--rules", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="include suppressed findings in the report",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--no-state", action="store_true",
        help="don't record this run in the health-probe state file",
    )
    args = parser.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for rule in rules:
            print(f"{rule.id} {rule.name} (v{rule.version})")
            print(f"    {rule.doc}")
        return 0

    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            parser.error(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.id in wanted]

    paths = args.paths or [package_root()]
    project = load_project(paths)
    findings = run_rules(project, rules)

    if args.format == "json":
        print(render_json(findings, rules, args.show_suppressed))
    else:
        print(render_text(findings, rules, args.show_suppressed))

    if not args.no_state:
        try:
            write_state(findings, rules)
        except OSError:
            pass

    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
