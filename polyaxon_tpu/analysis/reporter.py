"""graft-lint output: text/JSON renderers + the state file the
``check_static_analysis`` /status probe reads."""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from polyaxon_tpu.analysis.core import Finding, Rule
from polyaxon_tpu.conf.knobs import knob_str

__all__ = [
    "render_text",
    "render_json",
    "summarize",
    "state_file_path",
    "write_state",
    "read_state",
]


def summarize(findings: Sequence[Finding], rules: Sequence[Rule]) -> Dict:
    unsuppressed = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    by_rule: Dict[str, int] = {}
    for f in unsuppressed:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "unsuppressed": len(unsuppressed),
        "suppressed": len(suppressed),
        "by_rule": by_rule,
        "rules": {r.id: {"name": r.name, "version": r.version} for r in rules},
    }


def render_text(
    findings: Sequence[Finding],
    rules: Sequence[Rule],
    show_suppressed: bool = False,
) -> str:
    lines: List[str] = []
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else ""
        lines.append(f"{f.location()}: {f.rule}{tag}: {f.message}")
    summary = summarize(findings, rules)
    lines.append(
        f"graft-lint: {summary['unsuppressed']} finding(s), "
        f"{summary['suppressed']} suppressed, "
        f"{len(rules)} rule(s)"
    )
    return "\n".join(lines)


def render_json(
    findings: Sequence[Finding],
    rules: Sequence[Rule],
    show_suppressed: bool = False,
) -> str:
    payload = {
        "findings": [
            f.as_dict()
            for f in findings
            if show_suppressed or not f.suppressed
        ],
        "summary": summarize(findings, rules),
    }
    return json.dumps(payload, indent=2, sort_keys=True)


# -- state file (read by checks/health.py:check_static_analysis) -------------

def state_file_path() -> Path:
    """Resolved lazily so tests can monkeypatch the env."""
    override = knob_str("POLYAXON_TPU_LINT_STATE")
    if override:
        return Path(override).expanduser()
    home = knob_str("POLYAXON_TPU_HOME")
    return Path(home).expanduser() / "analysis" / "last_run.json"


def write_state(
    findings: Sequence[Finding],
    rules: Sequence[Rule],
    path: Optional[Path] = None,
) -> Path:
    path = path or state_file_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = dict(summarize(findings, rules), ts=time.time())
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(payload, sort_keys=True), encoding="utf-8")
    tmp.replace(path)
    return path


def read_state(path: Optional[Path] = None) -> Optional[Dict]:
    path = path or state_file_path()
    try:
        return json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
