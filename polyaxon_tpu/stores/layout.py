"""Managed storage layout for runs.

Parity: reference ``stores/managers/base.py:11-40`` and friends —
``get_experiment_outputs_path`` / logs path / data path resolution over
NFS/S3/GCS volumes.  TPU-native: one base directory (local disk or a
mounted GCS fuse path) with a fixed per-run layout; the reports/ directory
is the worker→control-plane reporting channel (the sidecar/publisher
replacement), and checkpoints/ is first-class (the reference only manages
outputs dirs; see SURVEY §5 checkpoint/resume).
"""

from __future__ import annotations

import shutil
from dataclasses import dataclass
from pathlib import Path
from typing import Union


@dataclass(frozen=True)
class RunPaths:
    root: Path

    @property
    def spec_path(self) -> Path:
        return self.root / "spec.json"

    @property
    def outputs(self) -> Path:
        return self.root / "outputs"

    @property
    def logs(self) -> Path:
        return self.root / "logs"

    @property
    def reports(self) -> Path:
        return self.root / "reports"

    @property
    def checkpoints(self) -> Path:
        return self.root / "checkpoints"

    @property
    def commands(self) -> Path:
        """Control-plane→worker command bus root: the inverse of reports/.
        The control plane drops ``<uuid>.json`` files into per-process
        mailboxes; each worker's heartbeat thread polls its own."""
        return self.root / "commands"

    @property
    def profiles(self) -> Path:
        """On-demand capture artifacts: ``profiles/<capture_id>/proc<N>/``."""
        return self.root / "profiles"

    @property
    def code(self) -> Path:
        return self.root / "code"

    def report_file(self, process_id: int) -> Path:
        return self.reports / f"proc{process_id}.jsonl"

    def log_file(self, process_id: int) -> Path:
        return self.logs / f"proc{process_id}.log"

    def command_dir(self, process_id: int) -> Path:
        return self.commands / f"proc{process_id}"

    def ensure(self) -> "RunPaths":
        for p in (self.root, self.outputs, self.logs, self.reports,
                  self.checkpoints, self.commands):
            p.mkdir(parents=True, exist_ok=True)
        return self


class StoreLayout:
    """Resolves per-run and shared paths under one base directory."""

    def __init__(self, base_dir: Union[str, Path]) -> None:
        self.base_dir = Path(base_dir)
        self.base_dir.mkdir(parents=True, exist_ok=True)

    @property
    def runs_dir(self) -> Path:
        return self.base_dir / "runs"

    @property
    def snapshots_dir(self) -> Path:
        return self.base_dir / "snapshots"

    @property
    def data_dir(self) -> Path:
        return self.base_dir / "data"

    @property
    def compile_cache_dir(self) -> Path:
        """Shared persistent XLA compile cache: gang members and
        successive runs of the same store reuse compiled executables
        (see ``runtime/compilecache.py``)."""
        return self.base_dir / "compile_cache"

    @property
    def kv_cache_dir(self) -> Path:
        """Shared persistent prefix-KV store (``serving/kvstore.py``):
        serving replicas snapshot their hot prefix blocks here, and
        replacement/scale-up replicas preload them during warmup — the
        compile-cache pattern applied to KV state, so a new replica
        boots prefix-warm as well as compile-warm."""
        return self.base_dir / "kv_cache"

    def run_paths(self, run_uuid: str) -> RunPaths:
        return RunPaths(self.runs_dir / run_uuid)

    def copy_outputs(self, from_uuid: str, to_uuid: str) -> None:
        """COPY cloning strategy: duplicate a run's outputs+checkpoints.

        Parity: reference ``scheduler/tasks/experiments.py:27-56``
        (``copy_experiment`` via stores).
        """
        src = self.run_paths(from_uuid)
        dst = self.run_paths(to_uuid).ensure()
        for sub in ("outputs", "checkpoints"):
            s, d = src.root / sub, dst.root / sub
            if s.exists():
                shutil.copytree(s, d, dirs_exist_ok=True)
