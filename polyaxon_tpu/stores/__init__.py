from polyaxon_tpu.stores.artifacts import (
    ArtifactStore,
    GsutilArtifactStore,
    LocalArtifactStore,
    artifact_store_from_url,
    gc_run_data,
    run_prefix,
    sync_run_down,
    sync_run_up,
)
from polyaxon_tpu.stores.layout import RunPaths, StoreLayout
from polyaxon_tpu.stores.snapshots import create_snapshot, materialize_snapshot

__all__ = [
    "StoreLayout",
    "RunPaths",
    "create_snapshot",
    "materialize_snapshot",
    "ArtifactStore",
    "LocalArtifactStore",
    "GsutilArtifactStore",
    "artifact_store_from_url",
    "run_prefix",
    "sync_run_up",
    "sync_run_down",
    "gc_run_data",
]
