from polyaxon_tpu.stores.layout import RunPaths, StoreLayout
from polyaxon_tpu.stores.snapshots import create_snapshot, materialize_snapshot

__all__ = ["StoreLayout", "RunPaths", "create_snapshot", "materialize_snapshot"]
