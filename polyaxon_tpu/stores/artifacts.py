"""Pluggable artifact stores: ship run outputs/checkpoints/logs off-box.

Parity: reference ``stores/managers/base.py:11-40`` (``StoreManager`` with
``ls/upload_file/download_file/upload_dir/download_dir``) and the external
polystores backends (S3/GCS/Azure).  TPU-native framing: the run directory
on a TPU-VM slice lives on ephemeral local disk (or a small NFS export), so
durable artifacts — orbax checkpoints, outputs, collected logs — are synced
to an addressable store keyed by run uuid.  Two backends ship:

- :class:`LocalArtifactStore` — a ``file://`` (or bare-path) rooted tree
  with copy semantics.  This is also the "mounted remote" backend: point it
  at a gcsfuse/NFS mountpoint and the copy IS the upload.
- :class:`GsutilArtifactStore` — ``gs://bucket/prefix`` via the ``gsutil``
  CLI (present on stock TPU-VM images), no SDK dependency.

Keys are ``/``-separated relative paths; a run's artifacts live under
``runs/<uuid>/{outputs,checkpoints,logs}/...`` (see :func:`run_prefix`).
"""

from __future__ import annotations

import shutil
import subprocess
from pathlib import Path
from typing import BinaryIO, Callable, List, Optional, Sequence, Union

from polyaxon_tpu.exceptions import PolyaxonTPUError

#: Run subdirectories that sync to/from the store (reports/ and commands/
#: are the live worker↔control-plane channels and stay local; code/ is
#: snapshot-addressed).  profiles/ carries on-demand capture artifacts
#: (xplane traces, device-memory snapshots, HLO text) — durable like
#: outputs, so a capture survives its host.
RUN_SYNC_SUBDIRS = ("outputs", "checkpoints", "logs", "profiles")


def run_prefix(run_uuid: str) -> str:
    return f"runs/{run_uuid}"


class ArtifactStore:
    """Key-addressed blob store with tree sync helpers.

    Subclasses implement the five primitives; ``upload_tree`` /
    ``download_tree`` are derived (backends with a native recursive copy —
    gsutil ``cp -r`` — override them).
    """

    url: str = ""

    # -- primitives -----------------------------------------------------------
    def put_file(self, local: Union[str, Path], key: str) -> None:
        raise NotImplementedError

    def get_file(self, key: str, local: Union[str, Path]) -> None:
        raise NotImplementedError

    def list(self, prefix: str = "") -> List[str]:
        """All keys under ``prefix`` (recursive)."""
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete(self, prefix: str) -> int:
        """Remove every key under ``prefix``; returns how many."""
        raise NotImplementedError

    # -- derived --------------------------------------------------------------
    def open(self, key: str) -> BinaryIO:
        """Stream a key's bytes (download-to-temp default).

        The temp file is unlinked immediately after opening (POSIX keeps
        the inode alive for the handle), so the payload is never held in
        memory and nothing leaks on close.
        """
        import os
        import tempfile

        fd, name = tempfile.mkstemp(prefix="polyaxon-tpu-artifact-")
        os.close(fd)
        try:
            self.get_file(key, name)
            f = open(name, "rb")
        finally:
            os.unlink(name)
        return f

    def upload_tree(self, local_dir: Union[str, Path], prefix: str) -> int:
        """Upload every file under ``local_dir`` to ``prefix/<relpath>``."""
        local_dir = Path(local_dir)
        if not local_dir.is_dir():
            return 0
        n = 0
        for p in sorted(local_dir.rglob("*")):
            if p.is_file():
                self.put_file(p, f"{prefix}/{p.relative_to(local_dir).as_posix()}")
                n += 1
        return n

    def download_tree(self, prefix: str, local_dir: Union[str, Path]) -> int:
        """Download every key under ``prefix`` into ``local_dir``."""
        local_dir = Path(local_dir)
        n = 0
        pre = prefix.rstrip("/") + "/"
        for key in self.list(prefix):
            rel = key[len(pre):] if key.startswith(pre) else key
            self.get_file(key, local_dir / rel)
            n += 1
        return n


class LocalArtifactStore(ArtifactStore):
    """``file://``-rooted store: keys are paths under one root directory."""

    def __init__(self, root: Union[str, Path]) -> None:
        self.root = Path(root).resolve()
        self.root.mkdir(parents=True, exist_ok=True)
        self.url = f"file://{self.root}"

    def _path(self, key: str) -> Path:
        p = (self.root / key).resolve()
        # A key like "../../etc" must not escape the root.
        if not p.is_relative_to(self.root):
            raise PolyaxonTPUError(f"Artifact key escapes store root: {key!r}")
        return p

    def put_file(self, local: Union[str, Path], key: str) -> None:
        dst = self._path(key)
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(local, dst)

    def get_file(self, key: str, local: Union[str, Path]) -> None:
        src = self._path(key)
        if not src.is_file():
            raise PolyaxonTPUError(f"Artifact not found: {key!r} in {self.url}")
        local = Path(local)
        local.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(src, local)

    def open(self, key: str) -> BinaryIO:
        src = self._path(key)
        if not src.is_file():
            raise PolyaxonTPUError(f"Artifact not found: {key!r} in {self.url}")
        return src.open("rb")

    def list(self, prefix: str = "") -> List[str]:
        base = self._path(prefix) if prefix else self.root
        if not base.is_dir():
            return []
        return sorted(
            p.relative_to(self.root).as_posix()
            for p in base.rglob("*")
            if p.is_file()
        )

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()

    def delete(self, prefix: str) -> int:
        base = self._path(prefix)
        if base.is_file():
            base.unlink()
            return 1
        if not base.is_dir():
            return 0
        n = sum(1 for p in base.rglob("*") if p.is_file())
        shutil.rmtree(base)
        return n


class GsutilArtifactStore(ArtifactStore):
    """``gs://bucket/prefix`` via the gsutil CLI (stock on TPU-VM images).

    ``runner`` is injectable so the command builder is unit-testable without
    a bucket; the default shells out with check=True.
    """

    def __init__(
        self,
        url: str,
        runner: Optional[Callable[[Sequence[str]], "subprocess.CompletedProcess"]] = None,
    ) -> None:
        if not url.startswith("gs://"):
            raise PolyaxonTPUError(f"Not a gs:// url: {url!r}")
        self.url = url.rstrip("/")
        self._run = runner or self._default_runner

    @staticmethod
    def _default_runner(cmd: Sequence[str]) -> "subprocess.CompletedProcess":
        if shutil.which("gsutil") is None:
            raise PolyaxonTPUError(
                "gsutil not found on PATH; use a file:// artifacts url or "
                "install the Cloud SDK"
            )
        return subprocess.run(
            list(cmd), check=True, capture_output=True, text=True
        )

    def _gs(self, key: str) -> str:
        return f"{self.url}/{key}" if key else self.url

    #: stderr markers gsutil emits for a genuinely-missing object — anything
    #: else (auth, network, quota) must surface as an error, not a miss.
    _NOT_FOUND_MARKERS = ("No URLs matched", "matched no objects", "NotFoundException")

    @classmethod
    def _is_not_found(cls, e: "subprocess.CalledProcessError") -> bool:
        stderr = e.stderr or ""
        return any(m in stderr for m in cls._NOT_FOUND_MARKERS)

    def put_file(self, local: Union[str, Path], key: str) -> None:
        self._run(["gsutil", "-q", "cp", str(local), self._gs(key)])

    def get_file(self, key: str, local: Union[str, Path]) -> None:
        local = Path(local)
        local.parent.mkdir(parents=True, exist_ok=True)
        try:
            self._run(["gsutil", "-q", "cp", self._gs(key), str(local)])
        except subprocess.CalledProcessError as e:
            if self._is_not_found(e):
                raise PolyaxonTPUError(
                    f"Artifact not found: {key!r} in {self.url}"
                ) from e
            raise

    def list(self, prefix: str = "") -> List[str]:
        try:
            proc = self._run(["gsutil", "ls", "-r", self._gs(prefix) + "/**"])
        except subprocess.CalledProcessError as e:
            # gsutil ls on an empty prefix exits 1 with "matched no objects".
            if self._is_not_found(e):
                return []
            raise
        base = self.url + "/"
        return sorted(
            line[len(base):]
            for line in (proc.stdout or "").splitlines()
            if line.startswith(base) and not line.endswith("/")
        )

    def exists(self, key: str) -> bool:
        try:
            self._run(["gsutil", "-q", "stat", self._gs(key)])
            return True
        except subprocess.CalledProcessError as e:
            # `gsutil stat` exits 1 with no marker for a missing object but
            # keeps stderr empty; auth/network failures write to stderr and
            # must not masquerade as "not found" (an operator would read a
            # 404 as data loss).
            if not (e.stderr or "").strip() or self._is_not_found(e):
                return False
            raise

    def delete(self, prefix: str) -> int:
        keys = self.list(prefix)
        if keys:
            self._run(["gsutil", "-q", "-m", "rm", "-r", self._gs(prefix)])
        return len(keys)

    def upload_tree(self, local_dir: Union[str, Path], prefix: str) -> int:
        local_dir = Path(local_dir)
        if not local_dir.is_dir():
            return 0
        n = sum(1 for p in local_dir.rglob("*") if p.is_file())
        if n:
            # Trailing-dot source: copy the *contents* of local_dir.
            self._run(
                ["gsutil", "-q", "-m", "cp", "-r", f"{local_dir}/.", self._gs(prefix)]
            )
        return n

    def download_tree(self, prefix: str, local_dir: Union[str, Path]) -> int:
        keys = self.list(prefix)
        if keys:
            local_dir = Path(local_dir)
            local_dir.mkdir(parents=True, exist_ok=True)
            self._run(
                ["gsutil", "-q", "-m", "cp", "-r", self._gs(prefix) + "/*", str(local_dir)]
            )
        return len(keys)


def artifact_store_from_url(url: str) -> ArtifactStore:
    """Scheme-dispatched construction: ``file://``/bare path or ``gs://``.

    The scheme registry mirrors the reference's store-type dispatch
    (``stores/validators.py`` volume-claim vs cloud-store selection).
    """
    url = url.strip()
    if not url:
        raise PolyaxonTPUError("Empty artifact store url")
    if url.startswith("gs://"):
        return GsutilArtifactStore(url)
    if url.startswith("file://"):
        return LocalArtifactStore(url[len("file://"):])
    if url.startswith("/") or url.startswith("."):
        return LocalArtifactStore(url)
    raise PolyaxonTPUError(
        f"Unsupported artifact store url {url!r} (use file:///path or gs://bucket/prefix)"
    )


#: Store key prefix for the shared prefix-KV store (layout-level, not
#: per-run: every replica of every serving run reads the same warm set).
KV_CACHE_PREFIX = "kv_cache"


def sync_kv_cache_up(store: ArtifactStore, layout) -> int:
    """Upload the layout's persistent prefix-KV store (complete
    snapshots + markers); returns file count.  Marker files ride along
    with their data dirs, so a partially uploaded tree at worst loses
    the newest version — never trusts a torn one."""
    return store.upload_tree(layout.kv_cache_dir, KV_CACHE_PREFIX)


def sync_kv_cache_down(store: ArtifactStore, layout) -> int:
    """Restore the prefix-KV store onto a fresh host (new TPU-VM slice)
    before its replicas boot, so warm boot survives host replacement
    exactly like the compile cache does."""
    return store.download_tree(KV_CACHE_PREFIX, layout.kv_cache_dir)


# -- run-level sync -----------------------------------------------------------
def sync_run_up(store: ArtifactStore, run_paths, run_uuid: str) -> int:
    """Upload a run's durable subdirs to ``runs/<uuid>/``; returns file count."""
    n = 0
    for sub in RUN_SYNC_SUBDIRS:
        local = run_paths.root / sub
        n += store.upload_tree(local, f"{run_prefix(run_uuid)}/{sub}")
    return n


def sync_run_down(store: ArtifactStore, run_paths, run_uuid: str) -> int:
    """Restore a run's durable subdirs from the store into its run dir."""
    n = 0
    for sub in RUN_SYNC_SUBDIRS:
        n += store.download_tree(
            f"{run_prefix(run_uuid)}/{sub}", run_paths.root / sub
        )
    return n


def gc_run_data(layout, store: "ArtifactStore | None", victims) -> None:
    """Remove deleted runs' local dirs and durable store trees.

    The one GC body behind every deletion path (user DELETE, project
    cascade, archived-retention cron) so they can't drift apart.  A
    failed store delete is logged, never raised: data GC must not block
    row deletion (the reference's deletion tasks swallow store errors
    the same way)."""
    import logging
    import shutil

    for v in victims:
        shutil.rmtree(layout.run_paths(v.uuid).root, ignore_errors=True)
        if store is not None:
            try:
                store.delete(run_prefix(v.uuid))
            except Exception:  # noqa: BLE001 — GC must not block deletion
                logging.getLogger(__name__).warning(
                    "Artifact GC failed for %s", v.uuid, exc_info=True
                )
