"""Content-addressed code snapshots: the dockerizer replacement.

Parity: reference ``dockerizer/`` (download + extract + generate + build
image, ``dockerizer/dockerizer/initializer/*``) and the scheduler's
image-exists short-circuit (``scheduler/dockerizer_scheduler.py:30-88``).
TPU-native: no containers — a run's code is the set of files matched by its
``BuildConfig``, hashed (sha256 over paths+contents) and stored once under
``snapshots/<hash>/``; identical code re-uses the existing snapshot exactly
like the reference re-uses a built image.
"""

from __future__ import annotations

import hashlib
import shutil
import uuid
from pathlib import Path
from typing import List, Union

from polyaxon_tpu.exceptions import StoreError
from polyaxon_tpu.schemas.run import BuildConfig


def _matched_files(build: BuildConfig, source_dir: Path) -> List[Path]:
    included: set = set()
    for pattern in build.include:
        included.update(p for p in source_dir.glob(pattern) if p.is_file())
    excluded: set = set()
    for pattern in build.exclude:
        excluded.update(source_dir.glob(pattern))
    # An excluded directory prunes everything under it.
    def is_excluded(p: Path) -> bool:
        return any(p == e or (e.is_dir() and e in p.parents) for e in excluded)

    return sorted(p for p in included if not is_excluded(p))


def _snapshot_walk(
    build: BuildConfig, source_dir: Path, write_dir: Union[Path, None] = None
) -> str:
    """Hash matched files, optionally streaming them into ``write_dir``.

    One walk, one read per file: the bytes fed to the hasher are exactly the
    bytes stored, so a file edited mid-snapshot can't be cached under the
    wrong content hash. Streams in chunks (no whole-context buffering) and
    preserves file modes (exec bits) via ``copystat``.
    """
    h = hashlib.sha256()
    for path in _matched_files(build, source_dir):
        rel = path.relative_to(source_dir)
        h.update(str(rel).encode())
        if write_dir is None:
            with path.open("rb") as src:
                while chunk := src.read(1 << 20):
                    h.update(chunk)
        else:
            target = write_dir / rel
            target.parent.mkdir(parents=True, exist_ok=True)
            with path.open("rb") as src, target.open("wb") as dst:
                while chunk := src.read(1 << 20):
                    h.update(chunk)
                    dst.write(chunk)
            shutil.copystat(path, target)
    return h.hexdigest()[:16]


def snapshot_hash(build: BuildConfig, source_dir: Union[str, Path]) -> str:
    return _snapshot_walk(build, Path(source_dir))


def create_snapshot(
    build: BuildConfig,
    source_dir: Union[str, Path],
    snapshots_dir: Union[str, Path],
) -> str:
    """Snapshot matched files; returns the content hash (idempotent)."""
    source_dir = Path(source_dir)
    if build.ref:  # pin to a pre-existing snapshot
        ref_dir = Path(snapshots_dir) / build.ref
        if not ref_dir.exists():
            raise StoreError(f"Snapshot ref {build.ref!r} does not exist")
        return build.ref
    if not source_dir.exists():
        raise StoreError(f"Build context {source_dir} does not exist")
    # Stream into a staging dir while hashing (the ref isn't known until the
    # walk ends), then rename to the hash-named dest.
    snapshots_dir = Path(snapshots_dir)
    staging = snapshots_dir / f".staging-{uuid.uuid4().hex}"
    staging.mkdir(parents=True, exist_ok=True)  # snapshot may be empty
    try:
        ref = _snapshot_walk(build, source_dir, staging)
        dest = snapshots_dir / ref
        if dest.exists():  # image-exists short-circuit
            shutil.rmtree(staging)
            return ref
        try:
            staging.rename(dest)
        except OSError:
            # A concurrent builder won the rename race — the snapshot we
            # wanted now exists; identical content, so just use it.
            if dest.exists():
                shutil.rmtree(staging, ignore_errors=True)
            else:
                raise
    except BaseException:
        shutil.rmtree(staging, ignore_errors=True)
        raise
    return ref


def materialize_snapshot(
    ref: str,
    snapshots_dir: Union[str, Path],
    dest: Union[str, Path],
    symlink: bool = True,
) -> Path:
    """Expose snapshot ``ref`` at ``dest`` (symlink by default: read-only use)."""
    src = Path(snapshots_dir) / ref
    if not src.exists():
        raise StoreError(f"Snapshot {ref!r} not found in {snapshots_dir}")
    dest = Path(dest)
    if dest.is_symlink() or dest.exists():
        if dest.is_symlink() or dest.is_file():
            dest.unlink()
        else:
            shutil.rmtree(dest)
    dest.parent.mkdir(parents=True, exist_ok=True)
    if symlink:
        dest.symlink_to(src, target_is_directory=True)
    else:
        shutil.copytree(src, dest)
    return dest
