"""Scheduler tasks: the build → start → monitor → done chain.

Parity: reference ``scheduler/tasks/experiments.py:59-103`` (build→start),
``scheduler/experiment_scheduler.py:563-660`` (spawner driving +
SCHEDULED/STARTING bookkeeping), the monitor/reconcile stack (§3.2), the
heartbeat zombie cron (``scheduler/tasks/experiments.py:111-120``), and the
gang restart policy (``polypod/templates/restart_policy.py``).

All tasks are closures over one :class:`SchedulerContext` so orchestration
state (active gang handles) lives in a single place.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from polyaxon_tpu.auditor import Auditor
from polyaxon_tpu.compiler import compile_gang_plan
from polyaxon_tpu.db.registry import RegistryError, RunRegistry
from polyaxon_tpu.events import EventTypes
from polyaxon_tpu.exceptions import PolyaxonTPUError
from polyaxon_tpu.lifecycles import StatusOptions as S
from polyaxon_tpu.monitor import AlertEngine, GangWatcher, RemediationEngine
from polyaxon_tpu.spawner import GangHandle, GangSpawner
from polyaxon_tpu.stats.metrics import labeled_key
from polyaxon_tpu.stores import StoreLayout, create_snapshot
from polyaxon_tpu.workers import CronTasks, SchedulerTasks, TaskBus

logger = logging.getLogger(__name__)

#: Self-managed retry budget for offloaded artifact uploads (the bus Retry
#: budget can't cover them — they run off the bus thread).
ARTIFACT_SYNC_MAX_ATTEMPTS = 20


@dataclass
class SchedulerContext:
    registry: RunRegistry
    bus: TaskBus
    auditor: Auditor
    layout: StoreLayout
    spawner: GangSpawner
    watcher: GangWatcher
    #: Alert rule engine, ticked by the monitor task alongside the watcher
    #: (None = alerting off, e.g. minimal test stands).
    alerts: Optional[AlertEngine] = None
    #: Remediation policy engine — acts on alert firing edges and decides
    #: the relaunch (resume-from-checkpoint, backoff, budget).  None =
    #: legacy blind restart (minimal test stands).
    remediation: Optional[RemediationEngine] = None
    #: Live gang handles keyed by run id (the reference keeps equivalent
    #: state in k8s; a single-service control plane keeps it in-process).
    gangs: Dict[int, GangHandle] = field(default_factory=dict)
    monitor_interval: float = 0.2
    heartbeat_ttl: float = 600.0
    #: How long a logically-done gang may keep live members before the
    #: spawner forces them down (survivors hung in collectives).
    terminal_grace: float = 10.0
    #: Consecutive monitor-poll failures before the run is failed outright.
    monitor_failure_streak: int = 25
    #: How long a run may sit in QUEUED before the cron re-dispatches it.
    queued_redispatch_ttl: float = 60.0
    #: Durable artifact store (None = off-box sync disabled).
    artifact_store: Optional[object] = None
    #: Metric-history scraper (``stats.tsdb.MetricScraper``), ticked by
    #: the monitor task as its own phase; None = metric history off.
    scraper: Optional[object] = None


def _record_done(
    ctx: SchedulerContext, run_id: int, status: str, actor: Optional[str] = None
) -> None:
    # Terminal = the gang's slice goes back into the inventory; freed
    # capacity immediately re-dispatches runs queued at admission.
    if ctx.registry.release_devices(run_id):
        ctx.bus.send(SchedulerTasks.ADMISSION_CHECK, {})
    # Resolve any still-open bus commands (profile etc.) to a typed EXPIRED
    # state — a command against a gang that just finished must answer, not
    # hang PENDING forever.
    expired = ctx.registry.expire_commands(run_id)
    if expired:
        logger.info("Expired %d open command(s) on finished run %s", expired, run_id)
    if ctx.alerts is not None:
        # Close the alert lifecycle with the run: firing → resolved ("run
        # finished"), pendings dropped, alert_state gauges back to 0.
        try:
            ctx.alerts.finalize(run_id)
        except Exception:
            logger.warning(
                "Alert finalize failed for run %s", run_id, exc_info=True
            )
    if ctx.remediation is not None:
        # Mirror the command expiry above: an action row never hangs open
        # past the run's terminal state.
        try:
            ctx.remediation.finalize(run_id)
        except Exception:
            logger.warning(
                "Remediation finalize failed for run %s", run_id, exc_info=True
            )
    run = ctx.registry.get_run(run_id)
    if run.service_url:
        # A terminal service must stop advertising its (now dead) URL.
        ctx.registry.update_run(run_id, service_url=None)
    if status == S.SUCCEEDED:
        # Fold the run's summary series (MFU, goodput, tokens/s, spec
        # acceptance) into its (project, kind) regression baseline, then
        # judge the run against the baseline as it stood *before* the
        # fold — the metric_regression verdict the canary
        # promote/rollback comparator reads.
        try:
            from polyaxon_tpu.conf.knobs import knob_float
            from polyaxon_tpu.stats.tsdb import fold_run_baselines

            folded = fold_run_baselines(
                ctx.registry,
                run,
                alpha=knob_float("POLYAXON_TPU_BASELINE_ALPHA"),
            )
            if folded and ctx.alerts is not None:
                ctx.alerts.evaluate_regression(run, folded)
        except Exception:
            logger.warning(
                "Baseline fold failed for run %s", run_id, exc_info=True
            )
    by_status = {
        S.SUCCEEDED: EventTypes.EXPERIMENT_SUCCEEDED,
        S.FAILED: EventTypes.EXPERIMENT_FAILED,
        S.STOPPED: EventTypes.EXPERIMENT_STOPPED,
    }
    extra = {"actor": actor} if actor else {}
    if status in by_status:
        ctx.auditor.record(by_status[status], run_id=run_id, **extra)
    ctx.auditor.record(
        EventTypes.EXPERIMENT_DONE,
        run_id=run_id,
        status=status,
        group_id=run.group_id,
        pipeline_id=run.pipeline_id,
    )
    if ctx.artifact_store is not None:
        # Ship durable artifacts (outputs/checkpoints/logs) off-box once the
        # gang is fully down and the watcher flushed its final ingest.
        ctx.bus.send(SchedulerTasks.ARTIFACTS_SYNC, {"run_id": run_id})


def register_scheduler_tasks(ctx: SchedulerContext) -> None:
    bus = ctx.bus
    reg = ctx.registry
    # Tick-phase self-telemetry rides the watcher's backend (None on
    # minimal test stands → every phase probe is a no-op).
    stats = getattr(ctx.watcher, "stats", None)
    phase_keys = {
        phase: labeled_key("tick_phase_s", phase=phase)
        for phase in ("watcher", "alerts", "remediation", "retention", "scrape")
    }

    def _observe_phase(phase: str, seconds: float) -> None:
        if stats is not None:
            stats.observe(phase_keys[phase], seconds)

    @bus.register(SchedulerTasks.EXPERIMENTS_BUILD)
    def experiments_build(run_id: int) -> None:
        run = reg.get_run(run_id)
        if run.is_done:
            return
        spec = run.spec
        build = getattr(spec, "build", None)
        if build is not None and run.code_ref is None:
            if not reg.set_status(run_id, S.BUILDING):
                return
            ctx.auditor.record(EventTypes.EXPERIMENT_BUILD_STARTED, run_id=run_id)
            try:
                ref = create_snapshot(build, build.context, ctx.layout.snapshots_dir)
            except Exception as e:
                reg.set_status(run_id, S.FAILED, message=f"build failed: {e}")
                _record_done(ctx, run_id, S.FAILED)
                return
            reg.update_run(run_id, code_ref=ref)
            _maybe_trigger_ci(reg.get_run(run_id), ref)
        ctx.auditor.record(EventTypes.EXPERIMENT_BUILD_DONE, run_id=run_id)

    def _maybe_trigger_ci(run, code_ref: str) -> None:
        """New code snapshot in a CI-enabled project → submit its CI spec.

        Parity: the reference triggers ``ci.trigger(project)`` from its
        repo-upload views (``api/repos/views.py:162``); here code arrives
        as a content-hashed snapshot during the build step, so the hash IS
        the commit.  The 'ci' tag guards against self-retrigger loops, and
        ``advance_ci_code_ref``'s atomic check-and-set makes concurrent
        builds of the same ref fire exactly one CI run.
        """
        # Self-retrigger guard: the CI run itself AND its descendants (a CI
        # group's trials, a CI pipeline's ops) must not fire CI — walk up
        # the parent chain looking for the 'ci' tag.
        node, hops = run, 0
        while node is not None and hops < 8:
            if "ci" in node.tags:
                return
            parent_id = node.group_id or node.pipeline_id
            node = reg.get_run(parent_id) if parent_id else None
            hops += 1
        ci = reg.get_project_ci(run.project)
        if ci is None:
            return
        if not reg.advance_ci_code_ref(run.project, code_ref):
            return
        from polyaxon_tpu.ci import submit_ci_run

        try:
            submit_ci_run(reg, ctx.auditor, run.project, ci["spec"], code_ref)
        except PolyaxonTPUError as e:
            logger.warning("CI trigger for %s failed: %s", run.project, e)

    @bus.register(SchedulerTasks.EXPERIMENTS_START)
    def experiments_start(run_id: int) -> None:
        run = reg.get_run(run_id)
        if run.is_done:
            return
        try:
            plan = compile_gang_plan(run.spec)
        except PolyaxonTPUError as e:
            reg.set_status(run_id, S.FAILED, message=f"compile failed: {e}")
            _record_done(ctx, run_id, S.FAILED)
            return
        if ctx.remediation is not None:
            # A straggler eviction recorded an elastic topology override in
            # the run's meta — every (re)launch re-applies it so the gang
            # stays on the smaller mesh across further restarts.
            try:
                plan = ctx.remediation.apply_elastic_plan(run, plan)
            except Exception:
                logger.warning(
                    "Elastic plan override failed for run %s", run_id,
                    exc_info=True,
                )
        # Gang admission (reference: scheduler/experiment_scheduler.py's
        # k8s-delegated placement; here an explicit slice inventory). No
        # inventory for the family → admission is off; otherwise the run
        # holds a whole slice from SCHEDULED until terminal.
        try:
            device = reg.acquire_device(
                run_id,
                plan.accelerator,
                plan.num_devices,
                num_slices=plan.num_slices,
                num_hosts=plan.num_hosts,
            )
        except PolyaxonTPUError as e:
            # E.g. a chips/num_slices mismatch: a caller bug, but it must
            # surface on the run (FAILED) — escaping the task would strand
            # the run in CREATED forever.
            reg.set_status(run_id, S.FAILED, message=f"admission failed: {e}")
            _record_done(ctx, run_id, S.FAILED)
            return
        if device is None:
            # Queue at admission: the QUEUED re-dispatch cron and the
            # release hook both retry this run later.
            reg.set_status(
                run_id,
                S.QUEUED,
                message=f"waiting for a free {plan.accelerator} slice "
                f"({plan.num_devices} chips)",
            )
            return
        if not reg.set_status(run_id, S.SCHEDULED):
            logger.warning("Run %s not schedulable from %s", run_id, run.status)
            if not device.get("unmanaged") and not device.get("already_held"):
                # This dispatch lost the race but newly claimed a slice:
                # give it back (the winning dispatch holds its own).
                reg.release_devices(run_id)
            return
        if plan.service_port is not None:
            # Service gang: pin the serving port now (the plan's 0 defers
            # to dispatch) — the reference's service object + proxy URL
            # equivalent.
            import dataclasses

            port = plan.service_port or ctx.spawner.allocate_service_port(run)
            plan = dataclasses.replace(
                plan,
                service_port=port,
                env_vars={
                    **plan.env_vars,
                    "POLYAXON_TPU_SERVICE_PORT": str(port),
                },
            )
        try:
            from polyaxon_tpu.tracking.trace import get_tracer

            with get_tracer().span(
                "gang.spawn", run_id=run_id, hosts=plan.num_hosts
            ):
                handle = ctx.spawner.start(run, plan)
        except Exception as e:  # disk-full/permission OSErrors included —
            # anything escaping here would strand the run in SCHEDULED,
            # a status the zombie cron never scans.
            reg.set_status(run_id, S.UNSCHEDULABLE, message=str(e))
            reg.set_status(run_id, S.FAILED, message=str(e))
            _record_done(ctx, run_id, S.FAILED)
            return
        ctx.gangs[run_id] = handle
        if plan.service_port is not None:
            # Advertise only once the gang actually launched; cleared again
            # when the run goes terminal (a dead URL must not linger).
            reg.update_run(
                run_id,
                service_url=f"http://{ctx.spawner.host_for(0)}:{plan.service_port}",
            )
        for process_id in range(plan.num_hosts):
            reg.upsert_process(
                run_id, process_id, pid=handle.processes[process_id].pid, status=S.STARTING
            )
        reg.set_status(run_id, S.STARTING)
        bus.send(
            SchedulerTasks.EXPERIMENTS_MONITOR,
            {"run_id": run_id},
            countdown=ctx.monitor_interval,
        )

    def _reschedule_monitor(run_id: int) -> None:
        # A fresh send, NOT Retry: the monitor loop is unbounded by design
        # and must not consume the bus's error-retry budget.
        bus.send(
            SchedulerTasks.EXPERIMENTS_MONITOR,
            {"run_id": run_id},
            countdown=ctx.monitor_interval,
        )

    @bus.register(SchedulerTasks.EXPERIMENTS_MONITOR)
    def experiments_monitor(run_id: int) -> None:
        t0 = time.perf_counter()
        try:
            _monitor_tick(run_id)
        finally:
            if stats is not None:
                stats.observe("monitor_tick_s", time.perf_counter() - t0)

    def _monitor_tick(run_id: int) -> None:
        handle = ctx.gangs.get(run_id)
        if handle is None:
            return
        # Tick lag: how far past its scheduled cadence this poll fired —
        # near-zero while the bus keeps up, climbing when monitor ticks
        # queue behind other work (the first visible symptom of a
        # saturated control plane).
        now = time.monotonic()
        last = getattr(handle, "last_monitor_at", None)
        if stats is not None and last is not None:
            expected = ctx.monitor_interval * ctx.bus.time_scale
            stats.gauge("monitor_tick_lag_s", max(0.0, (now - last) - expected))
        handle.last_monitor_at = now
        if ctx.scraper is not None:
            # Metric-history scrape: runs every tick but internally
            # throttled to its own cadence, so a not-due pass costs
            # microseconds and per-run tick fan-out doesn't multiply the
            # cost.  Never poll-fatal.
            phase_t0 = time.perf_counter()
            try:
                ctx.scraper.tick(time.time())
            except Exception:
                logger.warning("Metric scrape failed", exc_info=True)
            finally:
                _observe_phase("scrape", time.perf_counter() - phase_t0)
        phase_t0 = time.perf_counter()
        try:
            rollup = ctx.watcher.observe(handle)
            run = reg.get_run(run_id)
        except Exception:
            # A poll failure must not orphan the run: keep polling (the
            # zombie cron is the final backstop), but give up after a
            # sustained failure streak and fail the run explicitly.
            logger.exception("Monitor poll failed for run %s", run_id)
            handle.monitor_failures += 1
            if handle.monitor_failures >= ctx.monitor_failure_streak:
                ctx.gangs.pop(run_id, None)
                ctx.spawner.stop(handle)
                reg.set_status(run_id, S.FAILED, message="monitor failed repeatedly")
                _record_done(ctx, run_id, S.FAILED)
                return
            _reschedule_monitor(run_id)
            return
        finally:
            _observe_phase("watcher", time.perf_counter() - phase_t0)
        handle.monitor_failures = 0
        if run.is_done:
            # Stopped externally while we slept.
            ctx.gangs.pop(run_id, None)
            return
        if rollup == S.RUNNING:
            reg.set_status(run_id, S.RUNNING)
            if ctx.alerts is not None:
                # Same cadence as the watcher; the engine throttles itself
                # (interval_s) and counts rule errors instead of raising —
                # but a registry-level failure here must not kill the poll.
                transitions = []
                phase_t0 = time.perf_counter()
                try:
                    transitions = ctx.alerts.evaluate(handle) or []
                except Exception:
                    logger.warning(
                        "Alert evaluation failed for run %s", run_id, exc_info=True
                    )
                finally:
                    _observe_phase("alerts", time.perf_counter() - phase_t0)
                if ctx.remediation is not None:
                    # Detection→action: firing edges trigger typed actions
                    # (checkpoint-now, eviction); the tick advances
                    # multi-phase ones.  Never poll-fatal.
                    phase_t0 = time.perf_counter()
                    try:
                        if transitions:
                            ctx.remediation.on_transitions(handle, transitions)
                        ctx.remediation.tick(handle)
                    except Exception:
                        logger.warning(
                            "Remediation tick failed for run %s",
                            run_id,
                            exc_info=True,
                        )
                    finally:
                        _observe_phase(
                            "remediation", time.perf_counter() - phase_t0
                        )
        if rollup in (S.SUCCEEDED, S.FAILED, S.SKIPPED) and not handle.all_exited:
            # Gang is logically done but members are still alive — typically
            # a survivor blocked in a collective on a dead peer. Give the
            # gang a grace window to drain, then escalate TERM → KILL across
            # monitor ticks (never a blocking wait — a 5s spawner grace per
            # stuck gang would stall every other task on the bus thread);
            # otherwise the run would sit RUNNING forever (the survivor
            # keeps heartbeating, so the zombie cron can't catch it either).
            import signal
            import threading

            now = time.monotonic()
            if handle.terminal_since is None:
                handle.terminal_since = now
            # Grace windows ride the bus clock: time_scale compresses them
            # in tests exactly like every countdown.
            grace = ctx.terminal_grace * ctx.bus.time_scale
            elapsed = now - handle.terminal_since

            def _signal_off_thread(sig: int) -> None:
                # Each stage fires once, on its own thread: the ssh
                # transport's signal is a network round-trip that must not
                # stall the single bus thread (and must not be re-sent
                # every monitor tick).
                threading.Thread(
                    target=ctx.spawner.signal_gang,
                    args=(handle, sig),
                    name=f"gang-signal-{run_id}",
                    daemon=True,
                ).start()

            if elapsed >= 2 * grace and not handle.kill_sent:
                handle.kill_sent = True
                _signal_off_thread(signal.SIGKILL)
            elif elapsed >= grace and not handle.term_sent:
                handle.term_sent = True
                _signal_off_thread(signal.SIGTERM)
            _reschedule_monitor(run_id)
            return
        if rollup in (S.SUCCEEDED, S.FAILED, S.SKIPPED):
            # One final ingest now that every process flushed and exited.
            ctx.watcher.ingest(handle)
            ctx.gangs.pop(run_id, None)
            if ctx.remediation is not None:
                # Last advancement over the final ingest: an ack that
                # landed in the gang's dying flush still resolves its
                # action row (instead of expiring as the run closes).
                try:
                    ctx.remediation.tick(handle)
                except Exception:
                    logger.warning(
                        "Remediation final tick failed for run %s",
                        run_id,
                        exc_info=True,
                    )
            if rollup == S.FAILED and run.restarts < handle.plan.max_restarts:
                # Checkpoint-aware relaunch: the remediation engine decides
                # from-where (latest COMPLETE async checkpoint — finalize
                # markers reject torn saves) and how-long (exponential
                # backoff, per-run budget).  Without an engine, or if its
                # decision errors, fall back to the plan's fixed backoff —
                # the trainer still restores whatever checkpoints/ holds.
                decision = None
                if ctx.remediation is not None:
                    try:
                        decision = ctx.remediation.on_gang_failed(run, handle)
                    except Exception:
                        logger.warning(
                            "Remediation relaunch decision failed for run %s",
                            run_id,
                            exc_info=True,
                        )
                        decision = {
                            "backoff_s": handle.plan.backoff_seconds,
                            "from_step": None,
                            "message": None,
                        }
                else:
                    decision = {
                        "backoff_s": handle.plan.backoff_seconds,
                        "from_step": None,
                        "message": None,
                    }
                if decision is not None:
                    restarts = run.restarts + 1
                    reg.update_run(run_id, restarts=restarts)
                    reg.clear_processes(run_id)
                    # Rotate report files so the next attempt's watcher
                    # (fresh offsets) doesn't re-ingest this attempt's
                    # lines.
                    for process_id in range(handle.plan.num_hosts):
                        report = handle.paths.report_file(process_id)
                        if report.exists():
                            report.rename(
                                report.with_suffix(f".jsonl.attempt{run.restarts}")
                            )
                    reg.set_status(
                        run_id,
                        S.WARNING,
                        message=decision.get("message")
                        or (
                            f"gang failed; restart "
                            f"{restarts}/{handle.plan.max_restarts}"
                        ),
                    )
                    ctx.auditor.record(
                        EventTypes.EXPERIMENT_RESTARTED,
                        run_id=run_id,
                        from_step=decision.get("from_step"),
                    )
                    bus.send(
                        SchedulerTasks.EXPERIMENTS_START,
                        {"run_id": run_id},
                        countdown=decision.get("backoff_s") or 0.0,
                    )
                    return
                # Budget exhausted: fall through to terminal FAILED.
            reg.set_status(run_id, rollup)
            _record_done(ctx, run_id, rollup)
            return
        _reschedule_monitor(run_id)

    @bus.register(SchedulerTasks.EXPERIMENTS_STOP)
    def experiments_stop(
        run_id: int, cleanup: bool = False, actor: Optional[str] = None
    ) -> None:
        handle = ctx.gangs.pop(run_id, None)
        if handle is not None:
            ctx.spawner.stop(handle)
            ctx.watcher.ingest(handle)
        if cleanup:
            return
        run = reg.get_run(run_id)
        if run.is_done:
            return
        reg.set_status(run_id, S.STOPPING)
        for p in reg.get_processes(run_id):
            if p["status"] not in (S.SUCCEEDED, S.FAILED, S.STOPPED):
                reg.upsert_process(run_id, p["process_id"], status=S.STOPPED)
        reg.set_status(run_id, S.STOPPED)
        _record_done(ctx, run_id, S.STOPPED, actor=actor)

    @bus.register(SchedulerTasks.ARTIFACTS_SYNC)
    def artifacts_sync(run_id: int, _attempt: int = 0) -> None:
        """Upload a finished run's durable subdirs to the artifact store.

        Parity: reference outputs/log collection into its stores
        (``stores/managers/base.py:11-40``); here checkpoint shipping is
        first-class too.  The upload itself is offloaded off the bus
        thread (multi-GB gsutil trees must not head-of-line-block gang
        monitors/heartbeats/stop requests); transient store failures
        re-send the task with a bounded attempt counter — a flaky gsutil
        call must not silently drop a checkpoint.
        """
        from polyaxon_tpu.stores import sync_run_up

        store = ctx.artifact_store
        if store is None:
            return
        run = reg.get_run(run_id)
        paths = ctx.layout.run_paths(run.uuid)

        def _upload() -> None:
            n = sync_run_up(store, paths, run.uuid)
            ctx.auditor.record(
                EventTypes.EXPERIMENT_ARTIFACTS_SYNCED, run_id=run_id, files=n
            )

        # Failure handling lives in the bus (same retry/dead-letter
        # counters and error window as in-thread tasks): an upload
        # dead-letter is a LOST checkpoint and must stay operator-visible.
        bus.offload_with_retry(
            _upload,
            task=SchedulerTasks.ARTIFACTS_SYNC,
            kwargs={"run_id": run_id},
            attempt=_attempt,
            max_attempts=ARTIFACT_SYNC_MAX_ATTEMPTS,
            name=f"artifacts-sync-{run_id}",
        )

    @bus.register(SchedulerTasks.ADMISSION_CHECK)
    def admission_check() -> None:
        """Re-dispatch runs queued at admission (oldest first) after capacity
        was freed. Each re-entry retries ``acquire_device``; runs that still
        don't fit simply stay QUEUED (their status write is a no-op)."""
        for run in reg.list_runs(statuses=[S.QUEUED]):
            bus.send(SchedulerTasks.EXPERIMENTS_BUILD, {"run_id": run.id})
        # Sweeps throttle their own waves by free slices, so freed capacity
        # must also re-kick running groups whose trials are still CREATED
        # (no EXPERIMENT_DONE is coming to do it when the slices were held
        # by unrelated runs).
        from polyaxon_tpu.workers import HPTasks

        if bus.has_task(HPTasks.START):
            for group in reg.list_runs(kind="group", statuses=[S.RUNNING]):
                bus.send(HPTasks.START, {"group_id": group.id})

    @bus.register(CronTasks.CLEAN_ACTIVITY)
    def clean_activity(retention_seconds: float = 30 * 86400.0) -> None:
        phase_t0 = time.perf_counter()
        removed = reg.clean_old_rows(retention_seconds)
        _observe_phase("retention", time.perf_counter() - phase_t0)
        if removed.get("truncated"):
            logger.info(
                "Retention sweep hit its per-tick row budget; the "
                "remainder ages out on later ticks"
            )
        if any(removed.values()):
            logger.info("Retention cleanup removed %s", removed)

    @bus.register(CronTasks.CLEAN_ARCHIVES)
    def clean_archives(ttl_seconds: float = 7 * 86400.0) -> None:
        """Purge archived runs past the retention horizon — rows, outputs
        dirs, and store trees.  Parity: the reference's DELETE_ARCHIVED_*
        beat crons (``crons/tasks/deletion.py`` → the scheduler deletion
        tasks), collapsed to one pass over the registry."""
        from polyaxon_tpu.stores import gc_run_data

        for run in reg.archived_runs_older_than(ttl_seconds):
            try:
                victims = reg.delete_run(run.id)
            except RegistryError:
                continue  # already cascaded away with an earlier parent
            gc_run_data(ctx.layout, ctx.artifact_store, victims)
            ctx.auditor.record(
                EventTypes.EXPERIMENT_DELETED,
                run_id=run.id,
                cascaded=len(victims) - 1,
                reason="archive_retention",
            )
            logger.info(
                "Archive retention purged run %s (+%d children)",
                run.id,
                len(victims) - 1,
            )

    @bus.register(CronTasks.HEARTBEAT_CHECK)
    def heartbeat_check() -> None:
        # Heal runs stranded in QUEUED (their dispatched build/start task was
        # dead-lettered): re-enter the chain. EXPERIMENTS_BUILD/START are
        # idempotent under the lifecycle gate, so a re-dispatch can't
        # double-start a gang.
        for run in reg.stale_queued_runs(ctx.queued_redispatch_ttl):
            logger.warning("Re-dispatching run %s stranded in queued", run.id)
            bus.send(SchedulerTasks.EXPERIMENTS_BUILD, {"run_id": run.id})
        for run in reg.zombie_runs(ctx.heartbeat_ttl):
            ctx.auditor.record(EventTypes.EXPERIMENT_ZOMBIE, run_id=run.id)
            handle = ctx.gangs.pop(run.id, None)
            if handle is not None:
                # Off-thread: a zombie usually means an unreachable host,
                # where an ssh-transport stop would hold the bus thread for
                # the full grace + connect timeouts.
                import threading

                threading.Thread(
                    target=ctx.spawner.stop,
                    args=(handle,),
                    name=f"zombie-stop-{run.id}",
                    daemon=True,
                ).start()
            reg.set_status(
                run.id, S.FAILED, message=f"zombie: no heartbeat in {ctx.heartbeat_ttl}s"
            )
            _record_done(ctx, run.id, S.FAILED)
