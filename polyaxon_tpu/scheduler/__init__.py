from polyaxon_tpu.scheduler.tasks import register_scheduler_tasks

__all__ = ["register_scheduler_tasks"]
