"""The control-plane service: one object wiring every subsystem.

This is the TPU-native collapse of the reference's deployment topology —
Django API + celery workers + beat + monitors, all separate processes
(``polyaxon/config_manager.py:104-137`` service roles) — into a single
embeddable service: registry (state), task bus (async orchestration),
auditor/executor (events), spawner+watcher (gang layer), crons.

Two operating modes:
- **eager** (tests / notebooks): call :meth:`pump` / :meth:`wait` to drive
  the task graph in the calling thread — the reference's
  ``CELERY_TASK_ALWAYS_EAGER`` test pattern (``tests/base/case.py:79-87``);
- **service** (CLI / API server): :meth:`start` runs the bus in a
  background thread, with beat crons (heartbeat zombie check).
"""

from __future__ import annotations

import json
import logging
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from polyaxon_tpu.auditor import Auditor
from polyaxon_tpu.db import Run, RunRegistry
from polyaxon_tpu.events import EventTypes
from polyaxon_tpu.exceptions import PolyaxonTPUError
from polyaxon_tpu.executor import ExecutorHandlers
from polyaxon_tpu.lifecycles import StatusOptions as S
from polyaxon_tpu.monitor import GangWatcher
from polyaxon_tpu.schemas import PolyaxonFile
from polyaxon_tpu.schemas.specifications import BaseSpecification, Kinds
from polyaxon_tpu.scheduler.tasks import SchedulerContext, register_scheduler_tasks
from polyaxon_tpu.stores import StoreLayout
from polyaxon_tpu.workers import CronTasks, SchedulerTasks, TaskBus

logger = logging.getLogger(__name__)


class Orchestrator:
    #: Control-plane lease cadence/TTL: a service refreshes every interval;
    #: another control plane treats the lease as live within the TTL.
    LEASE_KEY = "platform.lease"
    LEASE_INTERVAL = 5.0
    LEASE_TTL = 15.0

    def __init__(
        self,
        base_dir: Union[str, Path],
        *,
        time_scale: float = 1.0,
        monitor_interval: Optional[float] = None,
        heartbeat_interval: Optional[float] = None,
        heartbeat_ttl: Optional[float] = None,
        heartbeat_check_interval: Optional[float] = None,
    ) -> None:
        self.base_dir = Path(base_dir)
        self.layout = StoreLayout(self.base_dir)
        self.registry = RunRegistry(self.base_dir / "registry.db")
        from polyaxon_tpu.conf import ConfService

        # Explicit arguments win; otherwise options resolve through the
        # conf stores (DB option table -> env -> default).
        self.conf = ConfService(
            self.registry,
            encryptor=self._build_encryptor(self.base_dir),
        )
        conf = self.conf
        monitor_interval = (
            monitor_interval
            if monitor_interval is not None
            else conf.get("scheduler.monitor_interval")
        )
        heartbeat_interval = (
            heartbeat_interval
            if heartbeat_interval is not None
            else conf.get("worker.heartbeat_interval")
        )
        heartbeat_ttl = (
            heartbeat_ttl
            if heartbeat_ttl is not None
            else conf.get("scheduler.heartbeat_ttl")
        )
        heartbeat_check_interval = (
            heartbeat_check_interval
            if heartbeat_check_interval is not None
            else conf.get("scheduler.heartbeat_check_interval")
        )
        from polyaxon_tpu.stats import MemoryStats, NoOpStats, StatsdStats

        stats_kind = conf.get("stats.backend")
        if stats_kind == "statsd":
            self.stats = StatsdStats(
                conf.get("stats.statsd_host"), conf.get("stats.statsd_port")
            )
        elif stats_kind == "noop":
            self.stats = NoOpStats()
        else:
            self.stats = MemoryStats()
        # Registry self-telemetry (op-family latency + lock wait/hold)
        # attaches after the fact: the registry must exist first because
        # the stats-backend *choice* is read through it.
        self.registry.attach_stats(self.stats)
        self.bus = TaskBus(time_scale=time_scale, stats=self.stats)
        self.auditor = Auditor(self.registry)
        self.executor = ExecutorHandlers(self.bus)
        self.auditor.subscribe(self.executor)
        # Usage analytics (reference tracker/): per-event counters on the
        # stats backend; external publish only when explicitly configured.
        from polyaxon_tpu.tracker import CLUSTER_ID_KEY, Tracker

        cluster_id = self.registry.get_option(CLUSTER_ID_KEY)
        if not cluster_id:
            import uuid as _uuid

            cluster_id = _uuid.uuid4().hex
            self.registry.set_option(CLUSTER_ID_KEY, cluster_id)
        self.auditor.subscribe(
            Tracker(
                self.stats,
                endpoint=conf.get("tracker.endpoint"),
                cluster_id=cluster_id,
            )
        )
        from polyaxon_tpu.conf.knobs import knob_str

        # Opt-in done/failed notifications (reference notifier/actions +
        # actions/registry/webhooks). Conf-driven; the legacy env vars keep
        # working through the option store's env resolution order.
        webhook = conf.get("notifier.webhook_url") or knob_str(
            "POLYAXON_TPU_WEBHOOK_URL"
        )
        kind = conf.get("notifier.webhook_kind") or knob_str(
            "POLYAXON_TPU_WEBHOOK_KIND"
        )
        actions = []
        if webhook:
            from polyaxon_tpu.notifier import WebhookAction
            from polyaxon_tpu.notifier.actions import SHAPERS, pagerduty_shaper

            if kind == "pagerduty":
                shaper = pagerduty_shaper(conf.get("notifier.pagerduty_routing_key"))
            else:
                shaper = SHAPERS.get(kind)
            actions.append(WebhookAction(webhook, shaper=shaper))
        email_host = conf.get("notifier.email_host")
        email_to = conf.get("notifier.email_to")
        if email_host and email_to:
            from polyaxon_tpu.notifier.actions import EmailAction

            actions.append(
                EmailAction(
                    host=email_host,
                    port=conf.get("notifier.email_port"),
                    sender=conf.get("notifier.email_from"),
                    recipients=[r.strip() for r in email_to.split(",") if r.strip()],
                    use_tls=conf.get("notifier.email_tls"),
                    username=conf.get("notifier.email_user") or None,
                    password=conf.get("notifier.email_password") or None,
                )
            )
        if actions:
            from polyaxon_tpu.notifier import Notifier

            self.auditor.subscribe(
                Notifier(
                    actions,
                    event_types=[
                        EventTypes.EXPERIMENT_SUCCEEDED,
                        EventTypes.EXPERIMENT_FAILED,
                        EventTypes.EXPERIMENT_ZOMBIE,
                        EventTypes.GROUP_DONE,
                        EventTypes.PIPELINE_DONE,
                    ],
                    stats=self.stats,
                )
            )
        # Alert-engine fan-out: named sinks + severity routing.  The log
        # sink is always present, so a deployment with no webhook still
        # sees its pages in the control-plane log.
        from polyaxon_tpu.notifier import LogAction
        from polyaxon_tpu.notifier.service import AlertRouter, parse_alert_routes

        alert_sinks = {"log": LogAction()}
        for action in actions:
            alert_sinks[action.name] = action
        self.alert_router = AlertRouter(
            alert_sinks,
            routes=parse_alert_routes(conf.get("notifier.alert_routes")),
            stats=self.stats,
        )
        self.auditor.subscribe(self.alert_router)
        from polyaxon_tpu.spawner import spawner_from_conf

        self.spawner = spawner_from_conf(
            self.layout, conf, heartbeat_interval=heartbeat_interval
        )
        # Metric history: an in-process TSDB of ring-buffer series with
        # staged rollups, persisted through the registry's metric_samples
        # table.  The scraper runs as its own monitor-tick phase; disable
        # via POLYAXON_TPU_TSDB_ENABLED for minimal-footprint deployments.
        from polyaxon_tpu.conf.knobs import knob_bool, knob_float, knob_int
        from polyaxon_tpu.stats.tsdb import MetricScraper, MetricStore

        self.metrics: Optional[MetricStore] = None
        self.scraper: Optional[MetricScraper] = None
        if knob_bool("POLYAXON_TPU_TSDB_ENABLED"):
            self.metrics = MetricStore(
                raw_points=knob_int("POLYAXON_TPU_TSDB_RAW_POINTS"),
                rollup_points=knob_int("POLYAXON_TPU_TSDB_ROLLUP_POINTS"),
                max_series=knob_int("POLYAXON_TPU_TSDB_MAX_SERIES"),
                pending_max=knob_int("POLYAXON_TPU_TSDB_PENDING_MAX"),
            )
            self.scraper = MetricScraper(
                self.metrics,
                stats=self.stats,
                registry=self.registry,
                fleets=lambda: self.fleets,
                interval_s=knob_float("POLYAXON_TPU_TSDB_SCRAPE_INTERVAL_S"),
                flush_rows=knob_int("POLYAXON_TPU_TSDB_FLUSH_ROWS"),
            )
            # Warm restart: replay the last hour of persisted raw samples
            # so rate()/burn windows don't start cold after a reboot.
            try:
                self.metrics.hydrate(
                    self.registry.get_metric_samples(
                        agg="raw", since=time.time() - 3600.0
                    )
                )
            except Exception:
                logger.warning("Metric history hydrate failed", exc_info=True)
        # The stats backend lets the watcher's stall/straggler detector
        # export its alarm gauges on /metrics; the metric store collects
        # the per-run history series behind the query API.
        self.watcher = GangWatcher(
            self.registry, stats=self.stats, metrics=self.metrics
        )
        # The alert engine ticks in the same monitor task as the watcher,
        # turning the signal tables into a pending→firing→resolved feed.
        from polyaxon_tpu.monitor import AlertEngine

        self.alerts = AlertEngine(
            self.registry,
            stats=self.stats,
            auditor=self.auditor,
            metrics=self.metrics,
        )
        # The remediation engine closes the detection→action loop: alert
        # firing edges trigger checkpoint-now/eviction through the command
        # bus, and FAILED gangs relaunch from their latest complete
        # checkpoint instead of step 0.
        from polyaxon_tpu.monitor import RemediationEngine

        self.remediation = RemediationEngine(
            self.registry,
            stats=self.stats,
            auditor=self.auditor,
            sender=self.send_command,
        )
        #: Serving fleets (serving/fleet.py:ServingFleet) registered on
        #: this control plane — the check_fleet probe and the fleet API
        #: read replica/router state from here.
        self.fleets: List[Any] = []
        artifacts_url = conf.get("stores.artifacts_url")
        self.artifact_store = None
        if artifacts_url:
            from polyaxon_tpu.stores import artifact_store_from_url

            self.artifact_store = artifact_store_from_url(artifacts_url)
        self.ctx = SchedulerContext(
            registry=self.registry,
            bus=self.bus,
            auditor=self.auditor,
            layout=self.layout,
            spawner=self.spawner,
            watcher=self.watcher,
            alerts=self.alerts,
            remediation=self.remediation,
            monitor_interval=monitor_interval,
            heartbeat_ttl=heartbeat_ttl,
            terminal_grace=conf.get("scheduler.terminal_grace"),
            monitor_failure_streak=conf.get("scheduler.monitor_failure_streak"),
            queued_redispatch_ttl=conf.get("scheduler.queued_redispatch_ttl"),
            artifact_store=self.artifact_store,
            scraper=self.scraper,
        )
        register_scheduler_tasks(self.ctx)
        from polyaxon_tpu.hpsearch import HPContext, register_hp_tasks

        register_hp_tasks(
            HPContext(registry=self.registry, bus=self.bus, auditor=self.auditor)
        )
        from polyaxon_tpu.polyflow import PipelineContext, register_pipeline_tasks

        register_pipeline_tasks(
            PipelineContext(
                registry=self.registry, bus=self.bus, auditor=self.auditor
            )
        )
        self._heartbeat_check_interval = heartbeat_check_interval
        import uuid as _uuid

        self._lease_id = _uuid.uuid4().hex

    # -- lifecycle ------------------------------------------------------------
    def refresh_lease(self) -> None:
        self.registry.set_option(
            self.LEASE_KEY, {"owner": self._lease_id, "at": time.time()}
        )

    def another_control_plane_active(self) -> bool:
        """Is a different control plane currently holding the lease?

        Guards :meth:`recover`: a CLI invocation over the base dir of a
        live ``serve`` must not reattach/re-dispatch the gangs that service
        is actively monitoring.
        """
        lease = self.registry.get_option(self.LEASE_KEY)
        return bool(
            lease
            and lease.get("owner") != self._lease_id
            and time.time() - float(lease.get("at", 0)) < self.LEASE_TTL
        )

    def recover(self) -> int:
        """Re-dispatch work stranded by a control-plane restart.

        The registry is durable; the task bus is not. Runs whose dispatch
        task died with the previous process re-enter the build→start chain,
        and sweeps/pipelines get their driving task re-kicked (the
        reference reconciles equivalent state from the k8s API on startup,
        SURVEY §3.2). Gang-phase runs (scheduled/starting/running) have no
        live handle in this process — the heartbeat cron zombies them and
        the restart policy revives what it can.
        """
        from polyaxon_tpu.workers import HPTasks, PipelineTasks

        if self.another_control_plane_active():
            import logging

            logging.getLogger(__name__).info(
                "Skipping recovery: another control plane holds the lease"
            )
            return 0
        n = 0
        for run in self.registry.list_runs(statuses=[S.CREATED, S.QUEUED]):
            if run.kind == Kinds.GROUP:
                # A group with trials already created must not re-create
                # them; re-kick the wave instead.
                has_trials = bool(self.registry.list_runs(group_id=run.id))
                self.bus.send(
                    HPTasks.START if has_trials else HPTasks.CREATE,
                    {"group_id": run.id},
                )
            elif run.kind == Kinds.PIPELINE:
                has_ops = bool(self.registry.list_runs(pipeline_id=run.id))
                self.bus.send(
                    PipelineTasks.CHECK if has_ops else PipelineTasks.START,
                    {"pipeline_id": run.id},
                )
            elif run.status == S.CREATED and (run.group_id or run.pipeline_id):
                # Wave/DAG scheduling owns dispatch of member runs — direct
                # re-entry would bypass concurrency windows and DAG order.
                continue
            else:
                self.bus.send(SchedulerTasks.EXPERIMENTS_BUILD, {"run_id": run.id})
            n += 1
        # Gang-phase runs: reattach to the live gang via the shared run dir
        # (remote rc/pid files, local pgid liveness) and resume monitoring;
        # a gang that can't be reattached is re-dispatched without touching
        # the run's restart budget — a control-plane restart is not the
        # run's failure.
        from polyaxon_tpu.compiler import compile_gang_plan
        from polyaxon_tpu.workers import SchedulerTasks as ST

        gang_phase = self.registry.list_runs(
            statuses=[S.SCHEDULED, S.STARTING, S.RUNNING, S.STOPPING]
        )
        redispatched = set()
        for run in gang_phase:
            if run.kind in (Kinds.GROUP, Kinds.PIPELINE) or run.id in self.ctx.gangs:
                continue
            if run.status == S.STOPPING:
                # The stop task died mid-flight. Reattach first so the stop
                # actually signals the (possibly still live) gang — without
                # a handle experiments_stop would mark the run STOPPED and
                # free its slice while the workers keep holding the chips.
                try:
                    plan = compile_gang_plan(run.spec)
                    handle = self.spawner.reattach(
                        run, plan, self.registry.get_processes(run.id)
                    )
                except PolyaxonTPUError:
                    handle = None
                if handle is not None:
                    self.ctx.gangs[run.id] = handle
                self.bus.send(SchedulerTasks.EXPERIMENTS_STOP, {"run_id": run.id})
                n += 1
                continue
            try:
                plan = compile_gang_plan(run.spec)
            except PolyaxonTPUError:
                continue  # was admitted once; a compile break now is terminal
            handle = self.spawner.reattach(
                run, plan, self.registry.get_processes(run.id)
            )
            attach = False
            if handle is not None:
                if any(ref.poll() is None for ref in handle.processes.values()):
                    attach = True  # gang still live: resume monitoring
                else:
                    # Every member is gone. Drain the report tail first —
                    # a gang that FINISHED while the control plane was down
                    # left terminal status lines — then decide: reported
                    # terminal = let the monitor finalize it; no terminal
                    # report = the gang died with the old control plane
                    # (e.g. took its TERM), which must not burn the run's
                    # restart budget.
                    self.watcher.ingest(handle)
                    procs = self.registry.get_processes(run.id)
                    terminal = (S.SUCCEEDED, S.FAILED, S.STOPPED)
                    attach = bool(procs) and all(
                        p["status"] in terminal for p in procs
                    )
            if attach:
                self.ctx.gangs[run.id] = handle
                self.bus.send(SchedulerTasks.EXPERIMENTS_MONITOR, {"run_id": run.id})
            else:
                self.registry.clear_processes(run.id)
                for process_id in range(plan.num_hosts):
                    report = self.layout.run_paths(run.uuid).report_file(process_id)
                    if report.exists():
                        report.rename(report.with_suffix(".jsonl.lost"))
                self.registry.set_status(
                    run.id,
                    S.WARNING,
                    message="gang lost across control-plane restart; re-dispatching",
                )
                self.bus.send(ST.EXPERIMENTS_START, {"run_id": run.id})
                redispatched.add(run.id)
            n += 1
        for run in self.registry.list_runs(statuses=[S.WARNING]):
            # A WARNING run is a restart whose EXPERIMENTS_START task died
            # with the previous bus; the send is idempotent under the gate.
            if run.id not in self.ctx.gangs and run.id not in redispatched:
                self.bus.send(SchedulerTasks.EXPERIMENTS_START, {"run_id": run.id})
                n += 1
        for group in self.registry.list_runs(kind=Kinds.GROUP, statuses=[S.RUNNING]):
            self.bus.send(HPTasks.START, {"group_id": group.id})
            n += 1
        for pipe in self.registry.list_runs(kind=Kinds.PIPELINE, statuses=[S.RUNNING]):
            self.bus.send(PipelineTasks.CHECK, {"pipeline_id": pipe.id})
            n += 1
        return n

    def start(self) -> None:
        self.refresh_lease()
        self.recover()
        # The lease refresh runs on its own timer thread, NOT as a bus
        # cron: a long blocking bus task (e.g. a multi-GB artifact sync)
        # would starve a cron-based refresh past LEASE_TTL, making a
        # concurrent CLI invocation misread the live service as dead and
        # steal its gangs.
        import threading

        self._lease_stop = threading.Event()

        def _lease_loop() -> None:
            while not self._lease_stop.wait(self.LEASE_INTERVAL):
                try:
                    self.refresh_lease()
                except Exception:  # registry closed mid-shutdown
                    import logging

                    logging.getLogger(__name__).exception("lease refresh failed")
            # Release on the way out, not only in stop(): if stop()'s join
            # timed out while a refresh was blocked on the DB, that refresh
            # would otherwise resurrect the lease AFTER stop() deleted it,
            # stalling the next control plane's recovery for a full TTL.
            try:
                self._release_lease()
            except Exception:
                pass

        self._lease_thread = threading.Thread(
            target=_lease_loop, name="lease-refresh", daemon=True
        )
        self._lease_thread.start()
        self.bus.add_cron(CronTasks.HEARTBEAT_CHECK, self._heartbeat_check_interval)
        self.bus.add_cron(
            CronTasks.CLEAN_ACTIVITY,
            3600.0,
            {"retention_seconds": self.conf.get("logs.retention_days") * 86400.0},
        )
        # Archived-run purge (reference DELETE_ARCHIVED_* beat entries,
        # ``celery_settings.py:740-860``): archived runs past the TTL are
        # deleted outright, data and all.
        self.bus.add_cron(
            CronTasks.CLEAN_ARCHIVES,
            3600.0,
            {"ttl_seconds": self.conf.get("cleaning.archives_ttl_days") * 86400.0},
        )
        self.bus.start()

    def _release_lease(self) -> None:
        """Delete the lease iff this control plane owns it (idempotent)."""
        lease = self.registry.get_option(self.LEASE_KEY)
        if lease and lease.get("owner") == self._lease_id:
            self.registry.delete_option(self.LEASE_KEY)

    @staticmethod
    def _build_encryptor(base_dir: Path):
        """Secret-at-rest encryptor, or None when `cryptography` is absent
        (optional dependency): secrets then store plaintext — the pre-
        round-4 behavior — rather than bricking every startup."""
        try:
            from polyaxon_tpu.conf.encryptor import Encryptor

            return Encryptor.from_base_dir(base_dir)
        except ImportError:
            import logging

            logging.getLogger(__name__).warning(
                "cryptography not installed — secret options will be stored "
                "unencrypted (pip install cryptography to enable at-rest "
                "encryption)"
            )
            return None

    def stop(self) -> None:
        stopper = getattr(self, "_lease_stop", None)
        if stopper is not None:
            stopper.set()
            self._lease_thread.join(timeout=2.0)
        # Clean shutdown releases the lease so the next control plane
        # recovers immediately instead of waiting out the TTL. (If the
        # join above timed out, the lease thread re-releases on exit.)
        self._release_lease()
        self.bus.stop()
        for run_id in list(self.ctx.gangs):
            handle = self.ctx.gangs.pop(run_id)
            self.spawner.stop(handle)
        self.registry.close()

    # -- client surface (the API layer calls these) ---------------------------
    def submit(
        self,
        spec: Union[str, Dict[str, Any], BaseSpecification],
        *,
        project: str = "default",
        name: Optional[str] = None,
        tags: Optional[list] = None,
        actor: Optional[str] = None,
    ) -> Run:
        """Create a run from a spec and fire its created event.

        The reference equivalent is POST /experiments → signals → auditor →
        executor (SURVEY §3.1).
        """
        if not isinstance(spec, BaseSpecification):
            spec = PolyaxonFile.load(spec).specification
        run = self.registry.create_run(spec, project=project, name=name, tags=tags)
        from polyaxon_tpu.events import created_event_for_kind

        event_type, key = created_event_for_kind(run.kind)
        # Actor attribution (reference events carry actor attributes,
        # ``events/event.py:41``): who did it rides the activity feed.
        extra = {"actor": actor} if actor else {}
        self.auditor.record(event_type, **{key: run.id}, **extra)
        return run

    def register_device(
        self,
        name: str,
        accelerator: str,
        chips: int,
        num_hosts: int = 1,
        actor: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Add slice capacity and immediately re-kick admission — queued
        runs and window-clamped sweeps must not wait for an unrelated run
        to finish before seeing the new inventory."""
        device = self.registry.register_device(
            name, accelerator, chips, num_hosts=num_hosts
        )
        self.auditor.record(
            EventTypes.CLUSTER_NODE_UPDATED,
            device=name,
            **({"actor": actor} if actor else {}),
        )
        self.bus.send(SchedulerTasks.ADMISSION_CHECK, {})
        return device

    def stop_run(self, run_id: int, actor: Optional[str] = None) -> None:
        run = self.registry.get_run(run_id)
        extra = {"actor": actor} if actor else {}
        if run.kind == Kinds.GROUP:
            # Stop all trials, then the group itself.
            for trial in self.registry.list_runs(group_id=run_id):
                if not trial.is_done:
                    self.bus.send(
                        SchedulerTasks.EXPERIMENTS_STOP,
                        {"run_id": trial.id, **extra},
                    )
            if self.registry.set_status(run_id, S.STOPPED):
                self.auditor.record(EventTypes.GROUP_STOPPED, group_id=run_id, **extra)
            return
        # The actor rides the stop task so the scheduler's single real
        # stop event carries who asked for it — no phantom/duplicate stops
        # in the feed.
        self.bus.send(
            SchedulerTasks.EXPERIMENTS_STOP, {"run_id": run_id, **extra}
        )

    def get_run(self, run_id: Union[int, str]) -> Run:
        return self.registry.get_run(run_id)

    # -- run command bus (control plane → workers) -----------------------------
    def send_command(
        self,
        run_id: int,
        kind: str,
        *,
        payload: Optional[Dict[str, Any]] = None,
        processes: Optional[List[int]] = None,
        actor: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Enqueue a worker-directed command and deliver it to the gang's
        per-process mailboxes.  Returns the registry command row; a command
        to a finished run resolves immediately to a typed EXPIRED row
        instead of waiting on a gang that will never answer."""
        from polyaxon_tpu.db.registry import CommandStatus

        run = self.registry.get_run(run_id)
        extra = {"actor": actor} if actor else {}
        if run.is_done:
            cmd = self.registry.enqueue_command(
                run.id,
                kind,
                payload=payload,
                expected=0,
                status=CommandStatus.EXPIRED,
                message=f"run already finished ({run.status})",
            )
            self.auditor.record(
                EventTypes.EXPERIMENT_COMMAND_SENT,
                run_id=run.id,
                kind=kind,
                status=CommandStatus.EXPIRED,
                **extra,
            )
            return cmd
        if processes is None:
            handle = self.ctx.gangs.get(run.id)
            if handle is not None:
                targets = list(range(handle.plan.num_hosts))
            else:
                rows = self.registry.get_processes(run.id)
                targets = [p["process_id"] for p in rows] or [0]
        else:
            targets = sorted({int(p) for p in processes})
        cmd = self.registry.enqueue_command(
            run.id,
            kind,
            payload=payload,
            process_id=targets[0] if len(targets) == 1 else None,
            expected=len(targets),
        )
        paths = self.layout.run_paths(run.uuid)
        body = json.dumps(
            {"uuid": cmd["uuid"], "kind": kind, "payload": payload or {}},
            default=str,
        )
        for process_id in targets:
            mailbox = paths.command_dir(process_id)
            mailbox.mkdir(parents=True, exist_ok=True)
            # Atomic drop: the worker's poll must never read a torn file.
            tmp = mailbox / f".{cmd['uuid']}.tmp"
            tmp.write_text(body)
            tmp.rename(mailbox / f"{cmd['uuid']}.json")
        self.auditor.record(
            EventTypes.EXPERIMENT_COMMAND_SENT,
            run_id=run.id,
            kind=kind,
            processes=targets,
            **extra,
        )
        return cmd

    def request_profile(
        self,
        run_id: int,
        *,
        num_steps: Optional[int] = None,
        duration_s: Optional[float] = None,
        processes: Optional[List[int]] = None,
        actor: Optional[str] = None,
    ) -> Dict[str, Any]:
        """On-demand gang-wide device profiling: a ``profile`` command whose
        uuid doubles as the capture id (one handle for the command row, the
        capture rows, and the ``profiles/<id>/`` artifact tree)."""
        payload: Dict[str, Any] = {}
        if num_steps is not None:
            payload["num_steps"] = int(num_steps)
        if duration_s is not None:
            payload["duration_s"] = float(duration_s)
        cmd = self.send_command(
            run_id, "profile", payload=payload, processes=processes, actor=actor
        )
        return {**cmd, "capture_id": cmd["uuid"]}

    # -- CI (per-project trigger; reference api/ci/ + ci/service.py) -----------
    def set_project_ci(
        self, project: str, spec, actor: Optional[str] = None
    ) -> Dict[str, Any]:
        """Enable/replace a project's CI: ``spec`` runs on every new code
        snapshot.  Validated up front — a stored CI spec must never blow
        up at trigger time."""
        if not isinstance(spec, BaseSpecification):
            spec = PolyaxonFile.load(spec).specification
        data = spec.to_dict()
        # Persist the build section with ONLY the fields the user set:
        # to_dict() serializes the default context '.', which after a
        # round-trip reads as explicitly set and defeats trigger_ci's
        # explicit-context guard (a default '.' would snapshot the
        # service host's cwd).
        if spec.build is not None:
            build = spec.build.model_dump(exclude_unset=True)
            if build:
                data["build"] = build
            else:
                data.pop("build", None)
        ci = self.registry.set_project_ci(project, data)
        self.auditor.record(
            EventTypes.CI_SET,
            project=project,
            **({"actor": actor} if actor else {}),
        )
        return ci

    def delete_project_ci(self, project: str, actor: Optional[str] = None) -> bool:
        removed = self.registry.delete_project_ci(project)
        if removed:
            self.auditor.record(
                EventTypes.CI_DELETED,
                project=project,
                **({"actor": actor} if actor else {}),
            )
        return removed

    def trigger_ci(
        self,
        project: str,
        context: Optional[str] = None,
        actor: Optional[str] = None,
    ) -> Optional[Run]:
        """Manual CI check: snapshot ``context`` (default: the CI spec's
        build context) and run the CI spec if the code hash is new.
        Returns the created run, or None when the code was already seen —
        the reference's repos-upload trigger, push-shaped for local mode."""
        from polyaxon_tpu.ci import submit_ci_run
        from polyaxon_tpu.schemas.run import BuildConfig
        from polyaxon_tpu.stores import create_snapshot

        ci = self.registry.get_project_ci(project)
        if ci is None:
            raise PolyaxonTPUError(f"Project {project!r} has no CI configured")
        spec = PolyaxonFile.load(ci["spec"]).specification
        build = getattr(spec, "build", None)
        # An EXPLICIT context is required from one side or the other: the
        # default BuildConfig context '.' would snapshot the SERVICE
        # HOST's cwd, which is never the project's code.
        spec_has_context = build is not None and "context" in build.model_fields_set
        if context is None and not spec_has_context:
            raise PolyaxonTPUError(
                "CI trigger needs a context directory (or a 'build' section "
                "in the CI spec naming one)"
            )
        build = build or BuildConfig()
        ref = create_snapshot(
            build, context or build.context, self.layout.snapshots_dir
        )
        if not self.registry.advance_ci_code_ref(project, ref):
            return None
        return submit_ci_run(
            self.registry, self.auditor, project, spec, ref, actor=actor
        )

    # -- archival + deletion ---------------------------------------------------
    # Parity: reference archive/restore/delete views + the deletion tasks
    # (``api/experiments/views.py`` archive/restore actions,
    # ``scheduler/tasks/deletion.py``).  Archive stops a live run first —
    # an archived run must not keep burning a slice.

    def archive_run(self, run_id: int, actor: Optional[str] = None) -> bool:
        run = self.registry.get_run(run_id)
        extra = {"actor": actor} if actor else {}
        if not run.is_done:
            self.stop_run(run_id, actor=actor)
        changed = self.registry.archive_run(run_id)
        if changed:
            self.auditor.record(
                EventTypes.EXPERIMENT_ARCHIVED, run_id=run_id, **extra
            )
        return changed

    def restore_run(self, run_id: int, actor: Optional[str] = None) -> bool:
        changed = self.registry.restore_run(run_id)
        if changed:
            self.auditor.record(
                EventTypes.EXPERIMENT_RESTORED,
                run_id=run_id,
                **({"actor": actor} if actor else {}),
            )
        return changed

    def delete_run(self, run_id: int, actor: Optional[str] = None) -> int:
        """Purge a run (cascading to trials/ops), its outputs dirs, and its
        store artifacts.  Live runs are stopped SYNCHRONOUSLY first: the
        stop task must not race the row deletion on the bus."""
        run = self.registry.get_run(run_id)
        if not run.is_done:
            self.stop_run(run_id, actor=actor)
            # The stop rides the bus; deletion is destructive, so wait for
            # the gang to die rather than deleting rows out from under the
            # stop handler. A stuck stop doesn't block the purge: the
            # handler's late status writes fail harmlessly once the row is
            # gone (set_status raises on a missing run; the bus logs it).
            try:
                self.wait(run_id, timeout=10.0)
            except PolyaxonTPUError:
                pass
        victims = self.registry.delete_run(run_id)
        self._gc_run_data(victims)
        self.auditor.record(
            EventTypes.EXPERIMENT_DELETED,
            run_id=run_id,
            cascaded=len(victims) - 1,
            **({"actor": actor} if actor else {}),
        )
        return len(victims)

    def delete_project(self, name: str, actor: Optional[str] = None) -> bool:
        """Archive-then-delete flow: refuses while live runs exist, then
        purges the project row AND its archived runs' data."""
        removed, victims = self.registry.delete_project(name)
        self._gc_run_data(victims)
        if removed:
            self.auditor.record(
                EventTypes.PROJECT_DELETED,
                project=name,
                **({"actor": actor} if actor else {}),
            )
        return removed

    def _gc_run_data(self, victims: list) -> None:
        from polyaxon_tpu.stores import gc_run_data

        gc_run_data(self.layout, self.artifact_store, victims)

    def clone_run(
        self, run_id: int, strategy: str = "restart", actor: Optional[str] = None
    ) -> Run:
        """Restart / resume / copy a run as a new run.

        Parity: reference restart/resume/copy views
        (``api/experiments/views.py:329-366``) + ``copy_experiment``
        (``scheduler/tasks/experiments.py:27-56``). ``resume`` and ``copy``
        both duplicate outputs+checkpoints into the clone's directories
        (the clone continues from the last checkpoint); the reference's
        shared-outputs RESUME is deliberately not reproduced — isolated
        dirs stay correct when the original is re-run concurrently.
        """
        if strategy not in ("restart", "resume", "copy"):
            raise PolyaxonTPUError(f"Unknown cloning strategy {strategy!r}")
        orig = self.registry.get_run(run_id)
        if orig.kind not in (Kinds.EXPERIMENT, Kinds.JOB, Kinds.BUILD):
            raise PolyaxonTPUError(
                f"Only experiment/job runs can be cloned, not {orig.kind!r} "
                "(restart a sweep or pipeline by submitting its spec again)"
            )
        # Deliberately NOT propagating group_id: a clone is user-initiated
        # and must not enter the sweep's wave accounting/concurrency window.
        run = self.registry.create_run(
            orig.spec,
            project=orig.project,
            name=f"{orig.name or orig.id}-{strategy}",
            original_id=orig.id,
            cloning_strategy=strategy,
            tags=orig.tags,
        )
        if orig.code_ref:
            self.registry.update_run(run.id, code_ref=orig.code_ref)
        if strategy in ("resume", "copy"):
            self.layout.copy_outputs(orig.uuid, run.uuid)
            if self.artifact_store is not None:
                # The original's local run dir may be gone (TPU-VM local
                # disk is ephemeral; the slice may have been recycled) —
                # the artifact store is the durable source of truth.
                from polyaxon_tpu.stores import run_prefix

                dst = self.layout.run_paths(run.uuid).ensure()
                for sub in ("outputs", "checkpoints"):
                    d = dst.root / sub
                    if not any(d.iterdir()):
                        self.artifact_store.download_tree(
                            f"{run_prefix(orig.uuid)}/{sub}", d
                        )
        event = (
            EventTypes.EXPERIMENT_RESUMED
            if strategy == "resume"
            else EventTypes.EXPERIMENT_CREATED
        )
        self.auditor.record(
            event, run_id=run.id, **({"actor": actor} if actor else {})
        )
        return self.registry.get_run(run.id)

    def list_artifacts(self, run_id: Union[int, str]) -> list:
        """A run's artifact keys: local run dir ∪ the durable store.

        Parity: reference outputs browsing over its store managers
        (``stores/managers/base.py:11-40``).
        """
        run = self.registry.get_run(run_id)
        paths = self.layout.run_paths(run.uuid)
        local = (
            {
                p.relative_to(paths.root).as_posix()
                for p in paths.root.rglob("*")
                if p.is_file()
            }
            if paths.root.is_dir()
            else set()
        )
        stored = set()
        if self.artifact_store is not None:
            from polyaxon_tpu.stores import run_prefix

            prefix = run_prefix(run.uuid) + "/"
            stored = {
                k[len(prefix):]
                for k in self.artifact_store.list(run_prefix(run.uuid))
            }
        return sorted(local | stored)

    @staticmethod
    def _artifact_key_ok(key: str) -> bool:
        # A '..' segment must not reach the store path join — the local
        # branch's resolve() guard doesn't cover the store fallback, where
        # 'runs/<uuid>/../<other-uuid>/x' would read another run's artifacts.
        from pathlib import PurePosixPath

        p = PurePosixPath(key)
        return not p.is_absolute() and ".." not in p.parts

    def artifact_local_path(self, run_id: Union[int, str], key: str):
        """The on-disk path of a local artifact, or None (absent/unsafe key)."""
        if not self._artifact_key_ok(key):
            return None
        run = self.registry.get_run(run_id)
        paths = self.layout.run_paths(run.uuid)
        local = (paths.root / key).resolve()
        if local.is_relative_to(paths.root.resolve()) and local.is_file():
            return local
        return None

    def open_artifact(self, run_id: Union[int, str], key: str):
        """A readable binary stream (local first, store fallback); None if
        absent.  Streams — multi-GB checkpoints never land in control-plane
        memory.  Caller closes."""
        local = self.artifact_local_path(run_id, key)
        if local is not None:
            return local.open("rb")
        if self.artifact_store is not None and self._artifact_key_ok(key):
            from polyaxon_tpu.stores import run_prefix

            run = self.registry.get_run(run_id)
            # One round-trip: attempt the read and treat not-found as None
            # (an exists() probe would double the gsutil subprocess cost).
            try:
                return self.artifact_store.open(f"{run_prefix(run.uuid)}/{key}")
            except PolyaxonTPUError:
                return None
        return None

    def get_artifact(self, run_id: Union[int, str], key: str) -> Optional[bytes]:
        """An artifact's bytes; None if absent. Small-payload convenience —
        prefer :meth:`open_artifact` for anything checkpoint-sized."""
        f = self.open_artifact(run_id, key)
        if f is None:
            return None
        with f:
            return f.read()

    # -- eager driving (tests; service mode doesn't need these) ----------------
    def pump(self, max_wait: float = 0.0) -> int:
        return self.bus.pump(max_wait=max_wait)

    def wait(
        self, run_id: int, timeout: float = 60.0, poll: float = 0.05
    ) -> Run:
        """Drive the bus until the run reaches a terminal status."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.bus.pump(max_wait=poll)
            run = self.registry.get_run(run_id)
            if run.is_done:
                return run
            time.sleep(min(poll, max(0.0, deadline - time.time())))
        raise PolyaxonTPUError(
            f"Run {run_id} not done after {timeout}s "
            f"(status={self.registry.get_run(run_id).status!r})"
        )
