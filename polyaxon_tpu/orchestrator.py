"""The control-plane service: one object wiring every subsystem.

This is the TPU-native collapse of the reference's deployment topology —
Django API + celery workers + beat + monitors, all separate processes
(``polyaxon/config_manager.py:104-137`` service roles) — into a single
embeddable service: registry (state), task bus (async orchestration),
auditor/executor (events), spawner+watcher (gang layer), crons.

Two operating modes:
- **eager** (tests / notebooks): call :meth:`pump` / :meth:`wait` to drive
  the task graph in the calling thread — the reference's
  ``CELERY_TASK_ALWAYS_EAGER`` test pattern (``tests/base/case.py:79-87``);
- **service** (CLI / API server): :meth:`start` runs the bus in a
  background thread, with beat crons (heartbeat zombie check).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, Optional, Union

from polyaxon_tpu.auditor import Auditor
from polyaxon_tpu.db import Run, RunRegistry
from polyaxon_tpu.events import EventTypes
from polyaxon_tpu.exceptions import PolyaxonTPUError
from polyaxon_tpu.executor import ExecutorHandlers
from polyaxon_tpu.lifecycles import StatusOptions as S
from polyaxon_tpu.monitor import GangWatcher
from polyaxon_tpu.schemas import PolyaxonFile
from polyaxon_tpu.schemas.specifications import BaseSpecification, Kinds
from polyaxon_tpu.scheduler.tasks import SchedulerContext, register_scheduler_tasks
from polyaxon_tpu.stores import StoreLayout
from polyaxon_tpu.workers import CronTasks, SchedulerTasks, TaskBus


class Orchestrator:
    def __init__(
        self,
        base_dir: Union[str, Path],
        *,
        time_scale: float = 1.0,
        monitor_interval: Optional[float] = None,
        heartbeat_interval: Optional[float] = None,
        heartbeat_ttl: Optional[float] = None,
        heartbeat_check_interval: Optional[float] = None,
    ) -> None:
        self.base_dir = Path(base_dir)
        self.layout = StoreLayout(self.base_dir)
        self.registry = RunRegistry(self.base_dir / "registry.db")
        from polyaxon_tpu.conf import ConfService

        # Explicit arguments win; otherwise options resolve through the
        # conf stores (DB option table -> env -> default).
        self.conf = ConfService(self.registry)
        conf = self.conf
        monitor_interval = (
            monitor_interval
            if monitor_interval is not None
            else conf.get("scheduler.monitor_interval")
        )
        heartbeat_interval = (
            heartbeat_interval
            if heartbeat_interval is not None
            else conf.get("worker.heartbeat_interval")
        )
        heartbeat_ttl = (
            heartbeat_ttl
            if heartbeat_ttl is not None
            else conf.get("scheduler.heartbeat_ttl")
        )
        heartbeat_check_interval = (
            heartbeat_check_interval
            if heartbeat_check_interval is not None
            else conf.get("scheduler.heartbeat_check_interval")
        )
        self.bus = TaskBus(time_scale=time_scale)
        self.auditor = Auditor(self.registry)
        self.executor = ExecutorHandlers(self.bus)
        self.auditor.subscribe(self.executor)
        import os as _os

        webhook = _os.environ.get("POLYAXON_TPU_WEBHOOK_URL")
        if webhook:
            # Opt-in done/failed notifications (reference notifier/actions).
            from polyaxon_tpu.notifier import Notifier, WebhookAction
            from polyaxon_tpu.notifier.actions import slack_shaper

            shaper = (
                slack_shaper
                if _os.environ.get("POLYAXON_TPU_WEBHOOK_KIND") == "slack"
                else None
            )
            self.auditor.subscribe(
                Notifier(
                    [WebhookAction(webhook, shaper=shaper)],
                    event_types=[
                        EventTypes.EXPERIMENT_SUCCEEDED,
                        EventTypes.EXPERIMENT_FAILED,
                        EventTypes.EXPERIMENT_ZOMBIE,
                        EventTypes.GROUP_DONE,
                        EventTypes.PIPELINE_DONE,
                    ],
                )
            )
        from polyaxon_tpu.spawner import spawner_from_conf

        self.spawner = spawner_from_conf(
            self.layout, conf, heartbeat_interval=heartbeat_interval
        )
        self.watcher = GangWatcher(self.registry)
        self.ctx = SchedulerContext(
            registry=self.registry,
            bus=self.bus,
            auditor=self.auditor,
            layout=self.layout,
            spawner=self.spawner,
            watcher=self.watcher,
            monitor_interval=monitor_interval,
            heartbeat_ttl=heartbeat_ttl,
            terminal_grace=conf.get("scheduler.terminal_grace"),
            monitor_failure_streak=conf.get("scheduler.monitor_failure_streak"),
            queued_redispatch_ttl=conf.get("scheduler.queued_redispatch_ttl"),
        )
        register_scheduler_tasks(self.ctx)
        from polyaxon_tpu.hpsearch import HPContext, register_hp_tasks

        register_hp_tasks(
            HPContext(registry=self.registry, bus=self.bus, auditor=self.auditor)
        )
        from polyaxon_tpu.polyflow import PipelineContext, register_pipeline_tasks

        register_pipeline_tasks(
            PipelineContext(
                registry=self.registry, bus=self.bus, auditor=self.auditor
            )
        )
        self._heartbeat_check_interval = heartbeat_check_interval

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        self.bus.add_cron(CronTasks.HEARTBEAT_CHECK, self._heartbeat_check_interval)
        self.bus.add_cron(
            CronTasks.CLEAN_ACTIVITY,
            3600.0,
            {"retention_seconds": self.conf.get("logs.retention_days") * 86400.0},
        )
        self.bus.start()

    def stop(self) -> None:
        self.bus.stop()
        for run_id in list(self.ctx.gangs):
            handle = self.ctx.gangs.pop(run_id)
            self.spawner.stop(handle)
        self.registry.close()

    # -- client surface (the API layer calls these) ---------------------------
    def submit(
        self,
        spec: Union[str, Dict[str, Any], BaseSpecification],
        *,
        project: str = "default",
        name: Optional[str] = None,
        tags: Optional[list] = None,
    ) -> Run:
        """Create a run from a spec and fire its created event.

        The reference equivalent is POST /experiments → signals → auditor →
        executor (SURVEY §3.1).
        """
        if not isinstance(spec, BaseSpecification):
            spec = PolyaxonFile.load(spec).specification
        run = self.registry.create_run(spec, project=project, name=name, tags=tags)
        created_events = {
            Kinds.EXPERIMENT: (EventTypes.EXPERIMENT_CREATED, "run_id"),
            Kinds.JOB: (EventTypes.EXPERIMENT_CREATED, "run_id"),
            Kinds.BUILD: (EventTypes.EXPERIMENT_CREATED, "run_id"),
            Kinds.GROUP: (EventTypes.GROUP_CREATED, "group_id"),
            Kinds.PIPELINE: (EventTypes.PIPELINE_CREATED, "pipeline_id"),
        }
        event_type, key = created_events.get(
            run.kind, (EventTypes.EXPERIMENT_CREATED, "run_id")
        )
        self.auditor.record(event_type, **{key: run.id})
        return run

    def register_device(
        self, name: str, accelerator: str, chips: int, num_hosts: int = 1
    ) -> Dict[str, Any]:
        """Add slice capacity and immediately re-kick admission — queued
        runs and window-clamped sweeps must not wait for an unrelated run
        to finish before seeing the new inventory."""
        device = self.registry.register_device(
            name, accelerator, chips, num_hosts=num_hosts
        )
        self.bus.send(SchedulerTasks.ADMISSION_CHECK, {})
        return device

    def stop_run(self, run_id: int) -> None:
        run = self.registry.get_run(run_id)
        if run.kind == Kinds.GROUP:
            # Stop all trials, then the group itself.
            for trial in self.registry.list_runs(group_id=run_id):
                if not trial.is_done:
                    self.bus.send(SchedulerTasks.EXPERIMENTS_STOP, {"run_id": trial.id})
            if self.registry.set_status(run_id, S.STOPPED):
                self.auditor.record(EventTypes.GROUP_STOPPED, group_id=run_id)
            return
        self.bus.send(SchedulerTasks.EXPERIMENTS_STOP, {"run_id": run_id})

    def get_run(self, run_id: Union[int, str]) -> Run:
        return self.registry.get_run(run_id)

    def clone_run(self, run_id: int, strategy: str = "restart") -> Run:
        """Restart / resume / copy a run as a new run.

        Parity: reference restart/resume/copy views
        (``api/experiments/views.py:329-366``) + ``copy_experiment``
        (``scheduler/tasks/experiments.py:27-56``). ``resume`` and ``copy``
        both duplicate outputs+checkpoints into the clone's directories
        (the clone continues from the last checkpoint); the reference's
        shared-outputs RESUME is deliberately not reproduced — isolated
        dirs stay correct when the original is re-run concurrently.
        """
        if strategy not in ("restart", "resume", "copy"):
            raise PolyaxonTPUError(f"Unknown cloning strategy {strategy!r}")
        orig = self.registry.get_run(run_id)
        if orig.kind not in (Kinds.EXPERIMENT, Kinds.JOB, Kinds.BUILD):
            raise PolyaxonTPUError(
                f"Only experiment/job runs can be cloned, not {orig.kind!r} "
                "(restart a sweep or pipeline by submitting its spec again)"
            )
        # Deliberately NOT propagating group_id: a clone is user-initiated
        # and must not enter the sweep's wave accounting/concurrency window.
        run = self.registry.create_run(
            orig.spec,
            project=orig.project,
            name=f"{orig.name or orig.id}-{strategy}",
            original_id=orig.id,
            cloning_strategy=strategy,
            tags=orig.tags,
        )
        if orig.code_ref:
            self.registry.update_run(run.id, code_ref=orig.code_ref)
        if strategy in ("resume", "copy"):
            self.layout.copy_outputs(orig.uuid, run.uuid)
        event = (
            EventTypes.EXPERIMENT_RESUMED
            if strategy == "resume"
            else EventTypes.EXPERIMENT_CREATED
        )
        self.auditor.record(event, run_id=run.id)
        return self.registry.get_run(run.id)

    # -- eager driving (tests; service mode doesn't need these) ----------------
    def pump(self, max_wait: float = 0.0) -> int:
        return self.bus.pump(max_wait=max_wait)

    def wait(
        self, run_id: int, timeout: float = 60.0, poll: float = 0.05
    ) -> Run:
        """Drive the bus until the run reaches a terminal status."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.bus.pump(max_wait=poll)
            run = self.registry.get_run(run_id)
            if run.is_done:
                return run
            time.sleep(min(poll, max(0.0, deadline - time.time())))
        raise PolyaxonTPUError(
            f"Run {run_id} not done after {timeout}s "
            f"(status={self.registry.get_run(run_id).status!r})"
        )
