"""Pluggable operational-metrics backends.

Parity: reference ``stats/`` (``statsd.py:7``, ``datadog.py:11``, noop) —
counters/gauges/timings for the control plane itself (task throughput,
gang spawn latency).  The statsd backend speaks the plain UDP protocol
with no dependency; the memory backend is for tests and the /status page.
"""

from __future__ import annotations

import socket
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Dict, List, Tuple


class StatsBackend:
    def incr(self, key: str, value: int = 1) -> None:  # pragma: no cover
        raise NotImplementedError

    def gauge(self, key: str, value: float) -> None:  # pragma: no cover
        raise NotImplementedError

    def timing(self, key: str, seconds: float) -> None:  # pragma: no cover
        raise NotImplementedError

    @contextmanager
    def timed(self, key: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timing(key, time.perf_counter() - t0)


class NoOpStats(StatsBackend):
    def incr(self, key: str, value: int = 1) -> None:
        pass

    def gauge(self, key: str, value: float) -> None:
        pass

    def timing(self, key: str, seconds: float) -> None:
        pass


class MemoryStats(StatsBackend):
    """In-process aggregation (tests + health/status introspection).

    Timing samples are bounded per key (recent window) — this backend is
    the DEFAULT and instruments every task execution, so unbounded lists
    would be a slow memory leak in a long-lived service.
    """

    TIMING_WINDOW = 512

    def __init__(self) -> None:
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timings: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=self.TIMING_WINDOW)
        )

    def incr(self, key: str, value: int = 1) -> None:
        self.counters[key] += value

    def gauge(self, key: str, value: float) -> None:
        self.gauges[key] = value

    def timing(self, key: str, seconds: float) -> None:
        self.timings[key].append(seconds)


class StatsdStats(StatsBackend):
    """Plain statsd-over-UDP (fire and forget, never raises)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125, prefix: str = "polyaxon_tpu") -> None:
        self.addr: Tuple[str, int] = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(f"{self.prefix}.{payload}".encode(), self.addr)
        except OSError:
            pass

    def incr(self, key: str, value: int = 1) -> None:
        self._send(f"{key}:{value}|c")

    def gauge(self, key: str, value: float) -> None:
        self._send(f"{key}:{value}|g")

    def timing(self, key: str, seconds: float) -> None:
        self._send(f"{key}:{seconds * 1000:.2f}|ms")
