"""Pluggable operational-metrics backends.

Parity: reference ``stats/`` (``statsd.py:7``, ``datadog.py:11``, noop) —
counters/gauges/timings for the control plane itself (task throughput,
gang spawn latency).  The statsd backend speaks the plain UDP protocol
with no dependency; the memory backend is for tests and the /status page.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Set, Tuple

from polyaxon_tpu.stats.metrics import Histogram, fold_labeled_key

logger = logging.getLogger(__name__)


class StatsBackend:
    def incr(self, key: str, value: int = 1) -> None:  # pragma: no cover
        raise NotImplementedError

    def gauge(self, key: str, value: float) -> None:  # pragma: no cover
        raise NotImplementedError

    def timing(self, key: str, seconds: float) -> None:  # pragma: no cover
        raise NotImplementedError

    def observe(self, key: str, value: float) -> None:
        """Record a distribution sample that is not a duration (e.g. batch
        occupancy).  Default: treat like a timing so every backend sees it."""
        self.timing(key, value)

    @contextmanager
    def timed(self, key: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timing(key, time.perf_counter() - t0)


class NoOpStats(StatsBackend):
    def incr(self, key: str, value: int = 1) -> None:
        pass

    def gauge(self, key: str, value: float) -> None:
        pass

    def timing(self, key: str, seconds: float) -> None:
        pass

    def observe(self, key: str, value: float) -> None:
        pass


class MemoryStats(StatsBackend):
    """In-process aggregation (tests + health/status + /metrics scrape).

    Timing samples are bounded per key (recent window) — this backend is
    the DEFAULT and instruments every task execution, so unbounded lists
    would be a slow memory leak in a long-lived service.  Every timing and
    ``observe`` also feeds a log-bucketed :class:`Histogram`, which holds
    full-run percentiles in O(buckets) memory and renders directly as a
    Prometheus histogram.

    Mutated from many threads (bus workers, serving loop, HTTP handlers)
    and read by iteration (health checks, the /metrics renderer) — all
    access goes through one lock, and readers should use :meth:`snapshot`
    rather than iterating the live dicts.

    Labeled series (``alert_state{rule=...,run=...}``) are capped per base
    metric name at ``max_series`` distinct label sets
    (``POLYAXON_TPU_METRICS_MAX_SERIES``); overflow folds into a single
    ``{...="other"}`` series so a buggy caller interpolating an unbounded
    identifier degrades the one metric instead of growing ``/metrics``
    (and every snapshot) without limit.
    """

    TIMING_WINDOW = 512

    def __init__(self, max_series: Optional[int] = None) -> None:
        if max_series is None:
            from polyaxon_tpu.conf.knobs import knob_int

            max_series = knob_int("POLYAXON_TPU_METRICS_MAX_SERIES")
        self._lock = threading.Lock()
        self._max_series = int(max_series)
        #: base metric name -> admitted labeled keys (cap bookkeeping).
        self._series: Dict[str, Set[str]] = defaultdict(set)
        self._fold_warned: Set[str] = set()
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timings: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=self.TIMING_WINDOW)
        )
        self.histograms: Dict[str, Histogram] = {}

    def _admit(self, key: str) -> str:
        """Cardinality gate (caller holds the lock): the key itself, or its
        ``other``-fold once the base metric is at ``max_series`` distinct
        label sets.  Flat keys pass through untouched."""
        if self._max_series <= 0 or "{" not in key:
            return key
        base = key.partition("{")[0]
        seen = self._series[base]
        if key in seen:
            return key
        if len(seen) < self._max_series:
            seen.add(key)
            return key
        folded = fold_labeled_key(key)
        if folded not in seen and len(seen) == self._max_series:
            seen.add(folded)  # the fold series itself always fits
        if base not in self._fold_warned:
            self._fold_warned.add(base)
            logger.warning(
                "metric %r exceeded POLYAXON_TPU_METRICS_MAX_SERIES=%d "
                "label sets; overflow folds into %r",
                base,
                self._max_series,
                folded,
            )
        self.counters["metrics_series_folded"] += 1
        return folded

    def incr(self, key: str, value: int = 1) -> None:
        with self._lock:
            self.counters[self._admit(key)] += value

    def gauge(self, key: str, value: float) -> None:
        with self._lock:
            self.gauges[self._admit(key)] = value

    def timing(self, key: str, seconds: float) -> None:
        with self._lock:
            key = self._admit(key)
            self.timings[key].append(seconds)
            self._histogram(key).observe(seconds)

    def observe(self, key: str, value: float) -> None:
        """Histogram-only sample (no raw-window copy kept)."""
        with self._lock:
            self._histogram(self._admit(key)).observe(value)

    def _histogram(self, key: str) -> Histogram:
        hist = self.histograms.get(key)
        if hist is None:
            hist = self.histograms[key] = Histogram()
        return hist

    def snapshot(self, include_timings: bool = True) -> Dict[str, Any]:
        """Consistent copy of all state, safe to iterate/serialize.

        The shape is what ``render_prometheus`` consumes: ``counters`` /
        ``gauges`` as plain dicts, ``timings`` as lists, ``histograms`` as
        ``Histogram.state()`` dicts.

        ``include_timings=False`` skips copying the bounded raw-sample
        windows (up to 512 floats *per key*) — the exposition path: the
        Prometheus renderer only reads counters/gauges/histograms, and the
        timings copy is by far the largest lock-held cost of a scrape, so
        skipping it keeps concurrent ``observe()`` callers off this lock's
        wait queue while ``/metrics`` is being served.
        """
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timings": (
                    {k: list(v) for k, v in self.timings.items()}
                    if include_timings
                    else {}
                ),
                "histograms": {k: h.state() for k, h in self.histograms.items()},
            }

    def summaries(self) -> Dict[str, Dict[str, float]]:
        """Per-key histogram summaries (count/sum/mean/p50/p95/p99)."""
        with self._lock:
            return {k: h.summary() for k, h in self.histograms.items()}


class StatsdStats(StatsBackend):
    """Plain statsd-over-UDP (fire and forget, never raises)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125, prefix: str = "polyaxon_tpu") -> None:
        self.addr: Tuple[str, int] = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _send(self, payload: str) -> None:
        try:
            self._sock.sendto(f"{self.prefix}.{payload}".encode(), self.addr)
        except OSError:
            pass

    def incr(self, key: str, value: int = 1) -> None:
        self._send(f"{key}:{value}|c")

    def gauge(self, key: str, value: float) -> None:
        self._send(f"{key}:{value}|g")

    def timing(self, key: str, seconds: float) -> None:
        self._send(f"{key}:{seconds * 1000:.2f}|ms")

    def observe(self, key: str, value: float) -> None:
        # dogstatsd histogram extension; plain statsd servers drop unknown
        # types silently, which is the right failure mode here.
        self._send(f"{key}:{value}|h")
