"""In-process metric history: ring-buffer TSDB + windowed deltas.

``/metrics`` and ``MemoryStats.snapshot()`` are point-in-time — cumulative
since boot, gone on restart.  This module adds the missing axis:

* :class:`MetricStore` — fixed-step ring series with staged downsampling
  (raw → 10s → 1m rollups carrying min/max/sum/count, so percent-style
  gauges and counter rates both survive compaction), a bounded pending
  queue for registry write-behind, and Prometheus-shaped queries
  (``increase``/``rate`` with counter-reset clamping, aligned
  aggregation over a time range).
* :class:`CounterWindow` / :class:`RatioWindow` /
  :class:`HistogramWindow` / :class:`WindowedView` — the one shared
  implementation of "rate over the last W seconds" over cumulative
  counters and histogram bucket snapshots.  Replaces ad-hoc deques and
  ``Histogram.reset()`` call sites (resetting breaks cumulative-counter
  semantics for any external scraper mid-window).
* :class:`MetricScraper` — the monitor tick's scrape phase: samples the
  control-plane stats backend and every live fleet replica's last probe
  stats (riding the router's probe results — no new connections) into
  labeled series, and flushes sealed samples to the registry in batches.
* :func:`slo_status` / :func:`fold_run_baselines` — multi-window
  burn-rate math for the ``slo_burn_rate`` alert and the cross-run
  EWMA baselines behind ``metric_regression``.

Everything here is control-plane-thread friendly: the store takes one
lock per call and never blocks on I/O (persistence happens in the
scraper's flush step, against the registry's own batched ingest op).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

from polyaxon_tpu.stats.metrics import (
    fold_labeled_key,
    labeled_key,
    split_labeled_key,
)

__all__ = [
    "MetricStore",
    "CounterWindow",
    "RatioWindow",
    "HistogramWindow",
    "WindowedView",
    "MetricScraper",
    "slo_status",
    "fold_run_baselines",
    "ROLLUP_STEPS",
]

#: Downsampling stages, coarsest last.  Queries with ``step >= stage``
#: read the matching rollup ring instead of raw points.
ROLLUP_STEPS: Tuple[float, ...] = (10.0, 60.0)

#: Registry ``agg`` column value per stage (raw rows use ``"raw"``).
_STEP_AGG = {10.0: "10s", 60.0: "1m"}


def _suffixed(key: str, suffix: str) -> str:
    """``registry_op_s{op="write"}`` + ``_count`` →
    ``registry_op_s_count{op="write"}`` — suffix the base name, keep the
    label set."""
    i = key.find("{")
    if i < 0:
        return key + suffix
    return key[:i] + suffix + key[i:]


class _Bucket:
    """One rollup slot: the aggregates a raw window compacts into."""

    __slots__ = ("start", "vmin", "vmax", "vsum", "vcount", "last")

    def __init__(self, start: float, value: float) -> None:
        self.start = start
        self.vmin = value
        self.vmax = value
        self.vsum = value
        self.vcount = 1
        self.last = value

    def merge(self, value: float) -> None:
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        self.vsum += value
        self.vcount += 1
        self.last = value

    def row(self, key: str, agg: str) -> Dict[str, Any]:
        return {
            "name": key,
            "at": self.start,
            "value": self.last,
            "agg": agg,
            "vmin": self.vmin,
            "vmax": self.vmax,
            "vsum": self.vsum,
            "vcount": self.vcount,
        }


class _Series:
    """One (name, labels) series: a raw ring plus one rollup ring per
    stage.  Not locked — :class:`MetricStore` serializes access."""

    __slots__ = ("key", "raw", "rollups", "sealed")

    def __init__(self, key: str, raw_points: int, rollup_points: int) -> None:
        self.key = key
        self.raw: Deque[Tuple[float, float]] = deque(maxlen=raw_points)
        self.rollups: Dict[float, Deque[_Bucket]] = {
            step: deque(maxlen=rollup_points) for step in ROLLUP_STEPS
        }
        #: Rollup buckets closed since the last drain — (agg, bucket)
        #: pairs handed to the registry write-behind.
        self.sealed: List[Tuple[str, _Bucket]] = []

    def record(self, at: float, value: float) -> None:
        if not self.raw or at >= self.raw[-1][0]:
            self.raw.append((at, value))
        for step, ring in self.rollups.items():
            start = (at // step) * step
            if ring and ring[-1].start == start:
                ring[-1].merge(value)
                continue
            if ring and start < ring[-1].start:
                # Late sample: merge into the matching earlier bucket if
                # it is still in the ring, otherwise drop it — rollups
                # are append-mostly and a sealed bucket may already have
                # been flushed.
                for b in reversed(ring):
                    if b.start == start:
                        b.merge(value)
                        break
                continue
            if ring:
                self.sealed.append((_STEP_AGG[step], ring[-1]))
            ring.append(_Bucket(start, value))

    def points(self, step: Optional[float]) -> List[Tuple[float, float, _Bucket]]:
        """(at, value, bucket-or-None) triples from the best stage for
        ``step`` — coarsest rollup whose step fits, else raw."""
        stage = None
        if step:
            for s in sorted(ROLLUP_STEPS, reverse=True):
                if step >= s:
                    stage = s
                    break
        if stage is None:
            return [(at, v, None) for at, v in self.raw]
        return [(b.start, b.last, b) for b in self.rollups[stage]]


def _increase(points: Sequence[Tuple[float, float]], since: float) -> Optional[float]:
    """Counter increase over ``[since, now]`` with reset clamping.

    Baseline = newest sample at-or-before ``since`` (else the oldest in
    the ring); the increase is the sum of positive deltas between
    consecutive samples from the baseline on.  A decrease means the
    counter restarted (replica restart) — the post-reset value counts
    from ~0, so it is *added*, never subtracted.  Needs ≥ 2 samples.
    """
    if len(points) < 2:
        return None
    start = 0
    for i, (at, _v) in enumerate(points):
        if at <= since:
            start = i
        else:
            break
    total = 0.0
    prev = points[start][1]
    for at, v in points[start + 1:]:
        if v >= prev:
            total += v - prev
        else:
            total += v
        prev = v
    return total


class MetricStore:
    """Bounded in-memory TSDB with staged rollups and write-behind.

    Series are keyed by Prometheus-style labeled keys
    (``replica_queue_depth{fleet="f",replica="f-r0"}``); the per-base-
    name cardinality cap folds overflow series through
    :func:`fold_labeled_key`, same as ``MemoryStats``.
    """

    def __init__(
        self,
        *,
        raw_points: int = 720,
        rollup_points: int = 360,
        max_series: int = 2048,
        pending_max: int = 8192,
    ) -> None:
        self._lock = threading.Lock()
        self.raw_points = max(2, int(raw_points))
        self.rollup_points = max(2, int(rollup_points))
        self.max_series = max(1, int(max_series))
        self.pending_max = max(0, int(pending_max))
        self._series: Dict[str, _Series] = {}
        self._by_base: Dict[str, List[str]] = {}
        self._pending: Deque[Dict[str, Any]] = deque()
        self.folded = 0
        self.dropped = 0
        self._hydrating = False

    # -- write path ------------------------------------------------------

    def _admit(self, key: str) -> str:
        if key in self._series:
            return key  # hot path: known series skip the label parse
        base, labels = split_labeled_key(key)
        keys = self._by_base.setdefault(base, [])
        if labels and len(keys) >= self.max_series:
            self.folded += 1
            folded = fold_labeled_key(key)
            if folded not in self._series and len(keys) >= self.max_series + 1:
                return keys[0]  # pathological: even the fold won't fit
            key = folded
            if key in self._series:
                return key
        self._series[key] = _Series(key, self.raw_points, self.rollup_points)
        keys.append(key)
        return key

    def record(self, key: str, value: float, at: float) -> None:
        try:
            v = float(value)
        except (TypeError, ValueError):
            return
        with self._lock:
            admitted = self._admit(key)
            self._series[admitted].record(float(at), v)
            if not self._hydrating:
                self._pending_raw(admitted, float(at), v)

    #: Raw rows waiting for the registry flush; bounded — overflow drops
    #: the oldest (history in memory is unaffected, only durability).
    def _pending_raw(self, key: str, at: float, value: float) -> None:
        q = self._pending
        q.append({"name": key, "at": at, "value": value, "agg": "raw"})
        while len(q) > self.pending_max:
            q.popleft()
            self.dropped += 1

    def record_snapshot(self, snapshot: Mapping[str, Any], at: float) -> None:
        """Ingest a full ``MemoryStats.snapshot()``: counters and gauges
        verbatim, histograms as ``<name>_count`` / ``<name>_sum`` series
        (enough to reconstruct rates and means over any window)."""
        for key, value in snapshot.get("counters", {}).items():
            self.record(key, value, at)
        for key, value in snapshot.get("gauges", {}).items():
            self.record(key, value, at)
        for key, state in snapshot.get("histograms", {}).items():
            self.record(_suffixed(key, "_count"), state.get("count", 0), at)
            self.record(_suffixed(key, "_sum"), state.get("sum", 0.0), at)

    def hydrate(self, rows: Iterable[Mapping[str, Any]]) -> int:
        """Replay persisted raw rows (oldest first) without re-queueing
        them for persistence — warm-restart path."""
        n = 0
        with self._lock:
            self._hydrating = True
        try:
            for row in rows:
                if row.get("agg", "raw") != "raw":
                    continue
                name = row.get("name")
                if not name:
                    continue
                self.record(name, row.get("value", 0.0), float(row.get("at", 0.0)))
                n += 1
        finally:
            with self._lock:
                self._hydrating = False
        return n

    def drain_pending(self, max_rows: int = 512) -> List[Dict[str, Any]]:
        """Pop up to ``max_rows`` rows for the registry write-behind:
        queued raw samples first, then rollup buckets sealed since the
        last drain."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            q = self._pending
            while q and len(out) < max_rows:
                out.append(q.popleft())
            if len(out) < max_rows:
                for series in self._series.values():
                    while series.sealed and len(out) < max_rows:
                        agg, bucket = series.sealed.pop(0)
                        out.append(bucket.row(series.key, agg))
                    if len(out) >= max_rows:
                        break
        return out

    # -- read path -------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._by_base)

    def series_keys(self, base: Optional[str] = None) -> List[str]:
        """Full labeled keys, optionally restricted to one base name."""
        with self._lock:
            if base is None:
                return sorted(self._series)
            return list(self._by_base.get(base, ()))

    def has_series(self, name: str) -> bool:
        base, _labels = split_labeled_key(name)
        with self._lock:
            return base in self._by_base

    def _matching(
        self, name: str, matchers: Optional[Mapping[str, str]]
    ) -> List[_Series]:
        base, inline = split_labeled_key(name)
        want = dict(inline)
        if matchers:
            want.update({k: str(v) for k, v in matchers.items()})
        out: List[_Series] = []
        for key in self._by_base.get(base, ()):
            _b, labels = split_labeled_key(key)
            if all(labels.get(k) == v for k, v in want.items()):
                out.append(self._series[key])
        return out

    def query(
        self,
        name: str,
        *,
        matchers: Optional[Mapping[str, str]] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        step: Optional[float] = None,
        agg: str = "avg",
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Aligned aggregation over every series matching ``name`` +
        ``matchers``.  Points are bucketed to ``floor(at/step)*step``
        (raw cadence when ``step`` is falsy) and reduced per bucket with
        ``agg`` ∈ {avg, min, max, sum, count, last} — rollup stages keep
        min/max/sum/count, so compacted history answers the same
        aggregates raw data would.
        """
        if agg not in ("avg", "min", "max", "sum", "count", "last"):
            raise ValueError(f"unknown agg {agg!r}")
        with self._lock:
            buckets: Dict[float, List[Tuple[float, float, Optional[_Bucket]]]] = {}
            for series in self._matching(name, matchers):
                for at, value, bucket in series.points(step):
                    if since is not None and at < since:
                        continue
                    if until is not None and at > until:
                        continue
                    t = (at // step) * step if step else at
                    buckets.setdefault(t, []).append((at, value, bucket))
        out: List[Dict[str, Any]] = []
        for t in sorted(buckets):
            pts = buckets[t]
            vmin = min(p[2].vmin if p[2] else p[1] for p in pts)
            vmax = max(p[2].vmax if p[2] else p[1] for p in pts)
            vsum = sum(p[2].vsum if p[2] else p[1] for p in pts)
            vcount = sum(p[2].vcount if p[2] else 1 for p in pts)
            if agg == "avg":
                value = vsum / vcount if vcount else 0.0
            elif agg == "min":
                value = vmin
            elif agg == "max":
                value = vmax
            elif agg == "sum":
                value = vsum
            elif agg == "count":
                value = float(vcount)
            else:  # last
                value = max(pts, key=lambda p: p[0])[1]
            out.append(
                {"at": t, "value": value, "min": vmin, "max": vmax, "count": vcount}
            )
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def increase(
        self,
        name: str,
        window_s: float,
        now: float,
        *,
        matchers: Optional[Mapping[str, str]] = None,
    ) -> Optional[float]:
        """Counter increase over the trailing window, summed across all
        label sets of the base name, counter resets clamped.  ``None``
        when no matching series has enough history — callers treat that
        as "signal absent", not zero."""
        since = now - float(window_s)
        total: Optional[float] = None
        with self._lock:
            for series in self._matching(name, matchers):
                inc = _increase(list(series.raw), since)
                if inc is not None:
                    total = inc if total is None else total + inc
        return total

    def rate(
        self,
        name: str,
        window_s: float,
        now: float,
        *,
        matchers: Optional[Mapping[str, str]] = None,
    ) -> Optional[float]:
        inc = self.increase(name, window_s, now, matchers=matchers)
        if inc is None or window_s <= 0:
            return None
        return inc / float(window_s)

    def latest(
        self, name: str, *, matchers: Optional[Mapping[str, str]] = None
    ) -> Optional[float]:
        with self._lock:
            best: Optional[Tuple[float, float]] = None
            for series in self._matching(name, matchers):
                if series.raw and (best is None or series.raw[-1][0] > best[0]):
                    best = series.raw[-1]
        return best[1] if best else None

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "series": len(self._series),
                "names": len(self._by_base),
                "folded": self.folded,
                "dropped": self.dropped,
                "pending": len(self._pending),
            }


# -- windowed deltas over cumulative counters/histograms ----------------------


class CounterWindow:
    """Trailing window over one cumulative counter: a ring of
    ``(at, value)`` samples kept for ``horizon_s``, answering
    ``increase``/``rate`` with reset clamping.  One sample at-or-before
    the window start is always retained so the baseline is exact."""

    __slots__ = ("horizon_s", "_samples")

    def __init__(self, horizon_s: float = 600.0) -> None:
        self.horizon_s = float(horizon_s)
        self._samples: Deque[Tuple[float, float]] = deque()

    def observe(self, value: float, at: float) -> None:
        self._samples.append((float(at), float(value)))
        while (
            len(self._samples) > 1
            and self._samples[1][0] <= at - self.horizon_s
        ):
            self._samples.popleft()

    def __len__(self) -> int:
        return len(self._samples)

    def increase(self, window_s: float, now: float) -> Optional[float]:
        return _increase(list(self._samples), now - float(window_s))

    def rate(self, window_s: float, now: float) -> Optional[float]:
        inc = self.increase(window_s, now)
        if inc is None or window_s <= 0:
            return None
        return inc / float(window_s)

    def latest(self) -> Optional[float]:
        return self._samples[-1][1] if self._samples else None


class RatioWindow:
    """Windowed numerator/denominator pair — shed fraction, cache hit
    rate, speculative accept rate: anything of the shape "events over
    opportunities in the last W seconds" over two cumulative counters."""

    __slots__ = ("num", "den")

    def __init__(self, horizon_s: float = 600.0) -> None:
        self.num = CounterWindow(horizon_s)
        self.den = CounterWindow(horizon_s)

    def observe(self, num: float, den: float, at: float) -> None:
        self.num.observe(num, at)
        self.den.observe(den, at)

    def deltas(self, window_s: float, now: float) -> Optional[Tuple[float, float]]:
        d_num = self.num.increase(window_s, now)
        d_den = self.den.increase(window_s, now)
        if d_num is None or d_den is None:
            return None
        return d_num, d_den

    def ratio(self, window_s: float, now: float) -> Optional[float]:
        d = self.deltas(window_s, now)
        if d is None:
            return None
        d_num, d_den = d
        return d_num / d_den if d_den > 0 else 0.0


def _quantile_from(
    edges: Sequence[float], counts: Sequence[int], count: int, q: float
) -> float:
    """``Histogram.quantile`` over a detached (edges, counts) pair —
    the delta buckets a :class:`HistogramWindow` produces."""
    if count <= 0:
        return 0.0
    target = max(1.0, q * count)
    running = 0
    for i, n in enumerate(counts):
        if n and running + n >= target:
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i] if i < len(edges) else edges[-1]
            return lo + (hi - lo) * ((target - running) / n)
        running += n
    return edges[-1] if edges else 0.0


class HistogramWindow:
    """Trailing window over cumulative histogram *snapshots* (the
    ``state()`` dicts a ``MemoryStats.snapshot()`` exports): windowed
    percentiles come from per-bucket deltas between the baseline and the
    latest snapshot — the histogram itself stays cumulative, so external
    scrapers never see counts go backwards (the ``Histogram.reset()``
    pattern this replaces)."""

    __slots__ = ("horizon_s", "_samples")

    def __init__(self, horizon_s: float = 600.0) -> None:
        self.horizon_s = float(horizon_s)
        self._samples: Deque[Tuple[float, Dict[str, Any]]] = deque()

    def observe(self, state: Mapping[str, Any], at: float) -> None:
        snap = {
            "edges": list(state.get("edges", ())),
            "counts": list(state.get("counts", ())),
            "count": int(state.get("count", 0)),
            "sum": float(state.get("sum", 0.0)),
        }
        self._samples.append((float(at), snap))
        while (
            len(self._samples) > 1
            and self._samples[1][0] <= at - self.horizon_s
        ):
            self._samples.popleft()

    def _delta(self, window_s: float, now: float) -> Optional[Dict[str, Any]]:
        if len(self._samples) < 2:
            return None
        since = now - float(window_s)
        base = self._samples[0][1]
        for at, snap in self._samples:
            if at <= since:
                base = snap
            else:
                break
        head = self._samples[-1][1]
        if head["count"] < base["count"] or len(head["counts"]) != len(
            base["counts"]
        ):
            # Counter reset (process restart / bucket relayout): the new
            # cumulative state counts from zero, so it IS the delta.
            base = {"edges": head["edges"], "counts": [0] * len(head["counts"]),
                    "count": 0, "sum": 0.0}
        counts = [
            max(0, h - b) for h, b in zip(head["counts"], base["counts"])
        ]
        return {
            "edges": head["edges"],
            "counts": counts,
            "count": max(0, head["count"] - base["count"]),
            "sum": max(0.0, head["sum"] - base["sum"]),
        }

    def quantile(self, q: float, window_s: float, now: float) -> Optional[float]:
        d = self._delta(window_s, now)
        if d is None:
            return None
        return _quantile_from(d["edges"], d["counts"], d["count"], q)

    def delta_count(self, window_s: float, now: float) -> Optional[int]:
        d = self._delta(window_s, now)
        return None if d is None else d["count"]

    def delta_sum(self, window_s: float, now: float) -> Optional[float]:
        d = self._delta(window_s, now)
        return None if d is None else d["sum"]


class WindowedView:
    """Keyed container of windows over a stats snapshot stream: feed it
    ``MemoryStats.snapshot()`` every tick and ask for windowed rates,
    increases, and percentiles by key."""

    def __init__(self, horizon_s: float = 600.0) -> None:
        self.horizon_s = float(horizon_s)
        self._counters: Dict[str, CounterWindow] = {}
        self._histograms: Dict[str, HistogramWindow] = {}

    def sample(self, snapshot: Mapping[str, Any], at: float) -> None:
        for key, value in snapshot.get("counters", {}).items():
            win = self._counters.get(key)
            if win is None:
                win = self._counters[key] = CounterWindow(self.horizon_s)
            win.observe(value, at)
        for key, state in snapshot.get("histograms", {}).items():
            hwin = self._histograms.get(key)
            if hwin is None:
                hwin = self._histograms[key] = HistogramWindow(self.horizon_s)
            hwin.observe(state, at)

    def counter(self, key: str) -> Optional[CounterWindow]:
        return self._counters.get(key)

    def histogram(self, key: str) -> Optional[HistogramWindow]:
        return self._histograms.get(key)

    def increase(self, key: str, window_s: float, now: float) -> Optional[float]:
        win = self._counters.get(key)
        return None if win is None else win.increase(window_s, now)

    def rate(self, key: str, window_s: float, now: float) -> Optional[float]:
        win = self._counters.get(key)
        return None if win is None else win.rate(window_s, now)

    def quantile(
        self, key: str, q: float, window_s: float, now: float
    ) -> Optional[float]:
        hwin = self._histograms.get(key)
        return None if hwin is None else hwin.quantile(q, window_s, now)


# -- burn-rate / baseline math ------------------------------------------------


def slo_status(
    store: MetricStore,
    *,
    bad: str,
    total: str,
    target: float,
    fast_s: float = 60.0,
    slow_s: float = 300.0,
    now: float,
    matchers: Optional[Mapping[str, str]] = None,
) -> Optional[Dict[str, Any]]:
    """Multi-window burn-rate status over an error-budget target.

    ``burn = (bad/total over window) / target`` — burn 1.0 consumes the
    budget exactly at the rate it refills.  The standard fast+slow pair:
    the *fast* window makes the alert responsive, the *slow* window
    keeps one spike from firing it — callers alert only when both burn.
    ``None`` when the total series has no history yet (signal absent).
    """
    d_total_slow = store.increase(total, slow_s, now, matchers=matchers)
    if d_total_slow is None:
        return None
    d_bad_slow = store.increase(bad, slow_s, now, matchers=matchers) or 0.0
    d_total_fast = store.increase(total, fast_s, now, matchers=matchers) or 0.0
    d_bad_fast = store.increase(bad, fast_s, now, matchers=matchers) or 0.0
    target = max(1e-9, float(target))

    def _burn(bad_n: float, total_n: float) -> float:
        if total_n <= 0:
            return 0.0
        return (bad_n / total_n) / target

    slow_frac = d_bad_slow / d_total_slow if d_total_slow > 0 else 0.0
    return {
        "target": target,
        "fast_window_s": float(fast_s),
        "slow_window_s": float(slow_s),
        "fast_burn": _burn(d_bad_fast, d_total_fast),
        "slow_burn": _burn(d_bad_slow, d_total_slow),
        "bad_fast": d_bad_fast,
        "total_fast": d_total_fast,
        "bad_slow": d_bad_slow,
        "total_slow": d_total_slow,
        "budget_remaining": max(0.0, 1.0 - slow_frac / target),
    }


#: The run-summary series folded into per-(project, kind) baselines on
#: completion — the comparator set the canary promote/rollback DAG reads.
BASELINE_SERIES: Tuple[Tuple[str, str], ...] = (
    ("run_mfu", "mfu"),
    ("run_goodput_ratio", "goodput_ratio"),
    ("run_tokens_per_device_s", "tokens_per_device_s"),
    ("run_spec_accept_rate", "spec_accept_rate"),
)


def fold_run_baselines(
    registry: Any,
    run: Any,
    *,
    alpha: float = 0.3,
    now: Optional[float] = None,
) -> Dict[str, Dict[str, Any]]:
    """Fold a completed run's summary series into its (project, kind)
    baseline rows.  Returns per-series fold results carrying the *prior*
    mean/std/count next to the new ones — the regression comparator
    judges the run against the baseline as it stood before the fold.
    """
    from polyaxon_tpu.monitor.watcher import goodput_status

    try:
        status = goodput_status(registry, run.id)
    except Exception:
        return {}
    if not status or not status.get("rows"):
        return {}
    project = getattr(run, "project", None) or "default"
    kind = getattr(run, "kind", None) or "run"
    out: Dict[str, Dict[str, Any]] = {}
    for series, field in BASELINE_SERIES:
        value = status.get(field)
        if value is None or float(value) <= 0.0:
            continue
        folded = registry.fold_metric_baseline(
            project,
            kind,
            series,
            float(value),
            alpha=alpha,
            run_id=run.id,
            now=now,
        )
        if folded:
            out[series] = folded
    return out


# -- the monitor tick's scrape phase ------------------------------------------

#: Closed vocabulary of numeric ``/v1/stats`` fields scraped per replica
#: — a bounded allowlist, so a chatty engine can't mint series.
_REPLICA_FIELDS: Tuple[str, ...] = (
    "slots",
    "slots_active",
    "queue_depth",
    "blocks_free",
    "block_occupancy",
    "prefix_cache_hit_rate",
    "prefix_cache_hit_rate_window",
    "spec_accept_rate",
    "spec_accept_rate_window",
    "requests_submitted",
    "requests_finished",
    "requests_shed",
    "tokens_generated",
    "tokens_per_s",
    "decode_steps",
)

#: Router counter names re-emitted as per-fleet series (closed set —
#: mirrors ``FleetRouter.counters``).
_ROUTER_COUNTERS: Tuple[str, ...] = (
    "requests",
    "sheds",
    "retries",
    "failovers",
    "ejections",
    "readmissions",
    "drains",
    "upstream_errors",
)


class MetricScraper:
    """The scrape phase of the monitor tick.

    Called every tick but internally throttled to ``interval_s`` — a
    pass that isn't due costs microseconds, so N runs ticking at 50ms
    don't multiply the scrape cost.  Each due pass samples:

    * the control-plane stats backend (counters + gauges verbatim,
      histograms as ``_count``/``_sum`` series),
    * every registered fleet's router counters (``router_*_total``
      labeled by fleet) and each replica's last probe stats (labeled by
      fleet + replica — riding the router's existing probe results, no
      new connections),
    * a derived ``router_shed_fraction_window`` gauge per fleet from the
      shared :class:`RatioWindow`,

    then flushes up to ``flush_rows`` sealed samples to the registry's
    ``metric_samples`` table.  Scrape errors are counted, never raised —
    a wedged fleet must not take the monitor tick down with it.
    """

    def __init__(
        self,
        store: MetricStore,
        *,
        stats: Any = None,
        registry: Any = None,
        fleets: Optional[Callable[[], Iterable[Any]]] = None,
        interval_s: float = 5.0,
        flush_rows: int = 512,
        window_s: float = 60.0,
    ) -> None:
        self.store = store
        self.stats = stats
        self.registry = registry
        self.fleets = fleets
        self.interval_s = max(0.05, float(interval_s))
        self.flush_rows = max(1, int(flush_rows))
        self.window_s = max(1.0, float(window_s))
        self.last_scrape = 0.0
        self.scrapes = 0
        self.errors = 0
        self.flushed_rows = 0
        self._fleet_windows: Dict[str, RatioWindow] = {}
        #: Label-key strings are pure functions of (name, fleet, replica)
        #: — cache them so the per-replica fan-out doesn't rebuild
        #: several hundred sorted/escaped key strings every scrape.
        self._key_cache: Dict[Tuple[str, ...], str] = {}

    def _fleet_key(self, name: str, fleet: str) -> str:
        ck = (name, fleet)
        key = self._key_cache.get(ck)
        if key is None:
            key = self._key_cache[ck] = labeled_key(name, fleet=fleet)
        return key

    def _replica_key(self, name: str, fleet: str, replica: str) -> str:
        ck = (name, fleet, replica)
        key = self._key_cache.get(ck)
        if key is None:
            key = self._key_cache[ck] = labeled_key(
                name, fleet=fleet, replica=replica
            )
        return key

    def tick(self, now: float) -> bool:
        """One monitor-tick entry; returns True when a scrape ran."""
        if now - self.last_scrape < self.interval_s:
            return False
        self.last_scrape = now
        self.scrapes += 1
        try:
            self._scrape_control_plane(now)
        except Exception:
            self.errors += 1
        try:
            self._scrape_fleets(now)
        except Exception:
            self.errors += 1
        try:
            self._flush()
        except Exception:
            self.errors += 1
        return True

    def _scrape_control_plane(self, now: float) -> None:
        if self.stats is None:
            return
        snap = self.stats.snapshot(include_timings=False)
        self.store.record_snapshot(snap, now)

    def _scrape_fleets(self, now: float) -> None:
        if self.fleets is None:
            return
        for fleet in list(self.fleets() or ()):
            router = getattr(fleet, "router", None)
            if router is None:
                continue
            fleet_name = str(getattr(fleet, "name", "") or "fleet")
            try:
                rstats = router.stats()
            except Exception:
                self.errors += 1
                continue
            counters = rstats.get("counters", {})
            for cname in _ROUTER_COUNTERS:
                if cname in counters:
                    key = self._fleet_key("router_" + cname + "_total", fleet_name)
                    self.store.record(key, counters[cname], now)
            self.store.record(
                self._fleet_key("router_ready_replicas", fleet_name),
                rstats.get("n_ready", 0),
                now,
            )
            win = self._fleet_windows.get(fleet_name)
            if win is None:
                win = self._fleet_windows[fleet_name] = RatioWindow(
                    max(self.window_s * 10.0, 600.0)
                )
            win.observe(
                counters.get("sheds", 0), counters.get("requests", 0), now
            )
            shed_frac = win.ratio(self.window_s, now)
            if shed_frac is not None:
                self.store.record(
                    self._fleet_key("router_shed_fraction_window", fleet_name),
                    shed_frac,
                    now,
                )
            replica_stats = getattr(router, "replica_stats", None)
            if replica_stats is None:
                continue
            try:
                per_replica = replica_stats()
            except Exception:
                self.errors += 1
                continue
            for rep_name, rep in per_replica.items():
                if not isinstance(rep, Mapping):
                    continue
                for field in _REPLICA_FIELDS:
                    value = rep.get(field)
                    if value is None:
                        continue
                    key = self._replica_key(
                        "replica_" + field, fleet_name, rep_name
                    )
                    self.store.record(key, value, now)

    def _flush(self) -> None:
        if self.registry is None:
            return
        rows = self.store.drain_pending(self.flush_rows)
        if not rows:
            return
        try:
            self.registry.add_metric_samples(rows)
            self.flushed_rows += len(rows)
        except Exception:
            self.errors += 1

    def status(self) -> Dict[str, Any]:
        out = {
            "interval_s": self.interval_s,
            "last_scrape": self.last_scrape,
            "scrapes": self.scrapes,
            "errors": self.errors,
            "flushed_rows": self.flushed_rows,
        }
        out.update(self.store.status())
        return out
