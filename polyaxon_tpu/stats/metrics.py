"""Log-bucketed histograms + Prometheus text exposition.

The reference's StatsD backend ships raw timers and lets the aggregation
server own percentiles; in-process we only kept a bounded window of raw
samples (``MemoryStats.timings``), which can't answer p99 over a long run
without unbounded memory.  :class:`Histogram` fixes that: geometric
("log-bucketed") buckets hold count/sum per bucket, so percentile
estimates cost O(buckets) memory forever, and the bucket layout maps 1:1
onto Prometheus histogram exposition (cumulative ``le`` buckets +
``_sum``/``_count``).

:func:`render_prometheus` turns a ``MemoryStats.snapshot()`` into
text-exposition v0.0.4 — the payload behind ``GET /metrics`` on both the
control plane and ``lm_server``.
"""

from __future__ import annotations

import math
import re
import time as _time
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

__all__ = [
    "Histogram",
    "default_buckets",
    "labeled_key",
    "split_labeled_key",
    "fold_labeled_key",
    "render_prometheus",
    "render_standard_gauges",
    "PROMETHEUS_CONTENT_TYPE",
]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def default_buckets(start: float = 1e-4, factor: float = 2.0, count: int = 20) -> List[float]:
    """Geometric bucket edges: ``start * factor**k`` for k in [0, count).

    The default spans 100µs .. ~52s with 2x resolution — wide enough for
    queue waits, decode steps, and whole train steps on one layout.
    """
    edges: List[float] = []
    edge = start
    for _ in range(count):
        edges.append(edge)
        edge *= factor
    return edges


class Histogram:
    """Fixed-bucket histogram with cumulative export and quantile estimates.

    Not internally locked — ``MemoryStats`` serializes access; standalone
    users on multiple threads must bring their own lock.
    """

    __slots__ = ("edges", "counts", "count", "sum")

    def __init__(self, edges: Optional[Sequence[float]] = None) -> None:
        self.edges: List[float] = list(edges) if edges is not None else default_buckets()
        if not self.edges:
            raise ValueError("histogram needs at least one bucket edge")
        if any(b <= a for a, b in zip(self.edges, self.edges[1:])):
            raise ValueError("bucket edges must be strictly increasing")
        # One slot per edge plus the +Inf overflow bucket.
        self.counts: List[int] = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect_left(self.edges, v)] += 1
        self.count += 1
        self.sum += v

    def cumulative(self) -> List[int]:
        """Cumulative counts per edge (``le`` semantics); +Inf == count."""
        out: List[int] = []
        running = 0
        for n in self.counts[:-1]:
            running += n
            out.append(running)
        return out

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile by linear interpolation within a bucket."""
        if self.count <= 0:
            return 0.0
        target = max(1.0, q * self.count)
        running = 0
        for i, n in enumerate(self.counts):
            if n and running + n >= target:
                lo = self.edges[i - 1] if i > 0 else 0.0
                hi = self.edges[i] if i < len(self.edges) else self.edges[-1]
                return lo + (hi - lo) * ((target - running) / n)
            running += n
        return self.edges[-1]

    def summary(self) -> Dict[str, float]:
        mean = self.sum / self.count if self.count else 0.0
        return {
            "count": float(self.count),
            "sum": self.sum,
            "mean": mean,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    def reset(self) -> None:
        """Deprecated: zero all buckets in place (bucket layout kept).

        Resetting a live histogram breaks cumulative-counter semantics
        for any external scraper that samples it mid-window — count and
        sum go *backwards*, which Prometheus-style rate math reads as a
        process restart.  Keep histograms cumulative and compute rolling
        windows from snapshot deltas instead
        (``polyaxon_tpu.stats.tsdb.HistogramWindow`` / ``WindowedView``).
        """
        import warnings

        warnings.warn(
            "Histogram.reset() is deprecated: it breaks cumulative-counter "
            "semantics for concurrent scrapers; use "
            "polyaxon_tpu.stats.tsdb.HistogramWindow snapshot deltas for "
            "rolling windows",
            DeprecationWarning,
            stacklevel=2,
        )
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum = 0.0

    def state(self) -> Dict[str, Any]:
        """Copyable snapshot (what ``MemoryStats.snapshot()`` exports)."""
        return {
            "edges": list(self.edges),
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.sum,
        }


# -- Prometheus text exposition (v0.0.4) ---------------------------------------

_INVALID_NAME_CHARS = re.compile(r"[^a-zA-Z0-9_:]")

#: Fallback process start time when psutil is unavailable: first import of
#: this module, which happens early in every entrypoint's life.
_IMPORT_TIME = _time.time()


def _process_start_time() -> float:
    try:
        import psutil

        return float(psutil.Process().create_time())
    except Exception:
        return _IMPORT_TIME


def _metric_name(key: str, prefix: str) -> str:
    name = _INVALID_NAME_CHARS.sub("_", key)
    if prefix:
        name = f"{prefix}_{name}"
    if not re.match(r"[a-zA-Z_:]", name):
        name = f"_{name}"
    return name


# Stats backends key counters/gauges by flat strings; per-series labels
# (``alert_state{rule=...,run=...}``) ride *inside* the key in exposition
# syntax, produced by :func:`labeled_key` and split back out by the
# renderer so base labels merge in.  Label order is sorted → one series
# per (name, labels) set no matter the caller's kwarg order.
_LABELED_KEY = re.compile(r"^(?P<name>[^{]+)\{(?P<body>.*)\}$")
_LABEL_PAIR = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def labeled_key(name: str, **labels: Any) -> str:
    if not labels:
        return name
    body = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return f"{name}{{{body}}}"


def split_labeled_key(key: str) -> "tuple[str, Dict[str, str]]":
    m = _LABELED_KEY.match(key)
    if not m:
        return key, {}
    pairs = {k: v for k, v in _LABEL_PAIR.findall(m.group("body"))}
    return m.group("name"), pairs


def _escape_label_value(value: Any) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def fold_labeled_key(key: str) -> str:
    """The overflow series a labeled key collapses into when a backend's
    per-metric cardinality cap is hit: same base name and label *keys*,
    every label *value* replaced by ``other``.  One fold series per
    (name, label-key-set), so a runaway caller degrades to a bounded
    aggregate instead of growing ``/metrics`` without limit.
    """
    base, labels = split_labeled_key(key)
    if not labels:
        return key
    return labeled_key(base, **{k: "other" for k in labels})


def _labels(pairs: Mapping[str, Any]) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs.items())
    return "{%s}" % body


def _fmt(value: float) -> str:
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render_prometheus(
    snapshot: Mapping[str, Any],
    prefix: str = "polyaxon_tpu",
    labels: Optional[Mapping[str, Any]] = None,
) -> str:
    """Render a ``MemoryStats.snapshot()`` as Prometheus text exposition.

    Counters are exported with a ``_total`` suffix (idempotently — a
    stat key already named ``*_total`` is not doubled), gauges verbatim,
    and histograms as cumulative ``_bucket{le=...}`` series + ``_sum``
    and ``_count``.  ``labels`` (e.g. ``{"process": "lm_server"}``) are
    added to every sample.
    """
    base_labels = dict(labels or {})
    lines: List[str] = []

    # Labeled keys of the same metric sort adjacently, so one TYPE line
    # per name is just "don't repeat the last one".
    last_typed = ""
    for key in sorted(snapshot.get("counters", {})):
        value = snapshot["counters"][key]
        base, own = split_labeled_key(key)
        name = _metric_name(base, prefix)
        if not name.endswith("_total"):
            name += "_total"
        if name != last_typed:
            lines.append(f"# TYPE {name} counter")
            last_typed = name
        series = dict(base_labels, **own) if own else base_labels
        lines.append(f"{name}{_labels(series)} {_fmt(value)}")

    last_typed = ""
    for key in sorted(snapshot.get("gauges", {})):
        value = snapshot["gauges"][key]
        base, own = split_labeled_key(key)
        name = _metric_name(base, prefix)
        if name != last_typed:
            lines.append(f"# TYPE {name} gauge")
            last_typed = name
        series = dict(base_labels, **own) if own else base_labels
        lines.append(f"{name}{_labels(series)} {_fmt(value)}")

    # Labeled histogram keys (``registry_op_s{op="write"}``) split like
    # counters/gauges: the label set rides every bucket/sum/count sample
    # of that series, and same-name series share one TYPE line (sorted
    # keys put them adjacent).
    last_typed = ""
    for key in sorted(snapshot.get("histograms", {})):
        state = snapshot["histograms"][key]
        base, own = split_labeled_key(key)
        name = _metric_name(base, prefix)
        edges: Sequence[float] = state["edges"]
        counts: Sequence[int] = state["counts"]
        if name != last_typed:
            lines.append(f"# TYPE {name} histogram")
            last_typed = name
        series = dict(base_labels, **own) if own else base_labels
        running = 0
        for edge, n in zip(edges, counts):
            running += n
            bucket_labels = dict(series)
            bucket_labels["le"] = _fmt(edge)
            lines.append(f"{name}_bucket{_labels(bucket_labels)} {running}")
        inf_labels = dict(series)
        inf_labels["le"] = "+Inf"
        lines.append(f"{name}_bucket{_labels(inf_labels)} {state['count']}")
        lines.append(f"{name}_sum{_labels(series)} {_fmt(state['sum'])}")
        lines.append(f"{name}_count{_labels(series)} {state['count']}")

    return "\n".join(lines) + "\n"


def render_standard_gauges(labels: Optional[Mapping[str, Any]] = None) -> str:
    """Exposition hygiene every scrape target should carry: the standard
    ``process_start_time_seconds`` (Prometheus derives restarts/uptime
    from it) and a ``polyaxon_tpu_build_info`` info-gauge whose labels
    pin the build version.  Appended to ``/metrics`` on both the control
    plane and ``lm_server``.
    """
    try:
        from polyaxon_tpu.version import __version__ as version
    except Exception:
        version = "unknown"
    base_labels = dict(labels or {})
    info_labels = dict(base_labels)
    info_labels["version"] = version
    lines = [
        "# TYPE process_start_time_seconds gauge",
        f"process_start_time_seconds{_labels(base_labels)} {_fmt(_process_start_time())}",
        "# TYPE polyaxon_tpu_build_info gauge",
        f"polyaxon_tpu_build_info{_labels(info_labels)} 1",
    ]
    return "\n".join(lines) + "\n"
