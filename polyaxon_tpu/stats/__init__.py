from polyaxon_tpu.stats.backends import MemoryStats, NoOpStats, StatsBackend, StatsdStats

__all__ = ["MemoryStats", "NoOpStats", "StatsBackend", "StatsdStats"]
