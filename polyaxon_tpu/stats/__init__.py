import threading

from polyaxon_tpu.stats.backends import MemoryStats, NoOpStats, StatsBackend, StatsdStats
from polyaxon_tpu.stats.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    Histogram,
    default_buckets,
    render_prometheus,
    render_standard_gauges,
)
from polyaxon_tpu.stats.tsdb import (
    CounterWindow,
    HistogramWindow,
    MetricScraper,
    MetricStore,
    RatioWindow,
    WindowedView,
    fold_run_baselines,
    slo_status,
)

__all__ = [
    "MemoryStats",
    "NoOpStats",
    "StatsBackend",
    "StatsdStats",
    "Histogram",
    "default_buckets",
    "render_prometheus",
    "render_standard_gauges",
    "PROMETHEUS_CONTENT_TYPE",
    "get_stats",
    "MetricStore",
    "MetricScraper",
    "CounterWindow",
    "RatioWindow",
    "HistogramWindow",
    "WindowedView",
    "slo_status",
    "fold_run_baselines",
]

_default_stats = None
_default_stats_lock = threading.Lock()


def get_stats() -> MemoryStats:
    """Process-wide ``MemoryStats`` registry.

    Worker-side components that have no orchestrator to hand them a
    backend (trainers, the serving engine inside ``lm_server``) record
    here by default, so one ``/metrics`` scrape of the process sees all
    of them.  The control plane keeps its own per-orchestrator backend.
    """
    global _default_stats
    if _default_stats is None:
        with _default_stats_lock:
            if _default_stats is None:
                _default_stats = MemoryStats()
    return _default_stats
