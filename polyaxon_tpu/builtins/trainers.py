"""Built-in entrypoints: quick-start trainers + test probes.

Parity: the reference's quick-start workloads (MNIST/CIFAR polyaxonfiles in
its docs/tutorials) — here as in-process jax entrypoints any spec can point
at (``run: {entrypoint: polyaxon_tpu.builtins.trainers:<name>}``).  The
probe entrypoints (`failing`, `sleepy`, `flaky_once`) exist for the
platform's own failure-handling tests, like the reference's fixture specs.
"""

from __future__ import annotations

import time

from polyaxon_tpu.tracking import Context


def noop(ctx: Context) -> None:
    """Smallest possible run: report one metric."""
    ctx.log_text("noop trainer running")
    ctx.log_metrics(step=0, done=1.0)


def failing(ctx: Context) -> None:
    """Always fails (failure-path probe)."""
    raise RuntimeError("intentional failure")


def sleepy(ctx: Context) -> None:
    """Sleeps `seconds` (stop/zombie probe)."""
    time.sleep(float(ctx.get_param("seconds", 30.0)))


def flaky_once(ctx: Context) -> None:
    """Fails on the first gang attempt, succeeds after restart.

    Uses a marker file in outputs/ (which survives a gang restart) to
    remember the first attempt.
    """
    marker = ctx.outputs_path / f"attempt_p{ctx.process_id}"
    if not marker.exists():
        marker.write_text("1")
        raise RuntimeError("flaky first attempt")
    ctx.log_metrics(recovered=1.0)


def synthetic_regression(ctx: Context) -> None:
    """A real (tiny) distributed training loop: pjit linear regression.

    Exercises the full TPU-native path — mesh, NamedSharding, jit train
    step, metric reporting — at a size that runs in milliseconds on the
    virtual CPU mesh.  Params: lr, steps, batch, dim.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    lr = float(ctx.get_param("lr", 0.1))
    steps = int(ctx.get_param("steps", 20))
    batch = int(ctx.get_param("batch", 64))
    dim = int(ctx.get_param("dim", 8))
    seed = ctx.seed if ctx.seed is not None else 0

    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim, 1)).astype(np.float32)
    x = rng.normal(size=(batch, dim)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(batch, 1)).astype(np.float32)

    params = {"w": jnp.zeros((dim, 1), jnp.float32)}
    opt = optax.sgd(lr)
    opt_state = opt.init(params)

    mesh = ctx.mesh
    if mesh is not None:
        data_axes = tuple(n for n in mesh.axis_names if n in ("data", "fsdp", "replica"))
        batch_sharding = NamedSharding(mesh, P(data_axes if data_axes else None))
        x = jax.device_put(x, batch_sharding)
        y = jax.device_put(y, batch_sharding)

    @jax.jit
    def step(params, opt_state, x, y):
        def loss_fn(p):
            pred = x @ p["w"]
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    loss = None
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        if ctx.is_leader and (i % 5 == 0 or i == steps - 1):
            ctx.log_metrics(step=i, loss=float(loss))
    ctx.log_text(f"final loss {float(loss):.6f}")
