"""Built-in entrypoints: quick-start trainers + test probes.

Parity: the reference's quick-start workloads (MNIST/CIFAR polyaxonfiles in
its docs/tutorials) — here as in-process jax entrypoints any spec can point
at (``run: {entrypoint: polyaxon_tpu.builtins.trainers:<name>}``).  The
probe entrypoints (`failing`, `sleepy`, `flaky_once`) exist for the
platform's own failure-handling tests, like the reference's fixture specs.
"""

from __future__ import annotations

import functools
import time

from polyaxon_tpu.stats import get_stats
from polyaxon_tpu.tracking import Context
from polyaxon_tpu.tracking.flightrec import get_progress
from polyaxon_tpu.tracking.trace import get_tracer


def _percentile_metrics(run_stats, key: str, out_prefix: str) -> dict:
    """Histogram percentiles for ``key`` as flat metric fields."""
    summary = run_stats.summaries().get(key)
    if not summary or not summary["count"]:
        return {}
    return {
        f"{out_prefix}_p50": summary["p50"],
        f"{out_prefix}_p95": summary["p95"],
        f"{out_prefix}_p99": summary["p99"],
    }


def noop(ctx: Context) -> None:
    """Smallest possible run: report one metric."""
    ctx.log_text("noop trainer running")
    ctx.log_metrics(step=0, done=1.0)


def failing(ctx: Context) -> None:
    """Always fails (failure-path probe)."""
    raise RuntimeError("intentional failure")


def sleepy(ctx: Context) -> None:
    """Sleeps `seconds` (stop/zombie probe)."""
    time.sleep(float(ctx.get_param("seconds", 30.0)))


def flaky_once(ctx: Context) -> None:
    """Fails on the first gang attempt, succeeds after restart.

    Uses a marker file in outputs/ (which survives a gang restart) to
    remember the first attempt.
    """
    marker = ctx.outputs_path / f"attempt_p{ctx.process_id}"
    if not marker.exists():
        marker.write_text("1")
        raise RuntimeError("flaky first attempt")
    ctx.log_metrics(recovered=1.0)


def stalling(ctx: Context) -> None:
    """Beats the progress beacon, then one process goes silent
    (stall/straggler-detection probe).

    Every process beats ``warm_steps`` steps ``beat_interval`` apart, then
    the ``stall_process`` victim (-1 = all of them) sleeps ``stall_s``
    without beating while its peers advance ``peer_steps`` more — which is
    what distinguishes a gang-wide *stall* (everyone silent, heartbeats
    fresh) from a *straggler* (one host falling behind the gang median).

    ``recover_steps`` > 0 makes the victim resume beating after the sleep
    (``recover_interval`` apart) — a stall that *clears* while the gang is
    still running, which is what the alert engine's firing → resolved
    transition needs to be tested against honestly.
    """
    progress = get_progress()
    warm = int(ctx.get_param("warm_steps", 5))
    interval = float(ctx.get_param("beat_interval", 0.02))
    for i in range(warm):
        progress.beat(step=i)
        time.sleep(interval)
    victim = int(ctx.get_param("stall_process", -1))
    recover = int(ctx.get_param("recover_steps", 0))
    if victim in (-1, ctx.process_id):
        time.sleep(float(ctx.get_param("stall_s", 2.0)))
        recover_interval = float(ctx.get_param("recover_interval", interval))
        for i in range(warm, warm + recover):
            progress.beat(step=i)
            time.sleep(recover_interval)
    else:
        for i in range(warm, warm + int(ctx.get_param("peer_steps", 100))):
            progress.beat(step=i)
            time.sleep(interval)
    ctx.log_metrics(step=warm, done=1.0)


def resume_counter(ctx: Context) -> None:
    """Counts resume attempts via a checkpoint file (artifact-store probe).

    Each attempt reads the counter from checkpoints/ (which clone/resume
    restores — from the local run dir or the artifact store), increments
    it, and reports it; outputs/ gets a marker file so output shipping is
    observable too.
    """
    state = ctx.checkpoints_path / "counter.txt"
    n = int(state.read_text()) if state.exists() else 0
    state.write_text(str(n + 1))
    (ctx.outputs_path / f"attempt_{n + 1}.marker").write_text("ok")
    ctx.log_metrics(step=n + 1, counter=float(n + 1))
    ctx.log_text(f"resume_counter attempt {n + 1}")


def _fault_injection(ctx: Context):
    """Per-step fault injector for the declared chaos params, or None.

    ``preempt_step``/``preempt_process``/``preempt_signal`` kill a worker
    mid-loop with REAL process death (SIGKILL, or SIGTERM then SIGKILL
    after ``preempt_grace_s`` — the preemption-notice shape), once per run:
    an outputs marker survives the restart so the resumed attempt trains
    through.  ``stall_at_step``/``stall_s``/``stall_process`` silence a
    worker's progress beats mid-loop (heartbeats keep flowing) to trip the
    stall/straggler detectors against a live train loop.
    """
    preempt_step = int(ctx.get_param("preempt_step", -1))
    stall_at = int(ctx.get_param("stall_at_step", -1))
    stall_s = float(ctx.get_param("stall_s", 0.0))
    if preempt_step < 0 and (stall_at < 0 or stall_s <= 0):
        return None
    preempt_process = int(ctx.get_param("preempt_process", 0))
    preempt_signal = str(ctx.get_param("preempt_signal", "kill"))
    preempt_grace_s = float(ctx.get_param("preempt_grace_s", 0.5))
    stall_process = int(ctx.get_param("stall_process", -1))

    def on_step(step: int) -> None:
        import os
        import signal as _signal

        if step == stall_at and stall_s > 0 and stall_process in (-1, ctx.process_id):
            ctx.log_text(f"injecting {stall_s:.1f}s stall at step {step}")
            time.sleep(stall_s)
        if step == preempt_step and preempt_process in (-1, ctx.process_id):
            marker = None
            if ctx.outputs_path is not None:
                marker = ctx.outputs_path / f"preempted_p{ctx.process_id}"
                if marker.exists():
                    return
                marker.write_text(str(step))
            ctx.log_text(
                f"injecting preemption at step {step} (signal={preempt_signal})"
            )
            if preempt_signal == "term":
                os.kill(os.getpid(), _signal.SIGTERM)
                time.sleep(max(preempt_grace_s, 0.0))
            os.kill(os.getpid(), _signal.SIGKILL)

    return on_step


def _should_measure_flops(ctx: Context, backend: str) -> bool:
    """Whether to probe per-step FLOPs via XLA cost analysis.

    The probe (``lower().compile()``) costs one extra compile of the
    step, so ``auto`` measures only on CPU (where compiles are cheap and
    the e2e path exercises cost analysis) and trusts the analytic
    estimate on TPU.  ``flops_probe: measure|analytic`` overrides."""
    mode = str(ctx.get_param("flops_probe", "auto"))
    if mode == "measure":
        return True
    if mode == "analytic":
        return False
    return backend == "cpu"


def _train_image_classifier(
    ctx: Context,
    *,
    label: str,
    loss_fn,
    accuracy_fn,
    init_fn,
    axes_tree,
    optimizer,
    flops_per_example: float = 0.0,
) -> None:
    """Shared image-classifier train loop (cnn_train / vit_train).

    Two data paths, one loop:

    - ``dataset: <name>`` — a store-registered dataset (see
      ``runtime/datasets.py``): host-sharded mmap shard reading, per-epoch
      shuffles, uint8 on the wire, and a position-exact resume (the data
      stream fast-forwards to the restored step).  ``cifar10-train`` after
      ``register_cifar10`` is the reference's CIFAR-10 guide
      (``docs/guides/training-cifar10.md``).
    - no dataset — synthetic class-conditional images (deterministic from
      the seed), isolating compute+collectives from IO for benchmarks.

    The hot loop is OVERLAPPED (see ``docs/pipeline.md``): host-side row
    gathers run ``prefetch`` batches ahead on ``prefetch_workers`` threads,
    the next batch's device placement is dispatched before the current
    step is consumed, checkpoint saves are async, and loss logging drains
    on a background thread — the device never waits on the host for any of
    them.  ``prefetch: 0`` restores the fully synchronous loop
    (byte-identical data stream; the A/B baseline).
    """
    import itertools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.parallel import template_for
    from polyaxon_tpu.runtime.data import global_batch_from_host_data
    from polyaxon_tpu.runtime.pipeline import MetricsDrain, TrainPipeline
    from polyaxon_tpu.runtime.train import build_train_step
    from polyaxon_tpu.tracking.ledger import get_ledger
    from polyaxon_tpu.tracking.profiling import StepClock, StepProfiler

    # Arm the utilization ledger first: model build, jit init, and data
    # setup all belong to this run's wall clock.
    led = get_ledger().start(source="train")

    steps = int(ctx.get_param("steps", 20))
    batch_size = int(ctx.get_param("batch", 64))
    image_size = int(ctx.get_param("image_size", 32))
    n_classes = int(ctx.get_param("classes", 10))
    dataset = ctx.get_param("dataset")
    save_every = int(ctx.get_param("save_every", 0))
    prefetch = int(ctx.get_param("prefetch", 2))
    prefetch_workers = int(ctx.get_param("prefetch_workers", 2))

    mesh = ctx.mesh
    if mesh is None:
        from polyaxon_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"data": jax.device_count()})
    template = template_for(ctx.strategy, dict(mesh.shape), ctx.strategy_options)

    ts = build_train_step(
        loss_fn=lambda p, b: loss_fn(p, b, template, mesh),
        init_fn=init_fn,
        axes_tree=axes_tree,
        optimizer=optimizer,
        mesh=mesh,
        template=template,
    )
    key = jax.random.PRNGKey(ctx.seed or 0)
    params, opt_state = ts.init(key)

    # Checkpoint/resume (same contract as lm_train): restore whatever the
    # checkpoints/ dir holds — a resumed clone inherits the original's.
    start_step = 0
    ckpt = None
    if save_every > 0 and ctx.checkpoints_path is not None:
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        ckpt = CheckpointManager(ctx.checkpoints_path, save_interval_steps=save_every)
        restored = ckpt.restore(params, opt_state)
        if restored is not None:
            params, opt_state = restored["params"], restored["opt_state"]
            start_step = restored["step"] + 1
            ctx.log_text(f"restored checkpoint at step {restored['step']}")

    if dataset is not None:
        from polyaxon_tpu.runtime.datasets import DatasetReader

        reader = DatasetReader(
            ctx.data_path,
            str(dataset),
            global_batch=batch_size,
            seed=ctx.seed or 0,
            num_processes=ctx.num_processes,
            process_id=ctx.process_id,
        )

        def place(local):
            return global_batch_from_host_data(
                {
                    "images": local["images"],
                    "labels": local["labels"].astype(np.int32),
                },
                ts.batch_sharding,
            )

        # Host prefetch over gather thunks + device prefetch onto the
        # step's batch sharding; each host prefetches only its own rows.
        pipe = TrainPipeline(
            reader.batch_tasks(start_step),
            place,
            prefetch=prefetch,
            workers=prefetch_workers,
        )
    else:
        # Synthetic class-conditional images (the fixture dataset's exact
        # recipe — shared helper so benchmark and fixture never diverge).
        from polyaxon_tpu.runtime.datasets import synthetic_class_images

        rng = np.random.default_rng(ctx.seed or 0)
        images, labels = synthetic_class_images(
            rng, batch_size, image_size, n_classes
        )
        fixed = ts.place_batch(
            {"images": jnp.asarray(images), "labels": jnp.asarray(labels)}
        )
        pipe = TrainPipeline(
            itertools.repeat(fixed), prefetch=0, tasks=False
        )

    acc_fn = jax.jit(lambda p, b: accuracy_fn(p, b, template, mesh))
    profiler = StepProfiler(
        ctx.outputs_path or ".",
        start_step=int(ctx.get_param("profile_start", -1)),
        num_steps=int(ctx.get_param("profile_steps", 0)),
    )
    # On-demand capture (control-plane `profile` commands): same per-step
    # hook as the launch-time profiler, armed only when a command arrives.
    from polyaxon_tpu.tracking.capture import get_capture_agent

    capture = get_capture_agent()
    ckpt_now = None
    if ckpt is not None:
        from polyaxon_tpu.runtime.checkpoint import CheckpointNowService

        ckpt_now = CheckpointNowService(ckpt, capture)
    inject = _fault_injection(ctx)
    drain = MetricsDrain(lambda step, vals: ctx.log_metrics(step=step, **vals))
    clock = StepClock()
    tracer = get_tracer()
    run_stats = get_stats()
    progress = get_progress()
    metrics = None
    batch = None
    # FLOPs denominator for live MFU: XLA cost analysis of the compiled
    # step where cheap (see _should_measure_flops — probed in-loop, once
    # the first real batch exists), analytic conv/attention estimate
    # otherwise.
    measure_flops = _should_measure_flops(ctx, jax.default_backend())
    led.set_flops_per_step(flops_per_example * batch_size)
    data_wait_accounted = 0.0
    from polyaxon_tpu.runtime.compilecache import aot_compile

    # Peek the first batch BEFORE the loop: it feeds the FLOPs probe and
    # the AOT compile of the step, so both land in the ledger's pre-loop
    # bucket (mark_loop_start below) instead of inside the first step's
    # measured wall — and with the persistent cache armed, a warm
    # restart loads the executable from disk.  step_fn is the compiled
    # executable; calling the jitted ts.step afterwards would compile a
    # second time.  The peeked batch is consumed at start_step, so the
    # data stream is position-identical.
    warm_batch = None
    step_fn, aot_s = ts.step, 0.0
    if steps > start_step:
        warm_batch = next(pipe)
        dwait = pipe.pop_data_wait_s()
        run_stats.timing("train.data_wait_s", dwait)
        led.account("data_wait_s", dwait)
        data_wait_accounted += dwait
        with tracer.span("train.aot_compile"):
            step_fn, aot_s = aot_compile(
                ts.step, params, opt_state, warm_batch, key
            )
        if step_fn is not ts.step:
            capture.register_executable("train_step", step_fn)
        if measure_flops:
            from polyaxon_tpu.tracking.ledger import executable_flops

            led.set_flops_per_step(
                executable_flops(step_fn)
                or ts.step_flops(params, opt_state, warm_batch, key)
                or flops_per_example * batch_size
            )
    first_step_s = None
    t0 = time.time()
    clock.start()
    led.mark_loop_start()
    try:
        with tracer.span("train.loop", steps=steps - start_step):
            for i in range(start_step, steps):
                profiler.on_step(i)
                capture.on_step(i)
                if inject is not None:
                    inject(i)
                with tracer.span("train.step", sample=tracer.hot_sample, step=i):
                    if warm_batch is not None:
                        batch, warm_batch = warm_batch, None
                    else:
                        batch = next(pipe)
                    params, opt_state, metrics = step_fn(
                        params, opt_state, batch, key
                    )
                if ctx.is_leader and (i % 10 == 0 or i == steps - 1):
                    # Push the device array; the drain thread does the host
                    # read — no sync in the dispatch path.
                    drain.push(i, {"loss": metrics["loss"]})
                if ckpt is not None:
                    ckpt.save(i, params, opt_state)
                if ckpt_now is not None:
                    ckpt_now.maybe_save(i, params, opt_state)
                step_dt = clock.tick()
                if step_dt is not None:
                    run_stats.timing("train.step_wall_s", step_dt)
                    if first_step_s is None:
                        # Cold-start honesty metric: AOT compile (or its
                        # cache load) + the first step's dispatch wall.
                        first_step_s = aot_s + step_dt
                dwait = pipe.pop_data_wait_s()
                run_stats.timing("train.data_wait_s", dwait)
                led.account("data_wait_s", dwait)
                data_wait_accounted += dwait
                led.step(step_dt, tokens=batch_size)
                led.maybe_flush()
                # Feed the stall watchdog (tracking/flightrec.py): a beat
                # per step keeps the adaptive deadline honest.
                progress.beat(step=i)
        # Fence BEFORE timing: with async dispatch, steps are still
        # executing when the loop exits — an unfenced clock read would
        # overstate throughput.
        jax.block_until_ready(params)
        dt = time.time() - t0
    finally:
        profiler.close()
        pipe.close()
        drain.close()
        if ckpt is not None:
            ckpt.wait_until_finished()
            ckpt.close()
    # Ledger finalization (every process — the gang roll-up sums hosts):
    # residual data waits not popped in-loop, checkpoint write blocks,
    # the drain backlog paid at close.  A crashed run skips this; the
    # worker's exit flush ships whatever was accounted by then.
    led.account("data_wait_s", max(0.0, pipe.data_wait_s - data_wait_accounted))
    if ckpt is not None:
        led.account("ckpt_block_s", ckpt.save_block_s)
    led.account("metric_drain_s", drain.close_wait_s)
    led.flush(final=True)
    steps_run = steps - start_step
    if steps_run <= 0 or batch is None:
        if ctx.is_leader:
            ctx.log_text(f"{label}: nothing to do (checkpoint already at end)")
        return
    # Every process must join the (global-array) accuracy computation —
    # leader-only dispatch would deadlock multi-host gangs.
    acc = float(acc_fn(params, batch))
    if ctx.is_leader:
        ips = steps_run * batch_size / dt
        clock.add("data_wait_s", pipe.data_wait_s)
        if ckpt is not None:
            clock.add("ckpt_block_s", ckpt.save_block_s)
            run_stats.timing("train.ckpt_block_s", ckpt.save_block_s)
        stats = clock.summary()  # per-step means
        stats.update(_percentile_metrics(run_stats, "train.step_wall_s", "step_wall_s"))
        ctx.log_metrics(
            step=steps,
            accuracy=acc,
            images_per_s=ips,
            aot_compile_s=aot_s,
            first_step_s=first_step_s or aot_s,
            **stats,
        )
        ctx.log_text(
            f"{label} done: {steps} steps, strategy={template.name}, "
            f"loss {float(metrics['loss']):.4f}, acc {acc:.3f}, {ips:.0f} img/s "
            f"(data wait {pipe.data_wait_s * 1e3 / steps_run:.1f} ms/step, "
            f"prefetch={'off' if prefetch <= 0 else prefetch})"
        )


def cnn_train(ctx: Context) -> None:
    """Train the CNN image classifier (the CIFAR-10 quick-start shape).

    Params: steps, batch (global), image_size, classes, lr, channels,
    dataset, save_every — data/checkpoint contracts in
    :func:`_train_image_classifier`.
    """
    import optax

    from polyaxon_tpu.models import cnn
    from polyaxon_tpu.tracking.ledger import conv_classifier_flops_per_image

    cfg = cnn.CNNConfig(
        image_size=int(ctx.get_param("image_size", 32)),
        n_classes=int(ctx.get_param("classes", 10)),
        channels=tuple(ctx.get_param("channels", (64, 128, 256))),
    )

    def normalized(fn):
        # uint8 rides the host→HBM wire (4x smaller than f32); normalize
        # on device where it fuses into the first conv.
        def wrapped(p, b, template, mesh):
            images = b["images"].astype(cfg.dtype) / 255.0 - 0.5
            return fn(p, {**b, "images": images}, cfg)

        return wrapped

    _train_image_classifier(
        ctx,
        label="cnn_train",
        loss_fn=normalized(cnn.loss_fn),
        accuracy_fn=normalized(cnn.accuracy),
        init_fn=lambda k: cnn.init_params(k, cfg),
        axes_tree=cnn.param_axes(cfg),
        optimizer=optax.adamw(float(ctx.get_param("lr", 1e-3))),
        flops_per_example=conv_classifier_flops_per_image(
            cfg.image_size,
            cfg.in_channels,
            cfg.channels,
            cfg.dense_dim,
            cfg.n_classes,
        ),
    )


def vit_train(ctx: Context) -> None:
    """Train the Vision Transformer image classifier.

    The ViT family exercises attention/MLP templates (tp/fsdp) the conv
    net cannot.  Params: steps, batch, image_size, patch_size, classes,
    lr, d_model, n_layers, n_heads, head_dim, d_ff, dataset, save_every —
    data/checkpoint contracts in :func:`_train_image_classifier`.
    """
    import jax.numpy as jnp
    import optax

    from polyaxon_tpu.models import vit
    from polyaxon_tpu.tracking.ledger import transformer_flops_per_token

    d_model = int(ctx.get_param("d_model", 192))
    n_heads = int(ctx.get_param("n_heads", 6))
    cfg = vit.ViTConfig(
        image_size=int(ctx.get_param("image_size", 32)),
        patch_size=int(ctx.get_param("patch_size", 4)),
        n_classes=int(ctx.get_param("classes", 10)),
        d_model=d_model,
        n_layers=int(ctx.get_param("n_layers", 6)),
        n_heads=n_heads,
        head_dim=int(ctx.get_param("head_dim", max(8, d_model // n_heads))),
        d_ff=int(ctx.get_param("d_ff", 4 * d_model)),
    )
    _train_image_classifier(
        ctx,
        label="vit_train",
        loss_fn=lambda p, b, template, mesh: vit.loss_fn(
            p, b, cfg, template=template, mesh=mesh
        ),
        accuracy_fn=lambda p, b, template, mesh: vit.accuracy(
            p, b, cfg, template=template, mesh=mesh
        ),
        init_fn=lambda k: vit.init_params(k, cfg),
        axes_tree=vit.param_axes(cfg),
        optimizer=optax.adamw(
            float(ctx.get_param("lr", 1e-3)), mu_dtype=jnp.bfloat16
        ),
        # A ViT image is a num_patches-token transformer sequence.
        flops_per_example=transformer_flops_per_token(
            cfg.n_params,
            cfg.n_layers,
            cfg.n_heads,
            cfg.head_dim,
            cfg.num_patches,
        )
        * cfg.num_patches,
    )


def lm_generate(ctx: Context) -> None:
    """Autoregressive generation from the flagship LM (the serving story).

    Params: ``target`` (run uuid whose checkpoint to load — typically an
    ``lm_train`` run with ``save_every``; omitted = fresh random weights,
    useful as a pure decode benchmark), ``prompt_len``, ``max_new_tokens``,
    ``batch``, ``temperature``, plus the model-shape params of ``lm_train``
    (must match the checkpointed config when ``target`` is set).  Reports
    ``decode_tokens_per_s`` and logs a sample of the generated ids.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from polyaxon_tpu.models import TransformerConfig, decode, init_params

    cfg_fields = {
        f: int(ctx.get_param(f))
        for f in (
            "vocab_size", "d_model", "n_layers", "n_heads",
            "head_dim", "d_ff", "n_kv_heads", "n_experts",
        )
        if ctx.get_param(f) is not None
    }
    seq = int(ctx.get_param("seq", 256))
    cfg = TransformerConfig(max_seq=seq, **cfg_fields)
    batch = int(ctx.get_param("batch", 1))
    prompt_len = int(ctx.get_param("prompt_len", 16))
    max_new = int(ctx.get_param("max_new_tokens", 64))
    temperature = float(ctx.get_param("temperature", 0.0))

    key = jax.random.PRNGKey(ctx.seed or 0)
    params = init_params(key, cfg)

    target = ctx.get_param("target")
    if target is not None:
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        runs_root = ctx.runs_root
        ckpt_dir = runs_root / str(target) / "checkpoints"
        ckpt = CheckpointManager(ckpt_dir)
        try:
            # Weights-only restore: no optimizer template, no optimizer IO.
            restored = ckpt.restore_params(params)
        except ValueError:
            # Pre-round-4 checkpoint layout: needs a full-state template.
            import optax

            restored = ckpt.restore(params, optax.adamw(1e-3).init(params))
        ckpt.close()
        if restored is None:
            raise RuntimeError(f"No checkpoint under {ckpt_dir}")
        params = restored["params"]
        ctx.log_text(f"restored weights from run {target} step {restored['step']}")

    # int8 weight-only decode (see decode.quantize_weights): +51% measured
    # on the bandwidth-bound per-token loop.
    qweights = None
    if str(ctx.get_param("quantize", "") or "") == "int8":
        qweights = decode.quantize_weights(params)
        ctx.log_text("lm_generate: int8 weight-only decode enabled")

    rng = np.random.default_rng(ctx.seed or 0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, prompt_len)))
    gen = jax.jit(
        lambda p, prompt, key, qw: decode.generate(
            p, prompt, cfg, max_new_tokens=max_new,
            temperature=temperature, rng=key, qweights=qw,
        )
    )
    pre = jax.jit(
        lambda p, prompt: decode.prefill(
            p, prompt, decode.init_cache(cfg, batch, prompt_len + max_new), cfg
        )[0]
    )
    # Host reads are the timing barriers (block_until_ready can return
    # early on axon tunnels). Prefill is timed separately so the decode
    # rate isn't diluted by the O(T^2) prompt pass.
    out = gen(params, prompt, key, qweights)
    np.asarray(out[0, 0])
    np.asarray(pre(params, prompt)[0, 0])
    p0 = time.time()
    np.asarray(pre(params, prompt)[0, 0])
    prefill_s = time.time() - p0
    t0 = time.time()
    out = gen(params, prompt, key, qweights)
    first = np.asarray(out[0, :16])
    total_s = time.time() - t0
    tps = batch * max_new / max(total_s - prefill_s, 1e-9)
    if ctx.is_leader:
        ctx.log_metrics(
            decode_tokens_per_s=tps,
            prefill_s=prefill_s,
            generated=batch * max_new,
        )
        ctx.log_text(
            f"lm_generate done: {batch}x{max_new} tokens at {tps:.0f} tok/s "
            f"decode (prefill {prefill_s*1e3:.0f} ms); sample: {first.tolist()}"
        )


def metric_probe(ctx: Context) -> None:
    """Report a deterministic metric of the hyperparams (hpsearch probe).

    score = -(lr - 0.7)^2  (max at lr=0.7); loss = (lr - 0.3)^2 (min at 0.3).
    Sweeps over this trainer exercise the full search loop in milliseconds.
    """
    lr = float(ctx.get_param("lr", 0.0))
    ctx.log_metrics(
        step=int(ctx.get_param("epochs", 1)),
        score=-((lr - 0.7) ** 2),
        loss=(lr - 0.3) ** 2,
    )


def lm_train(ctx: Context) -> None:
    """Train the flagship transformer LM under the spec's strategy.

    The quick-start "CIFAR-10 distributed" equivalent for this framework
    (BASELINE.md north-star): one entrypoint that honors whatever mesh +
    parallelism template the topology declares.  Data is a synthetic
    next-token stream (deterministic from the seed) so the benchmark
    isolates compute + collectives from IO.

    Params: steps, batch, seq, lr, and any TransformerConfig field
    (d_model, n_layers, n_heads, head_dim, d_ff, vocab_size, n_experts).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from polyaxon_tpu.models import (
        TransformerConfig,
        init_params,
        loss_fn,
        param_axes,
    )
    from polyaxon_tpu.parallel import template_for
    from polyaxon_tpu.runtime.train import build_train_step
    from polyaxon_tpu.tracking.ledger import (
        get_ledger,
        transformer_flops_per_token,
    )

    led = get_ledger().start(source="train")
    steps = int(ctx.get_param("steps", 10))
    batch_size = int(ctx.get_param("batch", 8))
    seq = int(ctx.get_param("seq", 128))
    lr = float(ctx.get_param("lr", 3e-4))
    cfg_fields = {
        f: int(ctx.get_param(f))
        for f in (
            "vocab_size", "d_model", "n_layers", "n_heads",
            "head_dim", "d_ff", "n_experts", "n_kv_heads", "ce_chunk",
        )
        if ctx.get_param(f) is not None
    }
    if ctx.get_param("attention_impl") is not None:
        cfg_fields["attention_impl"] = str(ctx.get_param("attention_impl"))
    cfg = TransformerConfig(max_seq=seq, **cfg_fields)

    mesh = ctx.mesh
    if mesh is None:
        from polyaxon_tpu.runtime.mesh import build_mesh

        mesh = build_mesh({"data": jax.device_count()})
    template = template_for(ctx.strategy, dict(mesh.shape), ctx.strategy_options)

    ts = build_train_step(
        loss_fn=lambda p, b: loss_fn(p, b, cfg, template=template, mesh=mesh),
        init_fn=lambda k: init_params(k, cfg),
        axes_tree=param_axes(cfg),
        optimizer=optax.adamw(lr),
        mesh=mesh,
        template=template,
    )
    key = jax.random.PRNGKey(ctx.seed or 0)
    params, opt_state = ts.init(key)

    # Checkpoint/resume: restore whatever the checkpoints/ dir holds (a
    # resumed clone inherits the original's checkpoints), save every
    # `save_every` steps.
    save_every = int(ctx.get_param("save_every", 0))
    start_step = 0
    ckpt = None
    if save_every > 0 and ctx.checkpoints_path is not None:
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        ckpt = CheckpointManager(ctx.checkpoints_path, save_interval_steps=save_every)
        restored = ckpt.restore(params, opt_state)
        if restored is not None:
            params, opt_state = restored["params"], restored["opt_state"]
            start_step = restored["step"] + 1
            ctx.log_text(f"restored checkpoint at step {restored['step']}")

    rng = np.random.default_rng(ctx.seed or 0)
    tokens = rng.integers(0, cfg.vocab_size, (batch_size, seq + 1))
    batch = ts.place_batch(
        {
            "tokens": jnp.asarray(tokens[:, :-1]),
            "targets": jnp.asarray(tokens[:, 1:]),
        }
    )

    from polyaxon_tpu.runtime.pipeline import MetricsDrain
    from polyaxon_tpu.tracking.profiling import StepClock, StepProfiler

    profiler = StepProfiler(
        ctx.outputs_path or ".",
        start_step=int(ctx.get_param("profile_start", -1)),
        num_steps=int(ctx.get_param("profile_steps", 0)),
    )
    # On-demand capture (control-plane `profile` commands): same per-step
    # hook as the launch-time profiler, armed only when a command arrives.
    from polyaxon_tpu.tracking.capture import get_capture_agent

    capture = get_capture_agent()
    # Remediation's checkpoint-now lands on the bus thread but must save
    # from the loop thread (donated buffers) — the service bridges them.
    ckpt_now = None
    if ckpt is not None:
        from polyaxon_tpu.runtime.checkpoint import CheckpointNowService

        ckpt_now = CheckpointNowService(ckpt, capture)
    inject = _fault_injection(ctx)
    # Metrics leave the loop as device arrays; a drain thread does the
    # host reads — even logging steps no longer serialize dispatch.
    drain = MetricsDrain(lambda step, vals: ctx.log_metrics(step=step, **vals))
    clock = StepClock()

    tracer = get_tracer()
    run_stats = get_stats()
    progress = get_progress()
    metrics = None
    # FLOPs denominator for live MFU: XLA cost analysis where cheap (one
    # extra compile — see _should_measure_flops), else the analytic
    # 6N + attention accounting bench.py uses.
    analytic = transformer_flops_per_token(
        cfg.n_params, cfg.n_layers, cfg.n_heads, cfg.head_dim, seq
    ) * (batch_size * seq)
    from polyaxon_tpu.runtime.compilecache import aot_compile
    from polyaxon_tpu.tracking.ledger import executable_flops

    # AOT-compile the step BEFORE the loop (and before the FLOPs probe,
    # which rides the compiled executable for free): the compile lands
    # in the ledger's pre-loop bucket (mark_loop_start below), and with
    # the persistent cache armed a warm restart loads the executable
    # from disk instead of compiling — aot_s IS the cold-start cost.
    # step_fn is the compiled executable — calling the jitted ts.step
    # afterwards would compile a second time.
    with tracer.span("train.aot_compile"):
        step_fn, aot_s = aot_compile(ts.step, params, opt_state, batch, key)
    if step_fn is not ts.step:
        capture.register_executable("train_step", step_fn)
    measured = (
        (
            executable_flops(step_fn)
            or ts.step_flops(params, opt_state, batch, key)
        )
        if _should_measure_flops(ctx, jax.default_backend())
        else None
    )
    led.set_flops_per_step(measured or analytic)
    first_step_s = None
    t0 = time.time()
    clock.start()
    led.mark_loop_start()
    try:
        with tracer.span("train.loop", steps=steps - start_step):
            for i in range(start_step, steps):
                profiler.on_step(i)
                capture.on_step(i)
                if inject is not None:
                    inject(i)
                with tracer.span("train.step", sample=tracer.hot_sample, step=i):
                    params, opt_state, metrics = step_fn(
                        params, opt_state, batch, key
                    )
                if ctx.is_leader and (i % 10 == 0 or i == steps - 1):
                    drain.push(
                        i,
                        {"loss": metrics["loss"], "grad_norm": metrics["grad_norm"]},
                    )
                if ckpt is not None:
                    ckpt.save(i, params, opt_state)  # async; fenced at close
                if ckpt_now is not None:
                    ckpt_now.maybe_save(i, params, opt_state)
                step_dt = clock.tick()
                if step_dt is not None:
                    run_stats.timing("train.step_wall_s", step_dt)
                    if first_step_s is None:
                        # Cold-start honesty metric: AOT compile (or its
                        # cache load) + the first step's dispatch wall.
                        first_step_s = aot_s + step_dt
                led.step(step_dt, tokens=batch_size * seq)
                led.maybe_flush()
                progress.beat(step=i)
        jax.block_until_ready(params)
        dt = time.time() - t0
    finally:
        profiler.close()
        drain.close()
        if ckpt is not None:
            ckpt.wait_until_finished()
            ckpt.close()
    # Ledger finalization (every process — the gang roll-up sums hosts).
    if ckpt is not None:
        led.account("ckpt_block_s", ckpt.save_block_s)
    led.account("metric_drain_s", drain.close_wait_s)
    led.flush(final=True)
    steps_run = steps - start_step
    if steps_run <= 0:
        if ctx.is_leader:
            ctx.log_text("lm_train: nothing to do (checkpoint already at end)")
        return
    loss = float(metrics["loss"]) if metrics is not None else None
    if ctx.is_leader:
        tps = steps_run * batch_size * seq / dt
        if ckpt is not None:
            clock.add("ckpt_block_s", ckpt.save_block_s)
            run_stats.timing("train.ckpt_block_s", ckpt.save_block_s)
        stats = clock.summary()
        stats.update(_percentile_metrics(run_stats, "train.step_wall_s", "step_wall_s"))
        ctx.log_metrics(
            step=steps,
            tokens_per_s=tps,
            aot_compile_s=aot_s,
            first_step_s=first_step_s or aot_s,
            **stats,
        )
        ctx.log_text(
            f"lm_train done: {steps} steps, strategy={template.name}, "
            f"final loss {loss:.4f}, {tps:.0f} tokens/s "
            f"(aot compile {aot_s:.2f}s)"
        )


def synthetic_regression(ctx: Context) -> None:
    """A real (tiny) distributed training loop: pjit linear regression.

    Exercises the full TPU-native path — mesh, NamedSharding, jit train
    step, metric reporting — at a size that runs in milliseconds on the
    virtual CPU mesh.  Params: lr, steps, batch, dim.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    lr = float(ctx.get_param("lr", 0.1))
    steps = int(ctx.get_param("steps", 20))
    batch = int(ctx.get_param("batch", 64))
    dim = int(ctx.get_param("dim", 8))
    seed = ctx.seed if ctx.seed is not None else 0

    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim, 1)).astype(np.float32)
    x = rng.normal(size=(batch, dim)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(batch, 1)).astype(np.float32)

    params = {"w": jnp.zeros((dim, 1), jnp.float32)}
    opt = optax.sgd(lr)
    opt_state = opt.init(params)

    mesh = ctx.mesh
    if mesh is not None:
        data_axes = tuple(n for n in mesh.axis_names if n in ("data", "fsdp", "replica"))
        batch_sharding = NamedSharding(mesh, P(data_axes if data_axes else None))
        x = jax.device_put(x, batch_sharding)
        y = jax.device_put(y, batch_sharding)

    # params/opt_state are rebound from the result every step — donate
    # them so XLA updates in place instead of copying both pytrees per
    # call (x/y are reused across steps and must NOT be donated).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y):
        def loss_fn(p):
            pred = x @ p["w"]
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    loss = None
    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        if ctx.is_leader and (i % 5 == 0 or i == steps - 1):
            ctx.log_metrics(step=i, loss=float(loss))
    ctx.log_text(f"final loss {float(loss):.6f}")
