"""Built-in service entrypoints: tensorboard + outputs file server.

Parity: reference plugin deployments — ``polypod/tensorboard.py:32`` (a
tensorboard pod over an experiment's outputs) and ``polypod/notebook.py:35``.
TPU-native framing: services are ordinary gangs whose entrypoint serves
until the platform stops them; the serving port is allocated at dispatch
and arrives as ``ctx.get_param("service_port")`` (also exported as
``POLYAXON_TPU_SERVICE_PORT``), and the run's ``service_url`` is recorded
in the registry.

Target resolution: services usually visualize ANOTHER run's outputs — the
``target`` param is that run's uuid; the shared store layout makes its
``outputs/`` reachable from this gang's host.
"""

from __future__ import annotations

import subprocess
import sys
import time
from pathlib import Path

from polyaxon_tpu.tracking import Context


def _target_outputs(ctx: Context) -> Path:
    """The outputs dir to serve: the `target` run's, or our own."""
    target = ctx.get_param("target")
    if ctx.get_param("logdir"):
        return Path(str(ctx.get_param("logdir")))
    own_outputs = ctx.outputs_path
    if target is None:
        return own_outputs
    # The worker hands us the layout's runs/ root; a target run's outputs
    # live beside ours on the shared layout.
    runs_root = ctx.runs_root or own_outputs.parent.parent
    return runs_root / str(target) / "outputs"


def _service_port(ctx: Context) -> int:
    port = ctx.get_param("service_port") or ctx.get_param("port")
    if not port:
        raise RuntimeError(
            "No service port allocated — submit this entrypoint under a "
            "service kind (notebook/tensorboard) so dispatch assigns one"
        )
    return int(port)


def tensorboard(ctx: Context) -> None:
    """Serve tensorboard over a run's outputs until stopped.

    Params: ``target`` (run uuid whose outputs to visualize; default: this
    run's own outputs), ``logdir`` (explicit path override), ``host``
    (bind address, default 0.0.0.0 so the URL is reachable off-host).
    """
    import os

    logdir = _target_outputs(ctx)
    port = _service_port(ctx)
    host = str(ctx.get_param("host", "0.0.0.0"))
    ctx.log_text(f"tensorboard serving {logdir} on {host}:{port}")
    # A subprocess (not the program API) so the gang's TERM→KILL escalation
    # tears it down exactly like any workload; --load_fast=false keeps the
    # data-loading path version-robust.  When the environment has no real
    # pkg_resources (setuptools >= 82 removed it; tensorboard 2.20 still
    # imports it), the _compat dir supplies a scoped shim — prepended only
    # for THIS subprocess, and never when the real module exists.
    env = dict(os.environ)
    import importlib.util

    if importlib.util.find_spec("pkg_resources") is None:
        compat_dir = str(Path(__file__).resolve().parents[1] / "_compat")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (compat_dir, env.get("PYTHONPATH")) if p
        )
    rc = subprocess.call(
        [
            sys.executable,
            "-m",
            "tensorboard.main",
            "--logdir",
            str(logdir),
            "--host",
            host,
            "--port",
            str(port),
            "--load_fast",
            "false",
        ],
        env=env,
    )
    if rc != 0:
        raise RuntimeError(f"tensorboard exited {rc}")


def jupyter(ctx: Context) -> None:
    """Serve JupyterLab until stopped — the default ``kind: notebook``
    entrypoint (reference ran real Jupyter: ``polypod/notebook.py:35``).

    Params: ``notebook_dir`` (default: this run's outputs — writable, so
    notebooks persist as artifacts), ``target`` (work in another run's
    outputs instead), ``host`` (bind address, default 0.0.0.0), ``token``
    (access token; default: a fresh random one), ``jupyter_bin`` (explicit
    server executable — tests point this at a stub so the service plumbing
    is verifiable without jupyter installed).

    The token is generated worker-side and published by appending
    ``?token=...`` to the dispatch-recorded service URL through the report
    channel — the control plane never has to know it in advance.
    """
    import os
    import secrets

    port = _service_port(ctx)
    host = str(ctx.get_param("host", "0.0.0.0"))
    if ctx.get_param("notebook_dir"):
        root = Path(str(ctx.get_param("notebook_dir")))
    else:
        root = _target_outputs(ctx)
    root.mkdir(parents=True, exist_ok=True)
    token = str(ctx.get_param("token") or secrets.token_hex(16))

    jupyter_bin = ctx.get_param("jupyter_bin")
    if jupyter_bin:
        argv = [str(jupyter_bin)]
    else:
        import importlib.util

        if importlib.util.find_spec("jupyterlab") is not None:
            argv = [sys.executable, "-m", "jupyterlab"]
        elif importlib.util.find_spec("jupyter_server") is not None:
            # Same --ServerApp flags; serves the classic file/API surface
            # when only the server core is installed.
            argv = [sys.executable, "-m", "jupyter_server"]
        else:
            raise RuntimeError(
                "jupyter is not installed on this worker — install jupyterlab "
                "or pass a jupyter_bin param"
            )
    argv += [
        f"--ServerApp.ip={host}",
        f"--ServerApp.port={port}",
        f"--ServerApp.token={token}",
        f"--ServerApp.root_dir={root}",
        "--ServerApp.port_retries=0",
        "--ServerApp.allow_remote_access=True",
        "--no-browser",
    ]
    if ctx.is_leader:
        ctx.report_service(query=f"token={token}")
    ctx.log_text(f"jupyter serving {root} on {host}:{port}")
    rc = subprocess.call(argv, env=dict(os.environ))
    if rc != 0:
        raise RuntimeError(f"jupyter exited {rc}")


def _make_lm_handler(engine, cfg, meta: dict, log=lambda line: None):
    """HTTP handler class over a :class:`ServingEngine` (factored out of
    ``lm_server`` so tests can drive the exact production handler against
    a bare engine, no platform Context required)."""
    import json as json_mod
    from http.server import BaseHTTPRequestHandler

    from polyaxon_tpu.serving.engine import EngineDrainingError
    from polyaxon_tpu.tracking.trace import (
        TraceContext,
        extract,
        get_tracer,
        new_trace_id,
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route into run logs, not stderr
            log("lm_server: " + fmt % args)

        def _json(self, code, payload, headers=None):
            body = json_mod.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code, kind, message, headers=None):
            # Machine-readable errors: routers and loadgen dispatch on
            # error.kind (429 "shed" is load signal, 503 "draining" is
            # lifecycle, connection drop is a fault) — string matching
            # on messages is not an API.
            return self._json(
                code, {"error": {"kind": kind, "message": message}}, headers
            )

        def do_GET(self):
            if self.path == "/v1/stats":
                payload = engine.stats()
                latency = engine.latency_summaries()
                if latency:
                    payload["latency"] = latency
                return self._json(200, payload)
            if self.path.startswith("/v1/trace/"):
                # Raw spans for one trace from this process's ring
                # buffer — the router merges these fleet-wide.  An empty
                # list is a valid answer (expired or never sampled).
                trace_id = self.path[len("/v1/trace/"):]
                spans = [
                    s
                    for s in get_tracer().spans()
                    if s.get("trace_id") == trace_id
                ]
                return self._json(200, {"trace_id": trace_id, "spans": spans})
            if self.path == "/metrics":
                from polyaxon_tpu.stats.metrics import (
                    PROMETHEUS_CONTENT_TYPE,
                    render_prometheus,
                    render_standard_gauges,
                )

                snapshot_fn = getattr(engine.stats_registry, "snapshot", None)
                if snapshot_fn is None:
                    text = "# engine stats backend keeps no in-process registry\n"
                else:
                    try:
                        snap = snapshot_fn(include_timings=False)
                    except TypeError:  # duck-typed stand-in without the kwarg
                        snap = snapshot_fn()
                    text = render_prometheus(
                        snap, labels={"component": "lm_server"}
                    )
                text += render_standard_gauges(labels={"component": "lm_server"})
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                return self.wfile.write(body)
            if self.path not in ("/healthz", "/"):
                return self._error(404, "not_found", "not found")
            stats = engine.stats()
            self._json(
                200,
                {
                    "ok": True,
                    "model": {
                        "n_params": cfg.n_params,
                        "vocab_size": cfg.vocab_size,
                        "max_seq": cfg.max_seq,
                        "n_kv_heads": cfg.kv_heads,
                    },
                    # "warming" until the start()-time warmup has
                    # pre-compiled the whole bucket family; LBs should
                    # gate traffic on state == "ready".
                    "state": stats["state"],
                    "engine": {
                        "slots": stats["slots"],
                        "slots_active": stats["slots_active"],
                        "queue_depth": stats["queue_depth"],
                        "warmup": stats["warmup"],
                    },
                    **meta,
                },
            )

        def do_POST(self):
            if self.path == "/v1/cancel":
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json_mod.loads(self.rfile.read(n) or b"{}")
                    rid = int(req["request_id"])
                except (KeyError, ValueError, TypeError) as e:
                    return self._error(400, "bad_request", str(e))
                return self._json(200, {"cancelled": engine.cancel(rid)})
            if self.path != "/generate":
                return self._error(404, "not_found", "not found")
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json_mod.loads(self.rfile.read(n) or b"{}")
                prompts = req["prompts"]
                max_new = int(
                    req.get("max_new_tokens", meta.get("default_max_new", 64))
                )
                temperature = float(req.get("temperature", 0.0))
                if not prompts or not isinstance(prompts[0], list):
                    raise ValueError("prompts must be a list of id lists")
            except (KeyError, ValueError, TypeError) as e:
                return self._error(400, "bad_request", str(e))
            # Join the caller's trace (router hop) or mint a fresh one
            # for direct clients; a malformed traceparent extracts to
            # None and degrades to a fresh trace, never an error.
            tctx = extract(self.headers)
            if tctx is None and getattr(engine, "trace_requests", False):
                tctx = TraceContext(new_trace_id())
            if tctx is not None and not tctx.sampled:
                tctx = None
            if tctx is None:
                return self._generate(prompts, max_new, temperature, None)
            with get_tracer().span(
                "serving.generate",
                sample=1.0,
                trace_id=tctx.trace_id,
                parent_id=tctx.span_id or None,
                prompts=len(prompts),
            ) as sp:
                return self._generate(
                    prompts, max_new, temperature, tctx.child(sp.span_id)
                )

        def _generate(self, prompts, max_new, temperature, tctx):
            try:
                # Mixed lengths are fine now — each prompt is its own
                # request; the engine batches them at the decode-step
                # level.  Validation happens in submit() per prompt.
                t0 = time.time()
                # The trace kwarg rides only when a context exists, so
                # duck-typed engine stand-ins keep working untraced.
                reqs = [
                    engine.submit(p, max_new, temperature, trace=tctx)
                    if tctx is not None
                    else engine.submit(p, max_new, temperature)
                    for p in prompts
                ]
            except EngineDrainingError as e:
                retry_after = str(int(meta.get("retry_after_s", 1)))
                return self._error(
                    503, "draining", str(e), {"Retry-After": retry_after}
                )
            except (KeyError, ValueError, TypeError) as e:
                return self._error(400, "bad_request", str(e))
            try:
                timeout_s = float(meta.get("request_timeout_s", 600))
                tokens = [r.wait(timeout=timeout_s) for r in reqs]
            except (RuntimeError, TimeoutError) as e:
                # The client is about to get an error and walk away:
                # release every still-running sibling's slot, KV blocks,
                # and prefix refs instead of decoding to max_new_tokens
                # for nobody.
                for r in reqs:
                    if not r.done.is_set():
                        engine.cancel(r.id)
                kinds = {r.error_kind for r in reqs if r.error_kind}
                if "shed" in kinds:
                    # Deadlock-shed: the pool cannot fit this working
                    # set RIGHT NOW.  429 + Retry-After tells the client
                    # to back off, not to count a fault.
                    retry_after = str(int(meta.get("retry_after_s", 1)))
                    return self._error(
                        429, "shed", str(e), {"Retry-After": retry_after}
                    )
                if isinstance(e, TimeoutError):
                    return self._error(503, "timeout", str(e))
                kind = next(iter(kinds)) if kinds else "engine_error"
                return self._error(503, kind, str(e))
            dt = time.time() - t0
            total = sum(len(t) for t in tokens)
            ttfts = [
                round(r.first_token_at - t0, 6)
                if r.first_token_at is not None
                else None
                for r in reqs
            ]
            payload = {
                "tokens": tokens,
                "decode_tokens_per_s": round(total / max(dt, 1e-9), 1),
                "ttft_s": ttfts,
            }
            if tctx is not None:
                # Per-request latency waterfalls ride the response so
                # clients (loadgen) see where the time went without a
                # second round-trip.
                payload["trace"] = {
                    "trace_id": tctx.trace_id,
                    "waterfalls": [
                        r.trace_summary
                        for r in reqs
                        if r.trace_summary is not None
                    ],
                }
            self._json(200, payload)

    return Handler


def lm_server(ctx: Context) -> None:
    """LM inference endpoint: the default ``kind: service`` entrypoint.

    A CONTINUOUS-BATCHING server (polyaxon_tpu/serving/engine.py) over a
    PAGED KV cache: one ref-counted block pool, per-request block tables,
    shared-prefix reuse (system prompts map to the same blocks,
    copy-on-write at divergence), chunked prefill interleaved with
    decode, and one jitted decode step advancing every in-flight request
    a token per iteration.  Concurrent connections feed the engine queue
    through a threaded front-end and block only on their own completion —
    a long generation (or a long PROMPT) never head-of-line-blocks a
    short one.  Routes:

    - ``POST /generate`` ``{"prompts": [[ids…]…], "max_new_tokens": N,
      "temperature": t}`` → ``{"tokens": [[ids…]…], "decode_tokens_per_s"}``
      (prompts may have DIFFERENT lengths — each is its own engine
      request; the KV cache stores UNEXPANDED GQA heads).  A request that
      times out server-side is CANCELLED (its slot and blocks free
      immediately) before the 503 goes out.
    - ``POST /v1/cancel`` ``{"request_id": N}`` → ``{"cancelled": bool}``
      — release an in-flight or queued request's slot, KV blocks, and
      prefix-cache references immediately.
    - ``GET /healthz`` → model/checkpoint metadata + engine occupancy +
      readiness ``state`` (``"warming"`` until the start()-time warmup
      has pre-compiled the decode step and every prefill bucket,
      ``"ready"`` after).
    - ``GET /v1/stats`` → queue depth, slot occupancy, tokens/s, block
      pool occupancy, prefix-cache hit rate, prefill backlog, latency
      percentiles (queue wait / TTFT / per-token decode).
    - ``GET /metrics`` → Prometheus text exposition of the same
      histograms plus the paging gauges (see docs/observability.md).

    Params: ``target`` (run uuid whose ``checkpoints/`` to serve — omit
    for fresh random weights, a load-testing double), the model-shape
    params of ``lm_train`` (must match the checkpoint), ``seq`` (max
    prompt+generation length per request), ``slots`` (concurrent
    sequences the batch holds), ``block_size`` (tokens per KV block),
    ``kv_blocks`` (pool size override — size below slots×seq to
    overcommit on prefix sharing), ``kv_quantize`` (``int8`` stores the
    KV pool quantized with per-row scales — <0.3× the pool HBM, so a
    fixed byte budget holds >2× the blocks; composes with ``quantize``),
    ``prefill_chunk`` (prompt tokens
    inserted per scheduler iteration; 0/unset = whole-prompt),
    ``prefix_cache`` (share identical prompt prefixes, default on),
    ``request_timeout_s`` (server-side wait budget per /generate),
    ``max_new_tokens`` (server default when a request omits it),
    ``eos_id`` (retire a slot early on this token), ``host``,
    ``quantize`` (``int8`` weight-only decode), ``spec_decode`` /
    ``spec_k`` / ``spec_min_ngram`` (speculative decoding: self-drafted
    multi-token steps for greedy requests — see docs/serving.md),
    ``kv_offload`` / ``kv_offload_blocks`` (pinned-host KV tier: parked
    sequences spill blocks to host instead of holding the pool, cold
    prefixes demote instead of evicting), ``kv_persist`` /
    ``kv_persist_dir`` (persist hot prefix blocks to the shared store's
    ``kv_cache/`` dir so replacement/scale-up replicas boot
    prefix-warm; ``kv_persist: true`` defaults the dir from the store
    layout).  The decode step's shapes depend only on (slots, pool
    size) — steady-state serving never recompiles.
    """
    import jax

    from polyaxon_tpu import stats as stats_backends
    from polyaxon_tpu.models import TransformerConfig, decode, init_params
    from polyaxon_tpu.serving import ServingEngine

    cfg_fields = {
        f: int(ctx.get_param(f))
        for f in (
            "vocab_size", "d_model", "n_layers", "n_heads",
            "head_dim", "d_ff", "n_kv_heads", "n_experts",
        )
        if ctx.get_param(f) is not None
    }
    seq = int(ctx.get_param("seq", 512))
    cfg = TransformerConfig(max_seq=seq, **cfg_fields)
    params = init_params(jax.random.PRNGKey(ctx.seed or 0), cfg)

    # Multi-chip serving: shard the weights over the gang's mesh per the
    # topology's strategy (tp shards heads over the tensor axis; GSPMD
    # propagates through the decode scan so the KV cache lands
    # heads-sharded too). Single-device keeps plain jit.  SINGLE-PROCESS
    # only: a sharded decode is a collective program every process must
    # enter, but only the process that receives the HTTP request would —
    # a multi-host sharded /generate would wedge in the collective.
    # Multi-host service gangs therefore keep the pre-mesh behavior:
    # each host serves an independent local replica.
    mesh = ctx.mesh if ctx.num_processes == 1 else None
    if ctx.num_processes > 1:
        ctx.log_text(
            "lm_server: multi-host gang — serving an independent replica "
            "per host (sharded decode needs a single-process mesh)"
        )
    template = None
    param_shardings = None
    if mesh is not None and mesh.size > 1:
        from polyaxon_tpu.models.decode import decode_param_shardings
        from polyaxon_tpu.parallel import template_for

        template = template_for(
            ctx.strategy, dict(mesh.shape), ctx.strategy_options
        )
        param_shardings = decode_param_shardings(
            cfg, mesh, template, params=params
        )
        params = jax.device_put(params, param_shardings)

    step = None
    target = ctx.get_param("target")
    if target is not None:
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        ckpt_dir = (ctx.runs_root or ctx.outputs_path.parent.parent) / str(
            target
        ) / "checkpoints"
        ckpt = CheckpointManager(ckpt_dir)
        # The (possibly sharded) init params are the restore template —
        # orbax restores each leaf onto its sharding, so a checkpoint
        # written under a training mesh reshards onto the serving mesh.
        restored = ckpt.restore_params(params)
        ckpt.close()
        if restored is None:
            raise RuntimeError(f"No checkpoint under {ckpt_dir}")
        params, step = restored["params"], restored["step"]
        ctx.log_text(f"lm_server: restored run {target} step {step}")

    # int8 weight-only decode (param ``quantize: int8``): the per-token
    # loop streams int8 weights (+51% measured decode throughput on the
    # bench model).  Composes with a sharded mesh: the (q, scale) pairs
    # shard like the weights they replaced, so each chip streams only
    # its shard's int8 bytes.
    qweights = None
    qweights_shardings = None
    if str(ctx.get_param("quantize", "") or "") == "int8":
        qweights = decode.quantize_weights(params)
        if template is not None:
            qweights_shardings = decode.quantized_weight_shardings(
                cfg, mesh, template, qweights
            )
            qweights = jax.device_put(qweights, qweights_shardings)
        ctx.log_text("lm_server: int8 weight-only decode enabled")

    port = _service_port(ctx)
    host = str(ctx.get_param("host", "0.0.0.0"))

    # Label this process's request spans so a fleet's merged trace puts
    # every replica on its own named track (the worker entrypoint set
    # sink/process_id already; the label rides on top).
    from polyaxon_tpu.tracking.trace import get_tracer

    get_tracer().configure(
        process=(
            f"lm_server-{ctx.run_uuid[:8]}" if ctx.run_uuid
            else f"lm_server-{port}"
        )
    )
    eos_id = ctx.get_param("eos_id")
    kv_blocks = ctx.get_param("kv_blocks")
    prefill_chunk = int(ctx.get_param("prefill_chunk", 0) or 0)
    kv_quantize = str(ctx.get_param("kv_quantize", "") or "") or None
    if kv_quantize:
        ctx.log_text(f"lm_server: kv_quantize={kv_quantize} KV pool enabled")
    spec_decode = ctx.get_param("spec_decode")
    spec_decode = (
        None
        if spec_decode is None
        else str(spec_decode).lower() not in ("0", "false", "no", "")
    )
    spec_k = ctx.get_param("spec_k")
    spec_min_ngram = ctx.get_param("spec_min_ngram")
    if spec_decode:
        ctx.log_text(
            f"lm_server: speculative decoding enabled "
            f"(spec_k={spec_k}, spec_min_ngram={spec_min_ngram})"
        )
    kv_offload = ctx.get_param("kv_offload")
    kv_offload = (
        None
        if kv_offload is None
        else str(kv_offload).lower() not in ("0", "false", "no", "")
    )
    kv_offload_blocks = ctx.get_param("kv_offload_blocks")
    kv_persist_dir = ctx.get_param("kv_persist_dir")
    if kv_persist_dir is None and str(
        ctx.get_param("kv_persist", "") or ""
    ).lower() in ("1", "true", "yes"):
        # Default the persist dir from the shared store layout: runs/
        # sits under the layout base, and kv_cache/ beside it (see
        # StoreLayout.kv_cache_dir) — every replica of a fleet lands on
        # the same store, which is what makes warm boot work.
        runs_root = ctx.runs_root or ctx.outputs_path.parent.parent
        kv_persist_dir = runs_root.parent / "kv_cache"
    # Weight identity for the persisted KV fingerprint: prefix blocks
    # are only reusable under the exact weights (and weight-quantize
    # mode) that produced them.
    kv_persist_sig = (
        f"ckpt:{target}:{step}" if target is not None
        else f"random:{ctx.seed or 0}"
    ) + (":wq-int8" if qweights is not None else "")
    if kv_offload:
        ctx.log_text("lm_server: host KV offload tier enabled")
    if kv_persist_dir:
        ctx.log_text(f"lm_server: prefix KV persistence at {kv_persist_dir}")
    engine = ServingEngine(
        params,
        cfg,
        slots=int(ctx.get_param("slots", 4)),
        max_len=seq,
        block_size=int(ctx.get_param("block_size", 16)),
        num_blocks=int(kv_blocks) if kv_blocks is not None else None,
        prefill_chunk=prefill_chunk if prefill_chunk > 0 else None,
        prefix_cache=str(ctx.get_param("prefix_cache", "1")).lower()
        not in ("0", "false", "no"),
        qweights=qweights,
        kv_quantize=kv_quantize,
        mesh=mesh if template is not None else None,
        eos_id=int(eos_id) if eos_id is not None else None,
        seed=ctx.seed or 0,
        spec_decode=spec_decode,
        spec_k=int(spec_k) if spec_k is not None else None,
        spec_min_ngram=(
            int(spec_min_ngram) if spec_min_ngram is not None else None
        ),
        kv_offload=kv_offload,
        kv_offload_blocks=(
            int(kv_offload_blocks) if kv_offload_blocks is not None else None
        ),
        kv_persist_dir=str(kv_persist_dir) if kv_persist_dir else None,
        kv_persist_sig=kv_persist_sig,
        # The process-wide registry: /metrics then also exports anything
        # else this worker records (pipeline waits, task timings).
        stats=stats_backends.get_stats(),
    ).start()

    from http.server import ThreadingHTTPServer

    # Control-plane drain: the fleet layer (or an operator) sends a
    # `drain` bus command before replacing this replica.  The handler
    # only flips the engine's admission flag (no I/O, no sleeps) —
    # new /generate calls get a typed 503 "draining" while in-flight
    # requests run to completion.
    from polyaxon_tpu.tracking.capture import get_capture_agent

    capture = get_capture_agent()

    def _on_drain(cmd):
        engine.drain()
        ctx.log_text("lm_server: drain command — no new admissions")
        capture.command_event(
            str(cmd.get("uuid") or ""), "complete", message="engine draining"
        )

    capture.register_handler("drain", _on_drain)

    meta = {
        "checkpoint_step": step,
        "target": target,
        "default_max_new": int(ctx.get_param("max_new_tokens", 64)),
        "request_timeout_s": float(ctx.get_param("request_timeout_s", 600)),
    }
    handler = _make_lm_handler(engine, cfg, meta, log=ctx.log_text)
    server = ThreadingHTTPServer((host, port), handler)
    ctx.log_text(
        f"lm_server: {cfg.n_params/1e6:.0f}M params, {engine.slots} slots "
        f"on {host}:{port}"
        + (f" (checkpoint step {step})" if step is not None else " (random init)")
    )
    try:
        server.serve_forever()
    finally:
        engine.stop()


def output_server(ctx: Context) -> None:
    """Serve a run's outputs dir over plain HTTP until stopped.

    The dependency-free notebook-kind analogue (and the test double for
    service plumbing): directory listing + file download for ``target``'s
    outputs.  Params: ``target``, ``logdir``, ``host`` (default 0.0.0.0 —
    the advertised service_url names the gang host, so the listener is
    network-visible; pass host: 127.0.0.1 for loopback-only).
    """
    import functools
    from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

    root = _target_outputs(ctx)
    port = _service_port(ctx)
    # 0.0.0.0 so the advertised service_url (which names the gang host, not
    # loopback) is reachable on remote pools too.
    host = str(ctx.get_param("host", "0.0.0.0"))
    handler = functools.partial(SimpleHTTPRequestHandler, directory=str(root))
    server = ThreadingHTTPServer((host, port), handler)
    ctx.log_text(f"output_server serving {root} on {host}:{port}")
    server.serve_forever()
