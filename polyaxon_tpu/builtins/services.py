"""Built-in service entrypoints: tensorboard + outputs file server.

Parity: reference plugin deployments — ``polypod/tensorboard.py:32`` (a
tensorboard pod over an experiment's outputs) and ``polypod/notebook.py:35``.
TPU-native framing: services are ordinary gangs whose entrypoint serves
until the platform stops them; the serving port is allocated at dispatch
and arrives as ``ctx.get_param("service_port")`` (also exported as
``POLYAXON_TPU_SERVICE_PORT``), and the run's ``service_url`` is recorded
in the registry.

Target resolution: services usually visualize ANOTHER run's outputs — the
``target`` param is that run's uuid; the shared store layout makes its
``outputs/`` reachable from this gang's host.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

from polyaxon_tpu.tracking import Context


def _target_outputs(ctx: Context) -> Path:
    """The outputs dir to serve: the `target` run's, or our own."""
    target = ctx.get_param("target")
    if ctx.get_param("logdir"):
        return Path(str(ctx.get_param("logdir")))
    own_outputs = ctx.outputs_path
    if target is None:
        return own_outputs
    # The worker hands us the layout's runs/ root; a target run's outputs
    # live beside ours on the shared layout.
    runs_root = ctx.runs_root or own_outputs.parent.parent
    return runs_root / str(target) / "outputs"


def _service_port(ctx: Context) -> int:
    port = ctx.get_param("service_port") or ctx.get_param("port")
    if not port:
        raise RuntimeError(
            "No service port allocated — submit this entrypoint under a "
            "service kind (notebook/tensorboard) so dispatch assigns one"
        )
    return int(port)


def tensorboard(ctx: Context) -> None:
    """Serve tensorboard over a run's outputs until stopped.

    Params: ``target`` (run uuid whose outputs to visualize; default: this
    run's own outputs), ``logdir`` (explicit path override), ``host``
    (bind address, default 0.0.0.0 so the URL is reachable off-host).
    """
    import os

    logdir = _target_outputs(ctx)
    port = _service_port(ctx)
    host = str(ctx.get_param("host", "0.0.0.0"))
    ctx.log_text(f"tensorboard serving {logdir} on {host}:{port}")
    # A subprocess (not the program API) so the gang's TERM→KILL escalation
    # tears it down exactly like any workload; --load_fast=false keeps the
    # data-loading path version-robust.  When the environment has no real
    # pkg_resources (setuptools >= 82 removed it; tensorboard 2.20 still
    # imports it), the _compat dir supplies a scoped shim — prepended only
    # for THIS subprocess, and never when the real module exists.
    env = dict(os.environ)
    import importlib.util

    if importlib.util.find_spec("pkg_resources") is None:
        compat_dir = str(Path(__file__).resolve().parents[1] / "_compat")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (compat_dir, env.get("PYTHONPATH")) if p
        )
    rc = subprocess.call(
        [
            sys.executable,
            "-m",
            "tensorboard.main",
            "--logdir",
            str(logdir),
            "--host",
            host,
            "--port",
            str(port),
            "--load_fast",
            "false",
        ],
        env=env,
    )
    if rc != 0:
        raise RuntimeError(f"tensorboard exited {rc}")


def jupyter(ctx: Context) -> None:
    """Serve JupyterLab until stopped — the default ``kind: notebook``
    entrypoint (reference ran real Jupyter: ``polypod/notebook.py:35``).

    Params: ``notebook_dir`` (default: this run's outputs — writable, so
    notebooks persist as artifacts), ``target`` (work in another run's
    outputs instead), ``host`` (bind address, default 0.0.0.0), ``token``
    (access token; default: a fresh random one), ``jupyter_bin`` (explicit
    server executable — tests point this at a stub so the service plumbing
    is verifiable without jupyter installed).

    The token is generated worker-side and published by appending
    ``?token=...`` to the dispatch-recorded service URL through the report
    channel — the control plane never has to know it in advance.
    """
    import os
    import secrets

    port = _service_port(ctx)
    host = str(ctx.get_param("host", "0.0.0.0"))
    if ctx.get_param("notebook_dir"):
        root = Path(str(ctx.get_param("notebook_dir")))
    else:
        root = _target_outputs(ctx)
    root.mkdir(parents=True, exist_ok=True)
    token = str(ctx.get_param("token") or secrets.token_hex(16))

    jupyter_bin = ctx.get_param("jupyter_bin")
    if jupyter_bin:
        argv = [str(jupyter_bin)]
    else:
        import importlib.util

        if importlib.util.find_spec("jupyterlab") is not None:
            argv = [sys.executable, "-m", "jupyterlab"]
        elif importlib.util.find_spec("jupyter_server") is not None:
            # Same --ServerApp flags; serves the classic file/API surface
            # when only the server core is installed.
            argv = [sys.executable, "-m", "jupyter_server"]
        else:
            raise RuntimeError(
                "jupyter is not installed on this worker — install jupyterlab "
                "or pass a jupyter_bin param"
            )
    argv += [
        f"--ServerApp.ip={host}",
        f"--ServerApp.port={port}",
        f"--ServerApp.token={token}",
        f"--ServerApp.root_dir={root}",
        "--ServerApp.port_retries=0",
        "--ServerApp.allow_remote_access=True",
        "--no-browser",
    ]
    if ctx.is_leader:
        ctx.report_service(query=f"token={token}")
    ctx.log_text(f"jupyter serving {root} on {host}:{port}")
    rc = subprocess.call(argv, env=dict(os.environ))
    if rc != 0:
        raise RuntimeError(f"jupyter exited {rc}")


def output_server(ctx: Context) -> None:
    """Serve a run's outputs dir over plain HTTP until stopped.

    The dependency-free notebook-kind analogue (and the test double for
    service plumbing): directory listing + file download for ``target``'s
    outputs.  Params: ``target``, ``logdir``, ``host`` (default 0.0.0.0 —
    the advertised service_url names the gang host, so the listener is
    network-visible; pass host: 127.0.0.1 for loopback-only).
    """
    import functools
    from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

    root = _target_outputs(ctx)
    port = _service_port(ctx)
    # 0.0.0.0 so the advertised service_url (which names the gang host, not
    # loopback) is reachable on remote pools too.
    host = str(ctx.get_param("host", "0.0.0.0"))
    handler = functools.partial(SimpleHTTPRequestHandler, directory=str(root))
    server = ThreadingHTTPServer((host, port), handler)
    ctx.log_text(f"output_server serving {root} on {host}:{port}")
    server.serve_forever()
