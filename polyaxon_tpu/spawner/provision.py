"""TPU-VM slice provisioning: the spawner layer that CREATES compute.

Parity: the reference's spawner materializes its own infrastructure (pods
through the k8s API — ``polypod/experiment.py:160-244`` create,
``:350-357`` start/stop); until now this platform required worker hosts to
pre-exist in conf.  TPU-native equivalent: slices are TPU VMs, and the
management plane for those is ``gcloud compute tpus tpu-vm`` — so the seam
is a set of PURE argv builders (unit-testable exactly like
``transport.build_ssh_argv``) plus a :class:`TPUVMProvisioner` with an
injectable runner (same pattern as ``stores.artifacts.GsutilArtifactStore``:
no SDK dependency, and a fake runner makes the whole pool lifecycle
testable without GCP).

:class:`TPUPool` composes the provisioner with the device registry and the
conf system: ``provision()`` creates N slices, registers each as an
admission device, and appends the worker IPs to ``spawner.hosts`` (slice
order — worker 0 of the first slice becomes the jax.distributed
coordinator); ``teardown()`` reverses all three.
"""

from __future__ import annotations

import json
import re
import subprocess
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from polyaxon_tpu.exceptions import PolyaxonTPUError


class ProvisionError(PolyaxonTPUError):
    """gcloud failed; ``not_found`` discriminates absent-resource errors."""

    def __init__(self, message: str, *, not_found: bool = False) -> None:
        super().__init__(message)
        self.not_found = not_found


#: accelerator-type prefix -> chips per worker host. v2/v3 pack 4 chips
#: (8 TensorCores) per host and their type suffix counts CORES; v4/v5p
#: also count cores but host 4 chips; v5litepod (v5e) and v6e count CHIPS
#: with 4-chip hosts (single-host slices below that).
_CHIPS_PER_HOST = {
    "v2": 4,
    "v3": 4,
    "v4": 4,
    "v5p": 4,
    "v5litepod": 4,
    "v6e": 4,
}
#: prefixes whose size suffix counts TensorCores (2 per chip), not chips
_CORE_COUNTED = ("v2", "v3", "v4", "v5p")

_ACCEL_RE = re.compile(r"^(?P<gen>[a-z0-9]+)-(?P<size>\d+)$")


def parse_accelerator_type(accelerator_type: str) -> Dict[str, int]:
    """``v5litepod-16`` -> {"chips": 16, "num_hosts": 4}.

    Hosts are ceil(chips / chips-per-host); single-host below one full
    host.  The authoritative host list always comes from the created VM's
    ``networkEndpoints`` — this is the *planning* estimate used for
    admission accounting before/without a describe call.
    """
    m = _ACCEL_RE.match(accelerator_type)
    if not m:
        raise ProvisionError(
            f"Unrecognized accelerator type {accelerator_type!r} "
            "(expected e.g. v5litepod-16, v4-8)"
        )
    gen, size = m.group("gen"), int(m.group("size"))
    if gen not in _CHIPS_PER_HOST:
        raise ProvisionError(
            f"Unknown TPU generation {gen!r} in {accelerator_type!r} "
            f"(known: {sorted(_CHIPS_PER_HOST)})"
        )
    chips = size // 2 if gen in _CORE_COUNTED else size
    chips = max(chips, 1)
    per_host = _CHIPS_PER_HOST[gen]
    return {"chips": chips, "num_hosts": max(1, -(-chips // per_host))}


# ---------------------------------------------------------------------------
# Pure argv builders (the unit-testable seam)
# ---------------------------------------------------------------------------


def _base(gcloud_bin: str, project: Optional[str]) -> List[str]:
    argv = [gcloud_bin, "compute", "tpus", "tpu-vm"]
    return argv + ([f"--project={project}"] if project else [])


def build_tpu_create_argv(
    name: str,
    *,
    zone: str,
    accelerator_type: str,
    version: str,
    gcloud_bin: str = "gcloud",
    project: Optional[str] = None,
    preemptible: bool = False,
    spot: bool = False,
    network: Optional[str] = None,
    extra_args: Sequence[str] = (),
) -> List[str]:
    argv = _base(gcloud_bin, project) + [
        "create",
        name,
        f"--zone={zone}",
        f"--accelerator-type={accelerator_type}",
        f"--version={version}",
        "--format=json",
    ]
    if preemptible:
        argv.append("--preemptible")
    if spot:
        argv.append("--spot")
    if network:
        argv.append(f"--network={network}")
    argv.extend(extra_args)
    return argv


def build_tpu_describe_argv(
    name: str, *, zone: str, gcloud_bin: str = "gcloud", project: Optional[str] = None
) -> List[str]:
    return _base(gcloud_bin, project) + [
        "describe", name, f"--zone={zone}", "--format=json",
    ]


def build_tpu_list_argv(
    *, zone: str, gcloud_bin: str = "gcloud", project: Optional[str] = None
) -> List[str]:
    return _base(gcloud_bin, project) + ["list", f"--zone={zone}", "--format=json"]


def build_tpu_delete_argv(
    name: str, *, zone: str, gcloud_bin: str = "gcloud", project: Optional[str] = None
) -> List[str]:
    return _base(gcloud_bin, project) + [
        "delete", name, f"--zone={zone}", "--quiet",
    ]


def build_tpu_ssh_argv(
    name: str,
    command: str,
    *,
    zone: str,
    worker: Union[int, str] = "all",
    gcloud_bin: str = "gcloud",
    project: Optional[str] = None,
) -> List[str]:
    """``gcloud ... ssh`` — the bootstrap channel (install deps, mount the
    shared base dir) before the platform's own SSHTransport takes over."""
    return _base(gcloud_bin, project) + [
        "ssh", name, f"--zone={zone}", f"--worker={worker}", f"--command={command}",
    ]


# ---------------------------------------------------------------------------
# Provisioner
# ---------------------------------------------------------------------------


@dataclass
class SliceInfo:
    """One TPU-VM slice as the management plane reports it."""

    name: str
    zone: str
    accelerator_type: str
    state: str
    hosts: List[str] = field(default_factory=list)
    chips: int = 0
    num_hosts: int = 0


Runner = Callable[[Sequence[str]], "subprocess.CompletedProcess"]


def _default_runner(argv: Sequence[str]) -> "subprocess.CompletedProcess":
    return subprocess.run(argv, capture_output=True, text=True, timeout=1800)


class TPUVMProvisioner:
    """Create/list/delete TPU-VM slices through the gcloud CLI.

    ``runner`` is injectable (tests use a fake writing canned JSON); errors
    discriminate not-found from auth/quota failures the same way
    ``GsutilArtifactStore`` does.
    """

    def __init__(
        self,
        *,
        zone: str,
        gcloud_bin: str = "gcloud",
        project: Optional[str] = None,
        runner: Runner = _default_runner,
    ) -> None:
        self.zone = zone
        self.gcloud_bin = gcloud_bin
        self.project = project
        self._run = runner

    # -- helpers --------------------------------------------------------------
    def _check(self, proc: "subprocess.CompletedProcess") -> str:
        if proc.returncode == 0:
            return proc.stdout or ""
        err = (proc.stderr or proc.stdout or "").strip()
        low = err.lower()
        raise ProvisionError(
            f"gcloud failed (rc={proc.returncode}): {err[-500:]}",
            not_found="not_found" in low or "not found" in low or "404" in low,
        )

    def _parse_slice(self, node: Dict[str, Any]) -> SliceInfo:
        name = (node.get("name") or "").rsplit("/", 1)[-1]
        accel = node.get("acceleratorType") or ""
        accel = accel.rsplit("/", 1)[-1]
        hosts = []
        for ep in node.get("networkEndpoints") or []:
            ip = ep.get("ipAddress") or (ep.get("accessConfig") or {}).get(
                "externalIp"
            )
            if ip:
                hosts.append(ip)
        try:
            plan = parse_accelerator_type(accel)
        except ProvisionError:
            plan = {"chips": 0, "num_hosts": len(hosts)}
        return SliceInfo(
            name=name,
            zone=self.zone,
            accelerator_type=accel,
            state=node.get("state") or "UNKNOWN",
            hosts=hosts,
            chips=plan["chips"],
            num_hosts=len(hosts) or plan["num_hosts"],
        )

    # -- operations -----------------------------------------------------------
    def create(
        self,
        name: str,
        *,
        accelerator_type: str,
        version: str,
        preemptible: bool = False,
        spot: bool = False,
        network: Optional[str] = None,
        extra_args: Sequence[str] = (),
    ) -> SliceInfo:
        self._check(
            self._run(
                build_tpu_create_argv(
                    name,
                    zone=self.zone,
                    accelerator_type=accelerator_type,
                    version=version,
                    gcloud_bin=self.gcloud_bin,
                    project=self.project,
                    preemptible=preemptible,
                    spot=spot,
                    network=network,
                    extra_args=extra_args,
                )
            )
        )
        return self.describe(name)

    def describe(self, name: str) -> SliceInfo:
        out = self._check(
            self._run(
                build_tpu_describe_argv(
                    name,
                    zone=self.zone,
                    gcloud_bin=self.gcloud_bin,
                    project=self.project,
                )
            )
        )
        return self._parse_slice(json.loads(out or "{}"))

    def list(self) -> List[SliceInfo]:
        out = self._check(
            self._run(
                build_tpu_list_argv(
                    zone=self.zone, gcloud_bin=self.gcloud_bin, project=self.project
                )
            )
        )
        return [self._parse_slice(n) for n in json.loads(out or "[]")]

    def delete(self, name: str, *, missing_ok: bool = False) -> bool:
        try:
            self._check(
                self._run(
                    build_tpu_delete_argv(
                        name,
                        zone=self.zone,
                        gcloud_bin=self.gcloud_bin,
                        project=self.project,
                    )
                )
            )
            return True
        except ProvisionError as e:
            if missing_ok and e.not_found:
                return False
            raise


# ---------------------------------------------------------------------------
# Pool lifecycle: provisioner × device registry × conf
# ---------------------------------------------------------------------------


class TPUPool:
    """Provision slices and wire them into admission + the ssh spawner.

    The registry rows gate gang admission (``acquire_device``); the
    ``spawner.hosts`` conf entry (slice order) is what
    ``spawner_from_conf`` hands the :class:`RemoteGangSpawner`.

    ``orchestrator`` (optional) routes device registration through
    ``Orchestrator.register_device`` so new capacity immediately re-kicks
    admission and lands in the audit trail; without it (bare tests) the
    raw registry is used.
    """

    def __init__(
        self, provisioner: TPUVMProvisioner, registry, conf, orchestrator=None
    ) -> None:
        self.provisioner = provisioner
        self.registry = registry
        self.conf = conf
        self.orchestrator = orchestrator

    def _register(self, info: SliceInfo) -> None:
        registrar = self.orchestrator or self.registry
        registrar.register_device(
            info.name,
            accelerator=info.accelerator_type,
            chips=info.chips,
            num_hosts=info.num_hosts,
        )

    def _hosts(self) -> List[str]:
        raw = self.conf.get("spawner.hosts") or ""
        return [h.strip() for h in raw.split(",") if h.strip()]

    def _set_hosts(self, hosts: List[str]) -> None:
        self.conf.set("spawner.hosts", ",".join(hosts))

    def provision(
        self,
        prefix: str,
        count: int,
        *,
        accelerator_type: str,
        version: str,
        preemptible: bool = False,
    ) -> List[SliceInfo]:
        """Create ``count`` slices named ``{prefix}-{i}``; register each.

        Already-created slices roll back on a mid-pool failure so a failed
        ``provision`` leaves no orphan VMs billing quietly.
        """
        created: List[SliceInfo] = []
        try:
            for i in range(count):
                created.append(
                    self.provisioner.create(
                        f"{prefix}-{i}",
                        accelerator_type=accelerator_type,
                        version=version,
                        preemptible=preemptible,
                    )
                )
        except ProvisionError:
            for info in created:
                try:
                    self.provisioner.delete(info.name, missing_ok=True)
                except ProvisionError:  # pragma: no cover - best effort
                    pass
            raise
        hosts = self._hosts()
        for info in created:
            self._register(info)
            hosts.extend(h for h in info.hosts if h not in hosts)
        self._set_hosts(hosts)
        # Only flip the backend when there genuinely are hosts to ssh to —
        # an ssh backend with an empty pool fails construction outright.
        if hosts and self.conf.get("spawner.backend") != "ssh":
            self.conf.set("spawner.backend", "ssh")
        return created

    def teardown(self, names: Sequence[str]) -> int:
        """Delete slices, drop their device rows, prune their hosts.

        Host/backend conf persists in a ``finally`` so a mid-loop gcloud
        failure can't leave already-deleted VMs' IPs in the ssh pool.
        """
        removed = 0
        hosts = self._hosts()
        try:
            for name in names:
                info = None
                try:
                    info = self.provisioner.describe(name)
                except ProvisionError as e:
                    if not e.not_found:
                        raise
                if self.provisioner.delete(name, missing_ok=True):
                    removed += 1
                if info is not None:
                    hosts = [h for h in hosts if h not in info.hosts]
                try:
                    self.registry.remove_device(name)
                except Exception:  # device may be unregistered already
                    pass
        finally:
            self._set_hosts(hosts)
            if not hosts and self.conf.get("spawner.backend") == "ssh":
                # An ssh backend with zero hosts can't even construct;
                # fall back to local so the control plane stays operable.
                self.conf.set("spawner.backend", "local")
        return removed

    def status(self) -> List[Dict[str, Any]]:
        """Join the management plane's view with the admission registry's."""
        devices = {d["name"]: d for d in self.registry.list_devices()}
        out = []
        for info in self.provisioner.list():
            dev = devices.pop(info.name, None)
            out.append(
                {
                    "name": info.name,
                    "state": info.state,
                    "accelerator": info.accelerator_type,
                    "chips": info.chips,
                    "num_hosts": info.num_hosts,
                    "hosts": info.hosts,
                    "registered": dev is not None,
                    "run_id": (dev or {}).get("run_id"),
                }
            )
        for name, dev in devices.items():
            out.append(
                {
                    "name": name,
                    "state": "UNPROVISIONED",
                    "accelerator": dev["accelerator"],
                    "chips": dev["chips"],
                    "num_hosts": dev["num_hosts"],
                    "hosts": [],
                    "registered": True,
                    "run_id": dev.get("run_id"),
                }
            )
        return out
