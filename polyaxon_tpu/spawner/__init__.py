from polyaxon_tpu.spawner.local import GangHandle, GangSpawner, LocalGangSpawner
from polyaxon_tpu.spawner.remote import RemoteGangSpawner, spawner_from_conf
from polyaxon_tpu.spawner.transport import (
    LocalExecTransport,
    ProcessRef,
    SSHTransport,
    Transport,
)

__all__ = [
    "GangHandle",
    "GangSpawner",
    "LocalGangSpawner",
    "RemoteGangSpawner",
    "spawner_from_conf",
    "Transport",
    "LocalExecTransport",
    "SSHTransport",
    "ProcessRef",
]
