from polyaxon_tpu.spawner.local import GangHandle, LocalGangSpawner

__all__ = ["GangHandle", "LocalGangSpawner"]
