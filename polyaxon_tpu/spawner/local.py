"""Local-subprocess gang spawner.

Parity: reference ``polypod/experiment.py`` — ``ExperimentSpawner`` builds
pods+services per replica, injects rendezvous env, and starts/stops the
experiment (``start_experiment`` :350-357, pod creation :160-244).
TPU-native: a *gang* is N host processes for one accelerator slice; the
spawner launches them as local subprocesses (the dev/test backend — a
TPU-VM ssh backend slots in behind the same interface), injecting the
coordinator/process-id/mesh env contract that replaces TF_CONFIG.  Each
process's stdout/stderr stream to per-process log files; the reporting
channel is the run's ``reports/`` dir.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from pathlib import Path
from dataclasses import dataclass, field
from typing import Dict, Optional

from polyaxon_tpu.compiler import GangPlan
from polyaxon_tpu.db.registry import Run
from polyaxon_tpu.exceptions import SpawnerError
from polyaxon_tpu.runtime.env import gang_env
from polyaxon_tpu.stores.layout import RunPaths, StoreLayout
from polyaxon_tpu.stores.snapshots import materialize_snapshot


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class GangHandle:
    """A live (or finished) gang: the spawner's unit of control."""

    run_id: int
    run_uuid: str
    plan: GangPlan
    paths: RunPaths
    processes: Dict[int, subprocess.Popen] = field(default_factory=dict)
    #: Byte offsets into each process's report file (watcher tail cursor).
    report_offsets: Dict[int, int] = field(default_factory=dict)
    started_at: float = field(default_factory=time.time)
    #: Consecutive monitor-poll failures (scheduler bookkeeping).
    monitor_failures: int = 0
    #: When the gang's roll-up first went terminal while members were still
    #: alive (scheduler grace-window bookkeeping).
    terminal_since: Optional[float] = None

    def poll(self) -> Dict[int, Optional[int]]:
        """process_id -> exit code (None while running)."""
        return {pid: proc.poll() for pid, proc in self.processes.items()}

    @property
    def all_exited(self) -> bool:
        return all(code is not None for code in self.poll().values())


class LocalGangSpawner:
    """Launches gangs as local subprocesses of ``runtime.worker``."""

    def __init__(
        self,
        layout: StoreLayout,
        *,
        heartbeat_interval: float = 5.0,
        python: Optional[str] = None,
    ) -> None:
        self.layout = layout
        self.heartbeat_interval = heartbeat_interval
        self.python = python or sys.executable

    def start(self, run: Run, plan: GangPlan) -> GangHandle:
        """Create the run dir, write the spec, launch all gang processes."""
        paths = self.layout.run_paths(run.uuid).ensure()
        paths.spec_path.write_text(json.dumps(run.spec_data))
        if run.code_ref:
            materialize_snapshot(run.code_ref, self.layout.snapshots_dir, paths.code)

        coordinator = (
            f"127.0.0.1:{_free_port()}" if plan.num_hosts > 1 else None
        )
        handle = GangHandle(
            run_id=run.id, run_uuid=run.uuid, plan=plan, paths=paths
        )
        seed = run.spec.environment.seed
        try:
            for process_id in range(plan.num_hosts):
                env = dict(os.environ)
                if plan.accelerator.startswith("cpu"):
                    # CPU gangs must not attach to a site-installed TPU
                    # plugin (sitecustomize-style PJRT registration keyed on
                    # these vars would pin the worker to the real chip).
                    for key in list(env):
                        if key.startswith(("PALLAS_AXON_", "AXON_")) or key == "TPU_SKIP_MDS_QUERY":
                            env.pop(key)
                    env["JAX_PLATFORMS"] = "cpu"
                env.update(plan.env_vars)
                # The worker runs with cwd=run_dir; make sure it can import
                # this package even when it isn't pip-installed (dev/test
                # checkouts) by prepending the package parent to PYTHONPATH —
                # after the spec's env_vars so a user PYTHONPATH augments
                # rather than clobbers it.
                pkg_parent = str(Path(__file__).resolve().parents[2])
                env["PYTHONPATH"] = os.pathsep.join(
                    p for p in (pkg_parent, env.get("PYTHONPATH")) if p
                )
                env.update(
                    gang_env(
                        run_id=run.id,
                        run_uuid=run.uuid,
                        run_dir=str(paths.root),
                        spec_path=str(paths.spec_path),
                        process_id=process_id,
                        num_processes=plan.num_hosts,
                        coordinator=coordinator,
                        devices_per_host=plan.devices_per_host,
                        accelerator=plan.accelerator,
                        mesh_axes=plan.mesh_axes,
                        strategy=plan.strategy,
                        strategy_options=plan.strategy_options,
                        heartbeat_interval=self.heartbeat_interval,
                        seed=seed,
                    )
                )
                log_path = paths.log_file(process_id)
                log_path.parent.mkdir(parents=True, exist_ok=True)
                log_fh = open(log_path, "ab")
                proc = subprocess.Popen(
                    [self.python, "-m", "polyaxon_tpu.runtime.worker"],
                    env=env,
                    stdout=log_fh,
                    stderr=subprocess.STDOUT,
                    cwd=str(paths.root),
                    # Own process group: stop() must take down the whole
                    # tree (shell-command runs spawn sh → user process).
                    start_new_session=True,
                )
                log_fh.close()  # child holds the fd
                handle.processes[process_id] = proc
        except Exception as e:
            self.stop(handle)
            raise SpawnerError(f"Failed to launch gang for run {run.id}: {e}") from e
        return handle

    @staticmethod
    def _signal_group(proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(proc.pid, sig)  # pgid == pid (start_new_session)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    def signal_gang(self, handle: GangHandle, sig: int) -> None:
        """Signal every live process group without waiting — the monitor's
        kill-escalation path, which must never block the task-bus thread."""
        for proc in handle.processes.values():
            if proc.poll() is None:
                self._signal_group(proc, sig)

    def stop(self, handle: GangHandle, grace: float = 5.0) -> None:
        """Terminate the gang (whole process groups): SIGTERM, wait
        ``grace``, then SIGKILL."""
        import signal

        for proc in handle.processes.values():
            if proc.poll() is None:
                self._signal_group(proc, signal.SIGTERM)
        deadline = time.time() + grace
        for proc in handle.processes.values():
            remaining = max(0.0, deadline - time.time())
            try:
                proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                self._signal_group(proc, signal.SIGKILL)
                proc.wait(timeout=5.0)
