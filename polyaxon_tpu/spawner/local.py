"""Gang spawner: N host processes for one accelerator slice.

Parity: reference ``polypod/experiment.py`` — ``ExperimentSpawner`` builds
pods+services per replica, injects rendezvous env, and starts/stops the
experiment (``start_experiment`` :350-357, pod creation :160-244).
TPU-native: a *gang* is N host processes for one accelerator slice; the
spawner launches ``runtime.worker`` once per host through a
:class:`~polyaxon_tpu.spawner.transport.Transport` (local subprocesses for
dev/test, ssh for real TPU-VM slices), injecting the coordinator/process-id/
mesh env contract that replaces TF_CONFIG.  Each process's stdout/stderr
stream to per-process log files; the reporting channel is the run's
``reports/`` dir on the shared store layout.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import time
from pathlib import Path
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from polyaxon_tpu.compiler import GangPlan
from polyaxon_tpu.db.registry import Run
from polyaxon_tpu.exceptions import SpawnerError
from polyaxon_tpu.runtime.env import gang_env
from polyaxon_tpu.spawner.transport import (
    LocalExecTransport,
    ProcessRef,
    Transport,
    terminate_refs,
)
from polyaxon_tpu.stores.layout import RunPaths, StoreLayout
from polyaxon_tpu.stores.snapshots import materialize_snapshot

LOOPBACK_HOSTS = ("127.0.0.1", "localhost", "::1")


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class GangHandle:
    """A live (or finished) gang: the spawner's unit of control."""

    run_id: int
    run_uuid: str
    plan: GangPlan
    paths: RunPaths
    processes: Dict[int, ProcessRef] = field(default_factory=dict)
    #: Byte offsets into each process's report file (watcher tail cursor).
    report_offsets: Dict[int, int] = field(default_factory=dict)
    started_at: float = field(default_factory=time.time)
    #: Consecutive monitor-poll failures (scheduler bookkeeping).
    monitor_failures: int = 0
    #: When the gang's roll-up first went terminal while members were still
    #: alive (scheduler grace-window bookkeeping).
    terminal_since: Optional[float] = None
    #: Escalation bookkeeping: each signal stage fires once per attempt
    #: (re-signalling every monitor tick would hammer ssh hosts).
    term_sent: bool = False
    kill_sent: bool = False
    #: Edge-trigger marks for the watcher's stall/straggler detector (one
    #: anomaly row per episode, not per monitor tick).
    anomaly_marks: Dict[str, bool] = field(default_factory=dict)

    def poll(self) -> Dict[int, Optional[int]]:
        """process_id -> exit code (None while running)."""
        return {pid: ref.poll() for pid, ref in self.processes.items()}

    @property
    def all_exited(self) -> bool:
        return all(code is not None for code in self.poll().values())


class GangSpawner:
    """Launches gangs of ``runtime.worker`` processes through a transport.

    ``hosts`` is the worker host pool: process ``i`` lands on
    ``hosts[i % len(hosts)]`` (one worker per TPU-VM host in the standard
    slice layout).  The coordinator address is ``hosts[0]`` — routable by
    every gang member, which is what ``jax.distributed.initialize`` needs.
    """

    def __init__(
        self,
        layout: StoreLayout,
        *,
        transport: Optional[Transport] = None,
        hosts: Optional[List[str]] = None,
        heartbeat_interval: float = 5.0,
        python: Optional[str] = None,
        coordinator_port_base: int = 8476,
    ) -> None:
        self.layout = layout
        self.transport = transport or LocalExecTransport()
        self.hosts = hosts or ["127.0.0.1"]
        self.heartbeat_interval = heartbeat_interval
        self.python = python or sys.executable
        self.coordinator_port_base = coordinator_port_base

    # -- host / coordinator assignment ---------------------------------------
    def host_for(self, process_id: int) -> str:
        return self.hosts[process_id % len(self.hosts)]

    def _pick_port(self, run: Run, offset: int) -> int:
        """A port on the head host: loopback pools probe a genuinely free
        one; remote heads get a derived port (base + offset block + run id)
        — the control plane can't probe a remote host's ports cheaply, and
        the run-id spread keeps concurrent gangs on a shared pool apart."""
        if self.host_for(0) in LOOPBACK_HOSTS:
            return _free_port()
        return self.coordinator_port_base + offset + run.id % 512

    def _coordinator(self, run: Run, plan: GangPlan) -> Optional[str]:
        if plan.num_hosts <= 1:
            return None
        return f"{self.host_for(0)}:{self._pick_port(run, 0)}"

    def allocate_service_port(self, run: Run) -> int:
        """The serving port for a service gang (block above the coordinator
        range so the two never collide)."""
        return self._pick_port(run, 512)

    # -- env contract ---------------------------------------------------------
    def _process_env(
        self,
        run: Run,
        plan: GangPlan,
        paths: RunPaths,
        process_id: int,
        coordinator: Optional[str],
    ) -> Dict[str, Optional[str]]:
        """Env overrides for one gang process (None = unset on the host)."""
        env: Dict[str, Optional[str]] = {}
        if plan.accelerator.startswith("cpu"):
            # CPU gangs must not attach to a site-installed TPU plugin
            # (sitecustomize-style PJRT registration keyed on these vars
            # would pin the worker to the real chip). The prefix strip
            # happens transport-side ON THE HOST — a remote worker's own
            # env can't be enumerated from here (see ``cpu_unset_prefixes``
            # in :meth:`start`).
            env["TPU_SKIP_MDS_QUERY"] = None
            env["JAX_PLATFORMS"] = "cpu"
        env.update(plan.env_vars)
        # The worker runs with cwd=run_dir; make sure it can import this
        # package even when it isn't pip-installed (dev/test checkouts) by
        # prepending the package parent to PYTHONPATH — after the spec's
        # env_vars so a user PYTHONPATH augments rather than clobbers it.
        pkg_parent = str(Path(__file__).resolve().parents[2])
        inherited_pp = env.get("PYTHONPATH") or os.environ.get("PYTHONPATH")
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_parent, inherited_pp) if p
        )
        env.update(
            gang_env(
                run_id=run.id,
                run_uuid=run.uuid,
                run_dir=str(paths.root),
                spec_path=str(paths.spec_path),
                process_id=process_id,
                num_processes=plan.num_hosts,
                coordinator=coordinator,
                devices_per_host=plan.devices_per_host,
                accelerator=plan.accelerator,
                mesh_axes=plan.mesh_axes,
                strategy=plan.strategy,
                dcn_axes=plan.dcn_axes,
                strategy_options=plan.strategy_options,
                heartbeat_interval=self.heartbeat_interval,
                seed=run.spec.environment.seed,
                data_dir=str(self.layout.data_dir),
                compile_cache_dir=str(self.layout.compile_cache_dir),
            )
        )
        return env

    # -- lifecycle ------------------------------------------------------------
    def start(self, run: Run, plan: GangPlan) -> GangHandle:
        """Create the run dir, write the spec, launch all gang processes."""
        paths = self.layout.run_paths(run.uuid).ensure()
        # Per-process command mailboxes (the control-plane→worker bus):
        # provisioned before launch so a command can never race a worker
        # that hasn't created its own dir yet.
        for process_id in range(plan.num_hosts):
            paths.command_dir(process_id).mkdir(parents=True, exist_ok=True)
        paths.spec_path.write_text(json.dumps(run.spec_data))
        if run.code_ref:
            materialize_snapshot(run.code_ref, self.layout.snapshots_dir, paths.code)

        coordinator = self._coordinator(run, plan)
        handle = GangHandle(
            run_id=run.id, run_uuid=run.uuid, plan=plan, paths=paths
        )
        cpu_unset_prefixes = (
            ("PALLAS_AXON_", "AXON_") if plan.accelerator.startswith("cpu") else ()
        )
        try:
            for process_id in range(plan.num_hosts):
                env = self._process_env(run, plan, paths, process_id, coordinator)
                log_path = paths.log_file(process_id)
                rc_path = log_path.with_suffix(".rc")
                ref = self.transport.launch(
                    self.host_for(process_id),
                    [self.python, "-m", "polyaxon_tpu.runtime.worker"],
                    env,
                    cwd=str(paths.root),
                    log_path=log_path,
                    rc_path=rc_path,
                    unset_prefixes=cpu_unset_prefixes,
                )
                handle.processes[process_id] = ref
        except Exception as e:
            self.stop(handle)
            raise SpawnerError(f"Failed to launch gang for run {run.id}: {e}") from e
        return handle

    def reattach(
        self, run: Run, plan: GangPlan, processes: List[Dict]
    ) -> Optional[GangHandle]:
        """Rebuild the handle for a gang a previous control plane launched.

        ``processes`` are the registry's process rows (pid + durable report
        offset). Returns None when the gang is not reattachable — run dir
        gone or pids unrecorded — in which case the caller re-dispatches.
        The reference gets this for free from k8s (pods outlive the API
        server); here the shared run dir + pid bookkeeping play that role.
        """
        paths = self.layout.run_paths(run.uuid)
        if not paths.root.exists():
            return None
        by_id = {p["process_id"]: p for p in processes}
        if any(
            process_id not in by_id or not by_id[process_id].get("pid")
            for process_id in range(plan.num_hosts)
        ):
            return None
        handle = GangHandle(
            run_id=run.id, run_uuid=run.uuid, plan=plan, paths=paths
        )
        for process_id in range(plan.num_hosts):
            row = by_id[process_id]
            rc_path = paths.log_file(process_id).with_suffix(".rc")
            handle.processes[process_id] = self.transport.reattach(
                self.host_for(process_id), int(row["pid"]), rc_path
            )
            handle.report_offsets[process_id] = int(row.get("report_offset") or 0)
        return handle

    def signal_gang(self, handle: GangHandle, sig: int) -> None:
        """Signal every live process group without waiting — the monitor's
        kill-escalation path, which must never block the task-bus thread."""
        for ref in handle.processes.values():
            if ref.poll() is None:
                ref.signal(sig)

    def stop(self, handle: GangHandle, grace: float = 5.0) -> None:
        """Terminate the gang (whole process groups): SIGTERM, wait
        ``grace``, then SIGKILL."""
        terminate_refs(handle.processes, grace=grace)


class LocalGangSpawner(GangSpawner):
    """The dev/test backend: gangs as local subprocesses (loopback pool)."""

    def __init__(
        self,
        layout: StoreLayout,
        *,
        heartbeat_interval: float = 5.0,
        python: Optional[str] = None,
    ) -> None:
        super().__init__(
            layout,
            transport=LocalExecTransport(),
            hosts=["127.0.0.1"],
            heartbeat_interval=heartbeat_interval,
            python=python,
        )
