"""Spawner transports: how a gang process reaches its host.

Parity: the reference's spawner drives *remote* infrastructure through the
k8s API (``polypod/experiment.py:160-244`` builds pods, ``:350-357``
starts/stops them).  TPU-native equivalent: a transport seam —
``launch(host, argv, env) / poll / signal`` — with two backends:

- :class:`LocalExecTransport` — subprocesses on this machine (dev/test; the
  whole e2e suite runs through it), and
- :class:`SSHTransport` — TPU-VM hosts over ssh, the way real multi-host
  slices are driven (``gcloud compute tpus tpu-vm ssh`` is a thin wrapper
  over exactly this).

The contract both sides share: the run directory lives on a filesystem
visible to the control plane AND every worker host at the same path (on
TPU-VM pods: an NFS or gcsfuse mount) — reports, logs, exit codes, and
code snapshots all ride it, so the control plane never needs a persistent
connection to a worker.
"""

from __future__ import annotations

import logging
import os
import shlex
import signal as signal_mod
import subprocess
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)


class ProcessRef:
    """A launched gang process as seen by the control plane."""

    #: Host-local pid (also the process-group id: transports launch every
    #: process as a session leader so signals take down the whole tree).
    pid: int

    def poll(self) -> Optional[int]:  # pragma: no cover - interface
        """Exit code, or None while running."""
        raise NotImplementedError

    def signal(self, sig: int) -> None:  # pragma: no cover - interface
        """Deliver ``sig`` to the process group (non-blocking)."""
        raise NotImplementedError

    def wait(self, timeout: float) -> Optional[int]:  # pragma: no cover
        """Block up to ``timeout`` for exit; return the code or None."""
        raise NotImplementedError


class Transport:
    """Launches gang processes on a host. One instance serves many gangs."""

    def launch(
        self,
        host: str,
        argv: Sequence[str],
        env: Dict[str, str],
        *,
        cwd: str,
        log_path: Path,
        rc_path: Path,
        unset_prefixes: Sequence[str] = (),
    ) -> ProcessRef:  # pragma: no cover - interface
        """Start ``argv`` on ``host`` with ``env`` exported (None values =
        unset), stdout+stderr appended to ``log_path``, exit code written to
        ``rc_path``.  ``unset_prefixes`` strips matching vars from the
        HOST's own environment — needed because the control plane cannot
        enumerate a remote host's env by name."""
        raise NotImplementedError

    def reattach(
        self, host: str, pid: int, rc_path: Path
    ) -> ProcessRef:  # pragma: no cover - interface
        """Rebuild a ref for a process launched by a PREVIOUS control plane
        (restart recovery).  The ref must poll correctly whether the process
        is still running or already exited."""
        raise NotImplementedError


# -- local exec ---------------------------------------------------------------


class _LocalProcessRef(ProcessRef):
    def __init__(self, proc: subprocess.Popen) -> None:
        self._proc = proc
        self.pid = proc.pid

    def poll(self) -> Optional[int]:
        return self._proc.poll()

    def signal(self, sig: int) -> None:
        try:
            os.killpg(self.pid, sig)  # pgid == pid (start_new_session)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                self._proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass

    def wait(self, timeout: float) -> Optional[int]:
        try:
            return self._proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None


class LocalExecTransport(Transport):
    """Subprocesses on the control-plane machine (ignores ``host``).

    Inherits the control plane's os.environ under the overrides — local
    workers need the same interpreter setup (PATH, venv) the service has.
    """

    def launch(
        self,
        host: str,
        argv: Sequence[str],
        env: Dict[str, str],
        *,
        cwd: str,
        log_path: Path,
        rc_path: Path,
        unset_prefixes: Sequence[str] = (),
    ) -> ProcessRef:
        full_env = dict(os.environ)
        for prefix in unset_prefixes:
            for key in list(full_env):
                if key.startswith(prefix):
                    full_env.pop(key)
        # The gang contract may DELETE inherited vars (e.g. the axon/TPU
        # plugin pins for CPU gangs): None means "unset".
        for key, value in env.items():
            if value is None:
                full_env.pop(key, None)
            else:
                full_env[key] = value
        log_path.parent.mkdir(parents=True, exist_ok=True)
        log_fh = open(log_path, "ab")
        proc = subprocess.Popen(
            list(argv),
            env={k: v for k, v in full_env.items() if v is not None},
            stdout=log_fh,
            stderr=subprocess.STDOUT,
            cwd=cwd,
            start_new_session=True,
        )
        log_fh.close()  # child holds the fd
        return _LocalProcessRef(proc)

    def reattach(self, host: str, pid: int, rc_path: Path) -> ProcessRef:
        return _ReattachedLocalRef(pid, rc_path)


class _ReattachedLocalRef(ProcessRef):
    """A local gang process inherited from a dead control plane.

    We are not its parent, so ``waitpid`` is unavailable: liveness comes
    from signal-0 to the process group (pgid == pid — launches are session
    leaders), and the exit code from the rc file when one exists.  A local
    launch records no rc file, so a process found dead reads as exit 1
    (status-wise the worker's own final report line, ingested from the run
    dir, still wins when it got written)."""

    def __init__(self, pid: int, rc_path: Path) -> None:
        self.pid = pid
        self._rc_path = rc_path
        self._exit_code: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._exit_code is not None:
            return self._exit_code
        try:
            raw = self._rc_path.read_text().strip()
        except OSError:
            raw = ""
        if raw:
            self._exit_code = int(raw)
            return self._exit_code
        try:
            os.killpg(self.pid, 0)
        except ProcessLookupError:
            self._exit_code = 1  # died before this control plane attached
            return self._exit_code
        except (PermissionError, OSError):
            # Exists but not signalable by us — treat as alive; the
            # heartbeat cron is the backstop if it's a reused pid.
            return None
        # Signal-0 counts zombies as alive: a worker whose (dead or
        # unrelated) parent never reaped it would read as running forever.
        try:
            with open(f"/proc/{self.pid}/stat") as fh:
                # Field 3, after the parenthesized comm (which may itself
                # contain spaces/parens — split after the LAST ')').
                state = fh.read().rsplit(")", 1)[1].split()[0]
            if state == "Z":
                self._exit_code = 1
                return self._exit_code
        except (OSError, IndexError):
            pass  # no procfs — fall back to signal-0 semantics
        return None

    def signal(self, sig: int) -> None:
        try:
            os.killpg(self.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            pass

    def wait(self, timeout: float) -> Optional[int]:
        deadline = time.time() + timeout
        while True:
            code = self.poll()
            if code is not None or time.time() >= deadline:
                return code
            time.sleep(min(0.2, max(0.0, deadline - time.time())))


# -- ssh ----------------------------------------------------------------------


def build_remote_script(
    argv: Sequence[str],
    env: Dict[str, str],
    *,
    cwd: str,
    log_path: str,
    rc_path: str,
    pid_path: str,
    unset_prefixes: Sequence[str] = (),
) -> str:
    """The shell script SSHTransport runs on the worker host.

    Pure function (unit-tested without ssh): backgrounds the worker in its
    own session, appends stdout+stderr to ``log_path``, records the session
    pid in ``pid_path`` and the worker's own pid in ``pid_path``+``.child``
    (signalling targets), and the exit code in ``rc_path`` (the poll
    channel) — all on the shared run dir, so polling never needs an ssh
    round-trip.  ``unset_prefixes`` strips matching vars from the HOST's
    environment (the control plane can't enumerate them by name).
    """
    pre = [f"cd {shlex.quote(cwd)}"]
    if unset_prefixes:
        cases = "|".join(f"{p}*" for p in unset_prefixes)
        pre.append(
            'for _v in $(env | sed -n "s/=.*//p"); do '
            f'case "$_v" in {cases}) unset "$_v";; esac; done'
        )
    for key, value in sorted(env.items()):
        if value is None:
            pre.append(f"unset {key}")
        else:
            pre.append(f"export {key}={shlex.quote(str(value))}")
    inner = " && ".join(pre)
    cmd = " ".join(shlex.quote(a) for a in argv)
    rc_q, rc_tmp_q = shlex.quote(rc_path), shlex.quote(rc_path + ".tmp")
    pid_q, pid_tmp_q = shlex.quote(pid_path), shlex.quote(pid_path + ".tmp")
    child_q = shlex.quote(pid_path + ".child")
    child_tmp_q = shlex.quote(pid_path + ".child.tmp")
    # The tmp+mv dance makes the rc/pid files appear atomically (the control
    # plane polls them over the shared mount). setsid → the whole remote
    # tree is one signalable session; $! after a backgrounded setsid is the
    # session leader's pid.  The wrapper must SURVIVE a group TERM (or the
    # exit code is never recorded): it forwards the signal to the worker and
    # re-waits for the real status.  SIGKILL can't be trapped, which is why
    # the worker's own pid is published: KILL goes to the worker, the
    # wrapper lives to record 137.
    wrapped = (
        "child=; "
        "trap 'kill -TERM \"$child\" 2>/dev/null' TERM INT; "
        f"{cmd} & child=$!; "
        f"echo $child > {child_tmp_q} && mv {child_tmp_q} {child_q}; "
        'rc=127; while :; do wait "$child"; rc=$?; '
        'kill -0 "$child" 2>/dev/null || break; done; '
        f"echo $rc > {rc_tmp_q} && mv {rc_tmp_q} {rc_q}"
    )
    return (
        f"{inner} && "
        f"setsid sh -c {shlex.quote(wrapped)} >> {shlex.quote(log_path)} 2>&1 & "
        f"echo $! > {pid_tmp_q} && mv {pid_tmp_q} {pid_q} && cat {pid_q}"
    )


def build_ssh_argv(
    host: str,
    script: str,
    *,
    user: Optional[str] = None,
    port: Optional[int] = None,
    identity_file: Optional[str] = None,
    extra_opts: Sequence[str] = (),
) -> List[str]:
    """The ssh command line (pure function, unit-tested)."""
    argv = ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=accept-new"]
    if port is not None:
        argv += ["-p", str(port)]
    if identity_file:
        argv += ["-i", identity_file]
    argv += list(extra_opts)
    target = f"{user}@{host}" if user else host
    argv += [target, script]
    return argv


class _RemoteProcessRef(ProcessRef):
    """A process on a worker host, observed via the shared run dir.

    Liveness: the rc file appearing means exited (its content is the code);
    no rc file means running — a host that dies without writing one is
    caught by the zombie-heartbeat cron, the same backstop local gangs have.
    """

    #: How long after a group SIGKILL (rc writer dead too) before the exit
    #: code is synthesized.
    KILL_SETTLE = 5.0

    def __init__(
        self, transport: "SSHTransport", host: str, pid: int, rc_path: Path
    ) -> None:
        self._transport = transport
        self.host = host
        self.pid = pid
        self._rc_path = rc_path
        self._child_pid_path = rc_path.with_suffix(".pid.child")
        self._exit_code: Optional[int] = None
        self._group_killed_at: Optional[float] = None

    def poll(self) -> Optional[int]:
        if self._exit_code is not None:
            return self._exit_code
        try:
            raw = self._rc_path.read_text().strip()
        except (FileNotFoundError, OSError):
            raw = ""
        if raw:
            self._exit_code = int(raw)
            return self._exit_code
        if (
            self._group_killed_at is not None
            and time.time() - self._group_killed_at > self.KILL_SETTLE
        ):
            # The whole session (rc writer included) took the KILL; nothing
            # will ever write the rc file — synthesize the code so the gang
            # reads as exited and the run can finalize.
            self._exit_code = 128 + int(signal_mod.SIGKILL)
            return self._exit_code
        return None

    def signal(self, sig: int) -> None:
        """Best-effort: an unreachable host (the usual reason to signal a
        zombie) must not crash the monitor/cron tasks doing the signalling."""
        # The ``-s N --`` spelling is the one dash's kill builtin accepts
        # for group targets (``kill -15 -- -pid`` it rejects).
        target = f"-- -{self.pid}"  # negative pid == whole remote session
        if sig == signal_mod.SIGKILL:
            # KILL can't be trapped: aim it at the worker itself (published
            # by the launch wrapper) so the wrapper survives to record the
            # exit code; fall back to the group if the file never appeared.
            try:
                child = self._child_pid_path.read_text().strip()
            except (FileNotFoundError, OSError):
                child = ""
            if child:
                target = child
            else:
                self._group_killed_at = self._group_killed_at or time.time()
        try:
            self._transport.run_on(
                self.host, f"kill -s {int(sig)} {target} 2>/dev/null || true"
            )
        except Exception as e:
            logger.warning("Signal %s to %s on %s failed: %s", sig, self.pid, self.host, e)

    def wait(self, timeout: float) -> Optional[int]:
        deadline = time.time() + timeout
        while True:
            code = self.poll()
            if code is not None or time.time() >= deadline:
                return code
            time.sleep(min(0.2, max(0.0, deadline - time.time())))


class SSHTransport(Transport):
    """Drive TPU-VM (or any ssh-reachable) hosts.

    Assumes: passwordless ssh (agent or ``identity_file``), the worker image
    has the same python env at ``remote_python``, and the store layout's
    base dir is mounted at the same path on every host.
    """

    def __init__(
        self,
        *,
        user: Optional[str] = None,
        port: Optional[int] = None,
        identity_file: Optional[str] = None,
        extra_opts: Sequence[str] = (),
        connect_timeout: float = 10.0,
    ) -> None:
        self.user = user
        self.port = port
        self.identity_file = identity_file
        self.extra_opts = list(extra_opts)
        self.connect_timeout = connect_timeout

    def run_on(self, host: str, script: str) -> str:
        """Run a short script on ``host``; returns stdout. Raises on failure."""
        argv = build_ssh_argv(
            host,
            script,
            user=self.user,
            port=self.port,
            identity_file=self.identity_file,
            extra_opts=self.extra_opts,
        )
        out = subprocess.run(
            argv,
            capture_output=True,
            text=True,
            timeout=self.connect_timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"ssh to {host} failed (rc={out.returncode}): {out.stderr.strip()[:500]}"
            )
        return out.stdout

    def launch(
        self,
        host: str,
        argv: Sequence[str],
        env: Dict[str, str],
        *,
        cwd: str,
        log_path: Path,
        rc_path: Path,
        unset_prefixes: Sequence[str] = (),
    ) -> ProcessRef:
        pid_path = rc_path.with_suffix(".pid")
        script = build_remote_script(
            argv,
            env,
            cwd=cwd,
            log_path=str(log_path),
            rc_path=str(rc_path),
            pid_path=str(pid_path),
            unset_prefixes=unset_prefixes,
        )
        out = self.run_on(host, script)
        pid = int(out.strip().splitlines()[-1])
        return _RemoteProcessRef(self, host, pid, rc_path)

    def reattach(self, host: str, pid: int, rc_path: Path) -> ProcessRef:
        # The remote ref is already reconstructable from disk alone: the rc
        # file (shared run dir) is the poll channel and pid the signal target.
        return _RemoteProcessRef(self, host, pid, rc_path)


def terminate_refs(
    refs: Dict[int, ProcessRef], grace: float = 5.0
) -> None:
    """TERM every live ref, wait up to ``grace``, then KILL stragglers."""
    for ref in refs.values():
        if ref.poll() is None:
            ref.signal(signal_mod.SIGTERM)
    deadline = time.time() + grace
    for ref in refs.values():
        remaining = max(0.0, deadline - time.time())
        if ref.wait(remaining) is None:
            ref.signal(signal_mod.SIGKILL)
            ref.wait(5.0)
