"""Remote gang spawner: TPU-VM slices over ssh.

Parity: the reference's spawner layer drives remote infrastructure through
the k8s API (``polypod/experiment.py:160-244`` pod creation, ``:350-357``
start/stop).  TPU-native: a TPU slice is N VM hosts each owning
``devices_per_host`` chips; ``gcloud``'s own multi-host story is "ssh to
every worker and run the same program" — this backend does exactly that
through :class:`~polyaxon_tpu.spawner.transport.SSHTransport`, with the
shared run dir (NFS / gcsfuse mount) as the report + exit-code channel.

Deployment contract (see ``docs/remote.md`` for the v5e-16 walkthrough):

- every worker host mounts the platform base dir at the SAME path as the
  control plane (outputs/, logs/, reports/ ride it);
- passwordless ssh from the control plane to every host;
- ``remote_python`` resolves on the hosts with polyaxon-tpu installed
  (or the shared mount's checkout on PYTHONPATH — the spawner injects it);
- the coordinator port range (``coordinator_port_base`` .. +512) is open
  between hosts (jax.distributed rides it over DCN/ICI-adjacent network).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from polyaxon_tpu.spawner.local import GangSpawner
from polyaxon_tpu.spawner.transport import SSHTransport, Transport
from polyaxon_tpu.stores.layout import StoreLayout


class RemoteGangSpawner(GangSpawner):
    """Launch gangs on a pool of ssh-reachable worker hosts.

    ``hosts`` are the TPU-VM workers in slice order (worker 0 first: process
    ids map onto hosts round-robin, and host 0 becomes the jax.distributed
    coordinator).  The transport is injectable so the whole orchestration
    path is testable with :class:`LocalExecTransport` standing in for ssh.
    """

    def __init__(
        self,
        layout: StoreLayout,
        hosts: Sequence[str],
        *,
        user: Optional[str] = None,
        identity_file: Optional[str] = None,
        ssh_opts: Sequence[str] = (),
        python: str = "python3",
        heartbeat_interval: float = 5.0,
        coordinator_port_base: int = 8476,
        transport: Optional[Transport] = None,
    ) -> None:
        if not hosts:
            raise ValueError("RemoteGangSpawner needs at least one worker host")
        super().__init__(
            layout,
            transport=transport
            or SSHTransport(user=user, identity_file=identity_file, extra_opts=ssh_opts),
            hosts=list(hosts),
            heartbeat_interval=heartbeat_interval,
            python=python,
            coordinator_port_base=coordinator_port_base,
        )


def spawner_from_conf(layout: StoreLayout, conf, *, heartbeat_interval: float):
    """Build the spawner the conf selects (reference: settings-driven
    spawner class selection in ``scheduler/spawners/``).

    ``spawner.backend=local`` (default) → :class:`LocalGangSpawner` semantics;
    ``spawner.backend=ssh`` → :class:`RemoteGangSpawner` over
    ``spawner.hosts`` (comma-separated).
    """
    backend = conf.get("spawner.backend")
    if backend == "ssh":
        hosts: List[str] = [
            h.strip() for h in (conf.get("spawner.hosts") or "").split(",") if h.strip()
        ]
        if not hosts:
            raise ValueError(
                "spawner.backend=ssh requires spawner.hosts "
                "(comma-separated worker addresses)"
            )
        user = conf.get("spawner.ssh_user") or None
        identity = conf.get("spawner.ssh_identity_file") or None
        return RemoteGangSpawner(
            layout,
            hosts,
            user=user,
            identity_file=identity,
            python=conf.get("spawner.remote_python"),
            heartbeat_interval=heartbeat_interval,
            coordinator_port_base=conf.get("spawner.coordinator_port_base"),
        )
    if backend != "local":
        raise ValueError(f"Unknown spawner.backend {backend!r} (local|ssh)")
    from polyaxon_tpu.spawner.local import LocalGangSpawner

    return LocalGangSpawner(layout, heartbeat_interval=heartbeat_interval)
