from polyaxon_tpu.tracker.service import CLUSTER_ID_KEY, Tracker, usage_rollup

__all__ = ["CLUSTER_ID_KEY", "Tracker", "usage_rollup"]
