"""Usage analytics: per-event counters + optional anonymized publish.

Parity: the reference tracker app (``tracker/publish_tracker.py`` — a
segment-style ``analytics.track`` of every platform event keyed by
cluster id, write-key gated, errors swallowed).  TPU-native shape:

- every audited event increments a ``usage.<event_type>`` counter on the
  configured stats backend (statsd/memory) — zero-config operational
  analytics;
- an OPTIONAL external publish (``tracker.endpoint`` conf option,
  default '' = off — telemetry is opt-in, the inverse of the
  reference's default) POSTs ``{cluster, event, created_at}`` with the
  actor and all entity payload STRIPPED, fire-and-forget off the bus
  thread;
- the operator surface is ``GET /api/v1/analytics``: event counts per
  day from the activity feed plus a platform summary (runs by
  kind/status, users, devices) — what the reference shipped to segment,
  kept queryable in-house instead.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request
from typing import Any, Dict, Optional

from polyaxon_tpu.events import Event

logger = logging.getLogger(__name__)

#: The options key holding the stable anonymous cluster id (minted once).
CLUSTER_ID_KEY = "platform.cluster_id"


class Tracker:
    """Auditor subscriber: counts every event, optionally publishes it."""

    def __init__(
        self,
        stats,
        *,
        endpoint: str = "",
        cluster_id: str = "",
    ) -> None:
        self.stats = stats
        self.endpoint = endpoint
        self.cluster_id = cluster_id
        #: Last publish thread (tests join it; None until a publish fires).
        self._last_publish = None

    def __call__(self, event: Event) -> None:
        self.stats.incr(f"usage.{event.event_type}")
        if not self.endpoint:
            return
        payload = {
            # Anonymized on purpose (reference serialized with
            # include_actor_name=False): event type + timing only, no
            # entity payloads, no actors.
            "cluster": self.cluster_id,
            "event": event.event_type,
            "created_at": event.created_at,
        }

        def _publish() -> None:
            try:
                req = urllib.request.Request(
                    self.endpoint,
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5)
            except Exception:  # noqa: BLE001 — analytics must never break the platform
                logger.debug("tracker publish failed", exc_info=True)

        # Always a dedicated thread: audits fire from API handlers (the
        # asyncio event loop) and the bus thread alike, and a slow
        # analytics endpoint must stall neither.  (bus.offload only
        # detaches when called FROM the bus thread — not enough here.)
        import threading

        t = threading.Thread(target=_publish, name="tracker-publish", daemon=True)
        self._last_publish = t
        t.start()


def usage_rollup(
    registry, days: int = 14, now: Optional[float] = None
) -> Dict[str, Any]:
    """Event counts per day + platform summary for ``/api/v1/analytics``
    (schema knowledge lives with the registry; this is the tracker-facing
    name)."""
    return registry.usage_rollup(days=days, now=now)
