"""Single sign-on: OAuth2 authorization-code login against a provider.

Parity: reference ``polyaxon/sso/`` — provider wizards for GitHub /
GitLab / Bitbucket / Azure that map an external identity onto a platform
user.  Collapsed here to one authorization-code flow over a provider
CATALOG (the four reference providers plus a generic ``oidc`` entry whose
endpoints come from conf), with the platform's own per-user tokens as the
session mechanism: a successful callback upserts the user, ROTATES their
platform token, and hands it to the browser (localStorage — consistent
with the dashboard's no-token-in-URL rule... the one-time callback
fragment excepted, which is the standard implicit-handoff tradeoff).

State is a signed nonce held in-process with a TTL — the control plane is
a single process (no shared cache to coordinate), so this matches the
deployment model the same way the reference leaned on Django sessions.
"""

from __future__ import annotations

import secrets
import time
from dataclasses import dataclass, replace
from typing import Dict, Optional
from urllib.parse import urlencode

from polyaxon_tpu.exceptions import PolyaxonTPUError


class SSOError(PolyaxonTPUError):
    pass


@dataclass(frozen=True)
class ProviderConfig:
    name: str
    authorize_url: str
    token_url: str
    userinfo_url: str
    #: JSON field of the userinfo payload that names the user
    username_field: str
    scope: str = ""


#: The reference's four providers (``sso/providers/``) + generic OIDC.
PROVIDERS: Dict[str, ProviderConfig] = {
    "github": ProviderConfig(
        name="github",
        authorize_url="https://github.com/login/oauth/authorize",
        token_url="https://github.com/login/oauth/access_token",
        userinfo_url="https://api.github.com/user",
        username_field="login",
        scope="read:user",
    ),
    "gitlab": ProviderConfig(
        name="gitlab",
        authorize_url="https://gitlab.com/oauth/authorize",
        token_url="https://gitlab.com/oauth/token",
        userinfo_url="https://gitlab.com/api/v4/user",
        username_field="username",
        scope="read_user",
    ),
    "bitbucket": ProviderConfig(
        name="bitbucket",
        authorize_url="https://bitbucket.org/site/oauth2/authorize",
        token_url="https://bitbucket.org/site/oauth2/access_token",
        userinfo_url="https://api.bitbucket.org/2.0/user",
        username_field="username",
        scope="account",
    ),
    "azure": ProviderConfig(
        name="azure",
        authorize_url=(
            "https://login.microsoftonline.com/common/oauth2/v2.0/authorize"
        ),
        token_url="https://login.microsoftonline.com/common/oauth2/v2.0/token",
        userinfo_url="https://graph.microsoft.com/v1.0/me",
        username_field="userPrincipalName",
        scope="User.Read",
    ),
    # Endpoints supplied entirely by conf (self-hosted GitLab, Keycloak,
    # Okta, dex, ...).
    "oidc": ProviderConfig(
        name="oidc",
        authorize_url="",
        token_url="",
        userinfo_url="",
        username_field="preferred_username",
        scope="openid profile",
    ),
}


def resolve_provider(conf) -> Optional[ProviderConfig]:
    """The configured provider with conf URL/field overrides applied;
    None when SSO is off (no provider or no client id)."""
    name = conf.get("sso.provider")
    if not name:
        return None
    base = PROVIDERS.get(name)
    if base is None:
        raise SSOError(f"Unknown SSO provider {name!r}")
    overrides = {}
    for field, key in (
        ("authorize_url", "sso.authorize_url"),
        ("token_url", "sso.token_url"),
        ("userinfo_url", "sso.userinfo_url"),
        ("username_field", "sso.username_field"),
    ):
        value = conf.get(key)
        if value:
            overrides[field] = value
    provider = replace(base, **overrides)
    if not conf.get("sso.client_id"):
        return None
    if not (provider.authorize_url and provider.token_url and provider.userinfo_url):
        raise SSOError(
            f"SSO provider {name!r} needs authorize/token/userinfo URLs "
            "(set sso.authorize_url etc.)"
        )
    return provider


class StateStore:
    """Single-use login nonces with a TTL (CSRF guard for the callback).

    Bounded: /auth/sso/login is unauthenticated, so without a cap a
    request loop would grow the dict for the whole TTL; at the cap the
    oldest nonce is evicted (its login attempt just restarts)."""

    def __init__(self, ttl: float = 600.0, max_size: int = 4096) -> None:
        self.ttl = ttl
        self.max_size = max_size
        self._states: Dict[str, float] = {}

    def issue(self) -> str:
        now = time.time()
        self._states = {
            s: t for s, t in self._states.items() if now - t < self.ttl
        }
        while len(self._states) >= self.max_size:
            self._states.pop(next(iter(self._states)))  # oldest (insert order)
        state = secrets.token_urlsafe(24)
        self._states[state] = now
        return state

    def redeem(self, state: Optional[str]) -> bool:
        if not state:
            return False
        issued = self._states.pop(state, None)
        return issued is not None and time.time() - issued < self.ttl


def authorize_redirect_url(
    provider: ProviderConfig, client_id: str, redirect_uri: str, state: str
) -> str:
    params = {
        "client_id": client_id,
        "redirect_uri": redirect_uri,
        "state": state,
        "response_type": "code",
    }
    if provider.scope:
        params["scope"] = provider.scope
    sep = "&" if "?" in provider.authorize_url else "?"
    return f"{provider.authorize_url}{sep}{urlencode(params)}"


async def exchange_code(
    session, provider: ProviderConfig, *, code: str, client_id: str,
    client_secret: str, redirect_uri: str,
) -> str:
    """code -> provider access token (server-side POST)."""
    async with session.post(
        provider.token_url,
        data={
            "client_id": client_id,
            "client_secret": client_secret,
            "code": code,
            "grant_type": "authorization_code",
            "redirect_uri": redirect_uri,
        },
        headers={"Accept": "application/json"},
    ) as resp:
        if resp.status != 200:
            raise SSOError(
                f"Token exchange failed ({resp.status}): "
                f"{(await resp.text())[:200]}"
            )
        payload = await resp.json(content_type=None)
    token = payload.get("access_token")
    if not token:
        raise SSOError(f"No access_token in provider response: {payload}")
    return token


async def fetch_username(session, provider: ProviderConfig, access_token: str) -> str:
    async with session.get(
        provider.userinfo_url,
        headers={
            "Authorization": f"Bearer {access_token}",
            "Accept": "application/json",
        },
    ) as resp:
        if resp.status != 200:
            raise SSOError(
                f"Userinfo fetch failed ({resp.status}): "
                f"{(await resp.text())[:200]}"
            )
        payload = await resp.json(content_type=None)
    username = payload.get(provider.username_field)
    if not username:
        raise SSOError(
            f"Userinfo payload has no {provider.username_field!r}: "
            f"{list(payload)}"
        )
    return str(username)


async def authenticate(
    provider: ProviderConfig,
    *,
    code: str,
    client_id: str,
    client_secret: str,
    redirect_uri: str,
    timeout: float = 15.0,
) -> str:
    """Full code -> identity resolution on one bounded client session
    (a stalled provider must not pin the callback handler for aiohttp's
    5-minute default)."""
    import aiohttp

    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=timeout)
    ) as session:
        access = await exchange_code(
            session,
            provider,
            code=code,
            client_id=client_id,
            client_secret=client_secret,
            redirect_uri=redirect_uri,
        )
        return await fetch_username(session, provider, access)


#: Page that hands the platform token to the dashboard (localStorage, same
#: slot the login form uses) and cleans the URL.
CALLBACK_HTML = """<!doctype html>
<html><body><script>
localStorage.setItem('px_token', {token!r});
location.replace('/');
</script>signed in — redirecting…</body></html>
"""
