"""REST + WebSocket API over the orchestrator.

Parity: the reference's DRF surface (``api/experiments/views.py`` — list/
detail :120-280, stop/restart/resume/copy :281-368, statuses :468, metric
ingestion :495-509) and its Sanic streams service (``streams/api.py:14-45``,
``streams/resources/experiments.py:22-113`` — WS log/metric tailing).
TPU-native collapse: one aiohttp app over the embedded orchestrator; live
tailing reads the registry's cursor-friendly rows (statuses/metrics/logs
are ordinary ordered rows), no RabbitMQ/Redis fan-out needed.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Any, Dict, Optional

from polyaxon_tpu.conf.knobs import knob_int, knob_str
from polyaxon_tpu.db.registry import RemediationStatus, Run, RunRegistry
from polyaxon_tpu.events import EventTypes
from polyaxon_tpu.exceptions import PolyaxonTPUError
from polyaxon_tpu.monitor.alerts import RuleContext, run_slo_status
from polyaxon_tpu.monitor.watcher import anomaly_status, goodput_status
from polyaxon_tpu.orchestrator import Orchestrator
from polyaxon_tpu.stats.metrics import (
    PROMETHEUS_CONTENT_TYPE,
    labeled_key,
    render_prometheus,
    render_standard_gauges,
    split_labeled_key,
)
from polyaxon_tpu.tracking.trace import chrome_trace

logger = logging.getLogger(__name__)

API_PREFIX = "/api/v1"

#: Status classes for the per-route request counter — a fixed vocabulary
#: (never the raw code) keeps the label set bounded.
_STATUS_CLASSES = {1: "1xx", 2: "2xx", 3: "3xx", 4: "4xx", 5: "5xx"}


def run_to_dict(run: Run) -> Dict[str, Any]:
    return {
        "id": run.id,
        "uuid": run.uuid,
        "kind": run.kind,
        "name": run.name,
        "project": run.project,
        "status": run.status,
        "group_id": run.group_id,
        "pipeline_id": run.pipeline_id,
        "original_id": run.original_id,
        "cloning_strategy": run.cloning_strategy,
        "restarts": run.restarts,
        "tags": run.tags,
        "last_metric": run.last_metric,
        "service_url": run.service_url,
        "is_done": run.is_done,
        "created_at": run.created_at,
        "started_at": run.started_at,
        "finished_at": run.finished_at,
        "archived_at": run.archived_at,
        "spec": run.spec_data,
    }


def create_app(orch: Orchestrator, auth_token: Optional[str] = None):
    """``auth_token`` enables bearer-token access control (reference
    ``scopes/`` permission classes + ephemeral/internal tokens, collapsed
    to one shared-secret scheme); ``/api/v1/status`` stays open for health
    probes, like the reference's ``/status`` endpoint."""
    from aiohttp import WSMsgType, web

    routes = web.RouteTableDef()
    reg: RunRegistry = orch.registry

    def _int_param(request, name: str, default: Optional[int] = None) -> Optional[int]:
        raw = request.rel_url.query.get(name)
        if raw is None:
            return default
        try:
            return int(raw)
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": f"query param {name!r} must be an integer"}),
                content_type="application/json",
            )

    def _float_param(
        request, name: str, default: Optional[float] = None
    ) -> Optional[float]:
        raw = request.rel_url.query.get(name)
        if raw is None:
            return default
        try:
            return float(raw)
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": f"query param {name!r} must be a number"}),
                content_type="application/json",
            )

    def _audit(request, event_type, **ctx):
        # Every mutating entity action lands in the activity feed with the
        # authenticated actor (reference events carry actor attributes).
        actor = request.get("actor")
        if actor:
            ctx["actor"] = actor
        orch.auditor.record(event_type, **ctx)

    def _json_error(exc_cls, message: str):
        """An aiohttp HTTP error carrying the API's JSON error shape."""
        return exc_cls(
            text=json.dumps({"error": message}),
            content_type="application/json",
        )

    def _project_denied(request, project: str) -> bool:
        """Project-scoped access (reference ``ownership/`` + ``scopes/``):
        owned projects admit owner + collaborators; admins (including the
        open-mode anonymous admin) see everything; ownerless projects stay
        open."""
        if request.get("role") == "admin":
            return False
        return not reg.project_access(project, request.get("actor"))

    def _require_project(request, project: str) -> None:
        if _project_denied(request, project):
            raise _json_error(
                web.HTTPForbidden, f"no access to project {project!r}"
            )

    def _require_project_owner(request, project: str) -> None:
        """Owner-or-admin gate for project administration (delete, share)."""
        if request.get("role") == "admin":
            return
        proj = reg.get_project(project)
        owner = (proj or {}).get("owner")
        if owner and owner != request.get("actor"):
            raise _json_error(
                web.HTTPForbidden,
                f"only the owner of {project!r} (or an admin) may do this",
            )

    def _run_or_404(request) -> Run:
        try:
            run = reg.get_run(int(request.match_info["run_id"]))
        except PolyaxonTPUError:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"run {request.match_info['run_id']} not found"}),
                content_type="application/json",
            )
        # Every run endpoint rides this lookup, so the project ACL holds
        # across detail/actions/logs/metrics/artifacts/WS uniformly.
        _require_project(request, run.project)
        return run

    @routes.get("/")
    async def dashboard(request):
        from polyaxon_tpu.api.dashboard import DASHBOARD_HTML

        return web.Response(text=DASHBOARD_HTML, content_type="text/html")

    @routes.get(f"{API_PREFIX}/status")
    async def status(request):
        # Health surface (reference checks/ + api/index/status.py). The
        # endpoint stays open for probes; operational task counters ride
        # the payload only for admins (or when auth is off entirely) —
        # task names and failure volumes are internal data.
        from polyaxon_tpu.checks import run_health_checks, task_counter_snapshot

        report = run_health_checks(orch)
        required = request.get("auth_required", True)
        show_counters = not required
        if required:
            resolved = _resolve_actor(request)
            show_counters = resolved is not None and resolved[1] == "admin"
        if show_counters:
            counters = task_counter_snapshot(orch)
            if counters:
                report["task_counters"] = counters
        code = 200 if report["healthy"] else 503
        return web.json_response(report, status=code)

    @routes.get("/metrics")
    async def prometheus_metrics(request):
        # Prometheus scrape surface over the control plane's own stats
        # backend (task throughput/latency histograms, watcher timings).
        # Auth-gated like the rest of the API — scrape configs carry
        # ``authorization: {credentials: <token>}``; only an in-memory
        # backend has state to export (statsd/noop render a comment).
        snapshot_fn = getattr(orch.stats, "snapshot", None)
        if snapshot_fn is None:
            body = f"# stats backend {type(orch.stats).__name__} keeps no in-process registry\n"
        else:
            # The renderer only reads counters/gauges/histograms, so skip
            # the raw timing-window copy — by far the largest lock-held
            # cost of a scrape (up to 512 floats per key).
            try:
                snap = snapshot_fn(include_timings=False)
            except TypeError:  # duck-typed stand-in without the kwarg
                snap = snapshot_fn()
            body = render_prometheus(snap, labels={"component": "control_plane"})
        # Exposition hygiene: standard process/build gauges render even
        # when the stats backend keeps no registry.
        body += render_standard_gauges(labels={"component": "control_plane"})
        return web.Response(
            body=body.encode("utf-8"),
            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE},
        )

    # -- runs CRUD + actions --------------------------------------------------
    @routes.post(f"{API_PREFIX}/runs")
    async def create_run(request):
        body = await request.json()
        _require_project(request, body.get("project", "default"))
        try:
            run = orch.submit(
                body.get("spec") or body.get("content"),
                project=body.get("project", "default"),
                name=body.get("name"),
                tags=body.get("tags"),
                actor=request.get("actor"),
            )
        except PolyaxonTPUError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(run_to_dict(run), status=201)

    @routes.get(f"{API_PREFIX}/runs")
    async def list_runs(request):
        q = request.rel_url.query
        statuses = q.getall("status", []) or None
        limit = _int_param(request, "limit", 100)
        offset = _int_param(request, "offset", 0)
        # DSL conditions on real columns push down to SQL WHERE; only
        # JSON-payload conditions (metric.*, declarations.*, tags) filter
        # in process — and only those force fetch-then-paginate.
        from polyaxon_tpu.query import (
            QueryError,
            apply_query,
            compile_to_sql,
            filters_archived,
            parse_query,
        )

        try:
            conds = parse_query(q.get("q"))
            clauses, params, residual = compile_to_sql(conds)
        except QueryError as e:
            return web.json_response({"error": str(e)}, status=400)
        # In-process filters (residual DSL conditions, project ACLs under
        # auth) must see the FULL result set before pagination — slicing
        # first would return empty/short pages while accessible runs sit
        # beyond them.  Admins skip the ACL fetch-all: the filter is a
        # no-op for them and SQL LIMIT/OFFSET is exact.
        post_filter = bool(residual) or (
            request.get("auth_required", False)
            and request.get("role") != "admin"
        )
        # ?archived=true → archived only; ?archived=all → both; default =
        # live runs only (the reference's default model manager).  A query
        # that filters on `archived:` itself takes over — stacking the
        # default exclusion under it would contradict the user's filter.
        archived_q = (q.get("archived") or "").lower()
        archived = {"true": True, "1": True, "all": None}.get(archived_q, False)
        if filters_archived(conds):
            archived = None
        runs = reg.list_runs(
            kind=q.get("kind"),
            project=q.get("project"),
            group_id=_int_param(request, "group_id"),
            pipeline_id=_int_param(request, "pipeline_id"),
            statuses=statuses,
            extra_where=(clauses, params) if clauses else None,
            limit=None if post_filter else limit,
            offset=0 if post_filter else offset,
            archived=archived,
        )
        if residual:
            runs = apply_query(runs, conditions=residual)
        # Owned projects are invisible to outsiders, not just read-only
        # (reference private projects). One ACL decision per project name.
        decided: Dict[str, bool] = {}
        visible = []
        for r in runs:
            if r.project not in decided:
                decided[r.project] = not _project_denied(request, r.project)
            if decided[r.project]:
                visible.append(r)
        if post_filter:
            visible = visible[offset : offset + limit]
        return web.json_response({"results": [run_to_dict(r) for r in visible]})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}")
    async def get_run(request):
        run = _run_or_404(request)
        payload = run_to_dict(run)
        # Live stall/straggler roll-up — detail view only, so list views
        # stay a single-table read.  A finished run cannot be stalled: its
        # progress rows age out, but the alarm must not outlive the gang
        # (the heartbeat stays "fresh" for heartbeat_fresh_s after exit).
        status = anomaly_status(reg, run.id)
        if run.is_done:
            status.update(stalled=False, stall_age_s=0.0, stragglers=[])
        payload["anomalies"] = status
        # Goodput/MFU roll-up block (no timeline — /goodput serves that).
        payload["goodput"] = goodput_status(reg, run.id, timeline_limit=0)
        # Alert roll-up: current lifecycle state per rule + counts, so the
        # detail view answers "is anything paging on this run" directly.
        alert_rows = reg.get_alerts(run.id)
        payload["alerts"] = {
            "firing": sum(1 for r in alert_rows if r["state"] == "firing"),
            "pending": sum(1 for r in alert_rows if r["state"] == "pending"),
            "resolved": sum(1 for r in alert_rows if r["state"] == "resolved"),
            "results": alert_rows,
        }
        # SLO roll-up: the run's declared error budget with both burn
        # windows and budget remaining — None unless the run declares
        # ``alert.slo_burn_rate.target`` and the metric store is live.
        metrics_store = getattr(orch, "metrics", None)
        if metrics_store is not None:
            try:
                payload["slo"] = run_slo_status(
                    RuleContext(reg, run, stats=orch.stats, metrics=metrics_store)
                )
            except Exception:
                logger.warning("SLO roll-up failed for run %d", run.id, exc_info=True)
                payload["slo"] = None
        else:
            payload["slo"] = None
        # Remediation roll-up: what the control plane DID about trouble
        # (checkpoint-now, resume-from-step, eviction) — the action half
        # of the alerts block above.
        rem_rows = reg.get_remediations(run.id)
        payload["remediations"] = {
            "total": len(rem_rows),
            "open": sum(
                1 for r in rem_rows if r["status"] in RemediationStatus.OPEN
            ),
            "results": rem_rows,
        }
        return web.json_response(payload)

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/stop")
    async def stop_run(request):
        run = _run_or_404(request)
        orch.stop_run(run.id, actor=request.get("actor"))
        return web.json_response({"ok": True})

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/restart")
    async def restart_run(request):
        run = _run_or_404(request)
        clone = orch.clone_run(run.id, strategy="restart", actor=request.get("actor"))
        return web.json_response(run_to_dict(clone), status=201)

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/resume")
    async def resume_run(request):
        run = _run_or_404(request)
        clone = orch.clone_run(run.id, strategy="resume", actor=request.get("actor"))
        return web.json_response(run_to_dict(clone), status=201)

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/copy")
    async def copy_run(request):
        run = _run_or_404(request)
        clone = orch.clone_run(run.id, strategy="copy", actor=request.get("actor"))
        return web.json_response(run_to_dict(clone), status=201)

    # -- chart views (reference ChartViewModel + its experiment/group views) --
    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/chart_views")
    async def create_chart_view(request):
        run = _run_or_404(request)
        body = await request.json()
        name = (body.get("name") or "").strip()
        charts = body.get("charts")
        if not name or not isinstance(charts, list) or not all(
            isinstance(c, str) for c in charts
        ):
            return web.json_response(
                {
                    "error": "a chart view needs a 'name' and 'charts' "
                    "(a list of metric names)"
                },
                status=400,
            )
        view = reg.create_chart_view(
            run.id,
            name,
            charts,
            meta=body.get("meta"),
            owner=request.get("actor"),
        )
        _audit(request, EventTypes.CHART_VIEW_CREATED, run_id=run.id, name=name)
        return web.json_response(view, status=201)

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/chart_views")
    async def list_chart_views(request):
        run = _run_or_404(request)
        return web.json_response({"results": reg.list_chart_views(run.id)})

    @routes.delete(f"{API_PREFIX}/runs/{{run_id}}/chart_views/{{view_id}}")
    async def delete_chart_view(request):
        run = _run_or_404(request)
        try:
            view_id = int(request.match_info["view_id"])
        except ValueError:
            raise _json_error(web.HTTPNotFound, "no such chart view")
        if not reg.delete_chart_view(run.id, view_id):
            raise _json_error(web.HTTPNotFound, "no such chart view")
        _audit(request, EventTypes.CHART_VIEW_DELETED, run_id=run.id, view_id=view_id)
        return web.json_response({"ok": True})

    # -- archival + deletion (reference api/archives/ + delete views) ---------
    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/archive")
    async def archive_run(request):
        run = _run_or_404(request)
        orch.archive_run(run.id, actor=request.get("actor"))
        return web.json_response(run_to_dict(reg.get_run(run.id)))

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/restore")
    async def restore_run(request):
        run = _run_or_404(request)
        orch.restore_run(run.id, actor=request.get("actor"))
        return web.json_response(run_to_dict(reg.get_run(run.id)))

    @routes.delete(f"{API_PREFIX}/runs/{{run_id}}")
    async def delete_run(request):
        run = _run_or_404(request)
        deleted = orch.delete_run(run.id, actor=request.get("actor"))
        return web.json_response({"ok": True, "deleted": deleted})

    @routes.get(f"{API_PREFIX}/archives")
    async def list_archives(request):
        """Archived runs, visible-project-filtered (reference archives API)."""
        runs = reg.list_runs(archived=True)
        decided: Dict[str, bool] = {}
        visible = []
        for r in runs:
            if r.project not in decided:
                decided[r.project] = not _project_denied(request, r.project)
            if decided[r.project]:
                visible.append(r)
        return web.json_response({"results": [run_to_dict(r) for r in visible]})

    # -- sub-resources --------------------------------------------------------
    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/statuses")
    async def get_statuses(request):
        run = _run_or_404(request)
        return web.json_response({"results": reg.get_statuses(run.id)})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/metrics")
    async def get_metrics(request):
        run = _run_or_404(request)
        since = _int_param(request, "since_id", 0)
        return web.json_response({"results": reg.get_metrics(run.id, since_id=since)})

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/metrics")
    async def post_metrics(request):
        # In-job metric ingestion (reference ExperimentMetricListView).
        run = _run_or_404(request)
        body = await request.json()
        reg.add_metric(run.id, body.get("values", {}), step=body.get("step"))
        return web.json_response({"ok": True}, status=201)

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/logs")
    async def get_logs(request):
        run = _run_or_404(request)
        rows = reg.get_logs(
            run.id,
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        return web.json_response({"results": rows})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/timeline")
    async def get_timeline(request):
        # Cross-process gang timeline: tracer spans reported by every
        # worker, assembled into Chrome-trace JSON (load in Perfetto or
        # chrome://tracing; pid = gang process id).
        run = _run_or_404(request)
        spans = reg.get_spans(run.id, since_id=_int_param(request, "since_id", 0))
        fmt = request.rel_url.query.get("format", "chrome")
        if fmt == "spans":
            # Raw registry rows for programmatic consumers.
            return web.json_response({"results": spans})
        if fmt != "chrome":
            return web.json_response(
                {"error": f"unknown timeline format {fmt!r}"}, status=400
            )
        return web.json_response(chrome_trace(spans))

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/goodput")
    async def get_goodput(request):
        # Per-run utilization ledger: gang-wide wall-clock decomposition
        # (buckets sum to wall time), goodput ratio, live MFU timeline,
        # compile/HBM telemetry — plus the raw per-process ledger rows
        # with since_id/limit paging for pollers.
        run = _run_or_404(request)
        status = goodput_status(reg, run.id)
        rows = reg.get_utilization(
            run.id,
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        status["results"] = rows
        return web.json_response(status)

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/anomalies")
    async def get_anomalies(request):
        # Incident timeline (stall/straggler/crash rows from the detector
        # and the workers' flight recorders) + the live detector roll-up.
        run = _run_or_404(request)
        rows = reg.get_anomalies(
            run.id,
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        status = anomaly_status(reg, run.id)
        if run.is_done:
            # The incident rows are history; the live roll-up is not —
            # a finished run cannot be currently stalled or straggling.
            status.update(stalled=False, stall_age_s=0.0, stragglers=[])
        return web.json_response({"results": rows, "status": status})

    # -- alerts (rule-engine lifecycle feed) ----------------------------------
    def _visible_alert_rows(request, rows):
        """Project-ACL filter for cluster-wide alert rows: one decision per
        run, same invisibility rule as the run list."""
        decided: Dict[int, bool] = {}
        out = []
        for row in rows:
            rid = row["run_id"]
            if rid not in decided:
                try:
                    run = reg.get_run(rid)
                    decided[rid] = not _project_denied(request, run.project)
                except PolyaxonTPUError:
                    decided[rid] = False
            if decided[rid]:
                out.append(row)
        return out

    @routes.get(f"{API_PREFIX}/alerts")
    async def list_alerts(request):
        # Cluster-wide alert feed: latest state per (run, rule), pageable
        # by transition (?since_id=), filterable by state/severity/rule/run.
        rows = reg.get_alerts(
            run_id=_int_param(request, "run_id"),
            state=request.query.get("state"),
            severity=request.query.get("severity"),
            rule=request.query.get("rule"),
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        engine = getattr(orch, "alerts", None)
        return web.json_response(
            {
                "results": _visible_alert_rows(request, rows),
                "engine": engine.status() if engine is not None else None,
            }
        )

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/alerts")
    async def get_run_alerts(request):
        run = _run_or_404(request)
        rows = reg.get_alerts(
            run.id,
            state=request.query.get("state"),
            severity=request.query.get("severity"),
            rule=request.query.get("rule"),
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        return web.json_response({"results": rows})

    # -- remediations (the detection→action audit trail) ----------------------
    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/remediations")
    async def get_run_remediations(request):
        run = _run_or_404(request)
        rows = reg.get_remediations(
            run.id,
            action=request.query.get("action"),
            status=request.query.get("status"),
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        engine = getattr(orch, "remediation", None)
        return web.json_response(
            {
                "results": rows,
                "engine": engine.status() if engine is not None else None,
            }
        )

    # -- metric history (registry TSDB: scrape → rollup → query) --------------
    #: Query params with reserved meaning on /metrics/query — everything
    #: else is treated as a label matcher (?fleet=prod&run=12).
    _QUERY_RESERVED = {"series", "name", "since", "until", "step", "agg", "limit"}

    def _metric_store(request):
        store = getattr(orch, "metrics", None)
        if store is None:
            raise _json_error(
                web.HTTPServiceUnavailable,
                "metric history disabled (POLYAXON_TPU_TSDB_ENABLED=false)",
            )
        return store

    def _require_metric_access(request, store, base: str, matchers) -> None:
        """Project ACL for the in-memory series: a run-labeled query is
        gated by that run's project; aggregating run-labeled series
        *across* runs (no ``run`` matcher) is admin-only, because the
        result would blend projects the caller may not see.  Cluster
        series (router/control-plane) are visible to any authed caller."""
        run_label = matchers.get("run")
        if run_label is not None:
            try:
                target = reg.get_run(int(run_label))
            except (ValueError, PolyaxonTPUError):
                raise _json_error(web.HTTPNotFound, f"run {run_label!r} not found")
            _require_project(request, target.project)
            return
        if request.get("role") == "admin":
            return
        for key in store.series_keys(base):
            _sbase, labels = split_labeled_key(key)
            if "run" in labels:
                raise _json_error(
                    web.HTTPForbidden,
                    f"series {base!r} is run-labeled: pass ?run=<id> "
                    "(cross-run aggregation is admin-only)",
                )

    @routes.get(f"{API_PREFIX}/metrics/query")
    async def metrics_query(request):
        store = _metric_store(request)
        name = request.query.get("series") or request.query.get("name")
        if not name:
            raise _json_error(
                web.HTTPBadRequest, "query param 'series' is required"
            )
        base, inline = split_labeled_key(name)
        if not store.has_series(base):
            raise _json_error(web.HTTPBadRequest, f"unknown series {base!r}")
        matchers = {
            k: v for k, v in request.query.items() if k not in _QUERY_RESERVED
        }
        _require_metric_access(request, store, base, {**inline, **matchers})
        max_points = knob_int("POLYAXON_TPU_TSDB_QUERY_MAX_POINTS")
        limit = _int_param(request, "limit", max_points)
        limit = max(1, min(limit, max_points))
        agg = request.query.get("agg", "avg")
        step = _float_param(request, "step")
        try:
            points = store.query(
                name,
                matchers=matchers,
                since=_float_param(request, "since"),
                until=_float_param(request, "until"),
                step=step,
                agg=agg,
                limit=limit,
            )
        except ValueError as exc:
            raise _json_error(web.HTTPBadRequest, str(exc))
        return web.json_response(
            {
                "series": name,
                "matchers": matchers,
                "agg": agg,
                "step": step,
                "points": points,
            }
        )

    @routes.get(f"{API_PREFIX}/metrics/series")
    async def metrics_series(request):
        store = _metric_store(request)
        return web.json_response(
            {"results": store.series_names(), "store": store.status()}
        )

    @routes.get(f"{API_PREFIX}/metrics/baselines")
    async def metrics_baselines(request):
        project = request.query.get("project", "default")
        _require_project(request, project)
        rows = reg.get_metric_baselines(project, kind=request.query.get("kind"))
        return web.json_response({"results": rows})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/metrics/history")
    async def run_metric_history(request):
        # Persisted per-run samples (raw + rollups) — survives control-plane
        # restarts, unlike the in-memory query window above.
        run = _run_or_404(request)
        agg = request.query.get("agg", "raw")
        rows = reg.get_metric_samples(
            run_id=run.id,
            name=request.query.get("series") or request.query.get("name"),
            agg=None if agg == "all" else agg,
            since=_float_param(request, "since"),
            until=_float_param(request, "until"),
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        return web.json_response({"results": rows})

    # -- on-demand device profiling (run command bus) -------------------------
    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/profile")
    async def post_profile(request):
        # Trigger a gang-wide windowed capture (xplane + device memory +
        # HLO) over the command bus.  A finished run answers immediately
        # with a typed EXPIRED command row — never a hang.
        run = _run_or_404(request)
        body = await request.json() if request.can_read_body else {}
        num_steps = body.get("num_steps")
        duration_s = body.get("duration_s")
        processes = body.get("processes")
        if processes is not None and (
            not isinstance(processes, list)
            or not all(isinstance(p, int) for p in processes)
        ):
            return web.json_response(
                {"error": "'processes' must be a list of gang process ids"},
                status=400,
            )
        try:
            cmd = await asyncio.to_thread(
                orch.request_profile,
                run.id,
                num_steps=int(num_steps) if num_steps is not None else None,
                duration_s=float(duration_s) if duration_s is not None else None,
                processes=processes,
                actor=request.get("actor"),
            )
        except (TypeError, ValueError) as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response(cmd, status=202)

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/profiles")
    async def list_profiles(request):
        # Capture index: every profile command (bus lifecycle rollup) plus
        # the per-host capture rows the watcher ingested so far.
        run = _run_or_404(request)
        commands = reg.get_commands(
            run.id,
            kind="profile",
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        captures = reg.get_captures(run.id)
        by_capture: Dict[str, list] = {}
        for row in captures:
            by_capture.setdefault(row["capture_id"], []).append(row)
        results = []
        for cmd in commands:
            results.append(
                {
                    **cmd,
                    "capture_id": cmd["uuid"],
                    "captures": by_capture.get(cmd["uuid"], []),
                }
            )
        return web.json_response({"results": results})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/profiles/{{capture_id}}")
    async def get_profile(request):
        # Per-capture manifest: bus command state, per-host capture rows
        # (status + artifact keys fetchable via the artifacts API), and a
        # merged chrome-trace of the gang's span ring over the capture
        # window (?format=chrome for the raw trace document).
        run = _run_or_404(request)
        capture_id = request.match_info["capture_id"]
        cmd = reg.get_command(capture_id)
        if cmd is None or cmd["run_id"] != run.id or cmd["kind"] != "profile":
            raise _json_error(web.HTTPNotFound, "no such capture")
        captures = reg.get_captures(run.id, capture_id=capture_id)
        window_start = min(
            (c["started_at"] for c in captures if c.get("started_at")),
            default=None,
        )
        window_end = max(
            (c["finished_at"] for c in captures if c.get("finished_at")),
            default=None,
        )
        trace = None
        if window_start is not None:
            end = window_end if window_end is not None else float("inf")
            spans = [
                s
                for s in reg.get_spans(run.id)
                if s["start"] < end
                and s["start"] + (s.get("duration") or 0.0) >= window_start
            ]
            trace = chrome_trace(spans)
        fmt = request.rel_url.query.get("format", "manifest")
        if fmt == "chrome":
            if trace is None:
                return web.json_response(
                    {"error": "capture has no span window yet"}, status=404
                )
            return web.json_response(trace)
        if fmt != "manifest":
            return web.json_response(
                {"error": f"unknown profile format {fmt!r}"}, status=400
            )
        return web.json_response(
            {
                "capture_id": capture_id,
                "command": cmd,
                "captures": captures,
                "window": {"start": window_start, "end": window_end},
                "trace": trace,
            }
        )

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/heartbeat")
    async def post_heartbeat(request):
        run = _run_or_404(request)
        reg.ping_heartbeat(run.id)
        return web.json_response({"ok": True})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/iterations")
    async def get_iterations(request):
        # Sweep iteration state (reference ExperimentGroupIteration rows):
        # hyperband brackets / BO observation rounds, per iteration.
        run = _run_or_404(request)
        return web.json_response({"results": reg.get_iterations(run.id)})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/processes")
    async def get_processes(request):
        run = _run_or_404(request)
        return web.json_response({"results": reg.get_processes(run.id)})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/artifacts")
    async def list_artifacts(request):
        # Outputs browsing (reference stores-managed outputs endpoints):
        # local run dir first, artifact store as the durable fallback.
        run = _run_or_404(request)
        # Store listing may shell out to gsutil — keep it off the event loop.
        results = await asyncio.to_thread(orch.list_artifacts, run.id)
        return web.json_response({"results": results})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/artifacts/{{key:.+}}")
    async def get_artifact(request):
        run = _run_or_404(request)
        key = request.match_info["key"]
        local = orch.artifact_local_path(run.id, key)
        if local is not None:
            return web.FileResponse(local)  # sendfile, zero-copy
        # Store fallback: the open (gsutil cp to a temp file) blocks for the
        # transfer — keep it off the event loop — then stream chunks so a
        # multi-GB checkpoint never sits in control-plane memory.
        f = await asyncio.to_thread(orch.open_artifact, run.id, key)
        if f is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"artifact {key!r} not found"}),
                content_type="application/json",
            )
        resp = web.StreamResponse(
            headers={"Content-Type": "application/octet-stream"}
        )
        await resp.prepare(request)
        try:
            while True:
                chunk = await asyncio.to_thread(f.read, 1 << 20)
                if not chunk:
                    break
                await resp.write(chunk)
        finally:
            f.close()
        await resp.write_eof()
        return resp

    # -- projects (reference api/projects/) ------------------------------------
    @routes.post(f"{API_PREFIX}/projects")
    async def create_project(request):
        body = await request.json()
        # Under auth the creator owns the project (reference ``ownership/``);
        # an explicit body owner — including null for a deliberately open
        # project — overrides; open mode (anonymous admin) stays ownerless.
        actor = request.get("actor")
        is_admin = request.get("role") == "admin"
        if "owner" in body:
            owner = body["owner"]
        else:
            owner = actor if actor not in (None, "anonymous") else None
        if not is_admin:
            # Non-admins may only own projects themselves (no assigning
            # ownership to third parties)...
            if owner not in (None, actor):
                raise _json_error(
                    web.HTTPForbidden, "only admins may assign another owner"
                )
            # ...and may not CLAIM a run-implied project others already use
            # (registering 'ml' with an owner would 403 every existing
            # user of it — an ownership takeover).
            if owner is not None and reg.get_project(body.get("name", "")):
                raise _json_error(
                    web.HTTPForbidden,
                    "project already has runs; an admin must register its "
                    "ownership",
                )
        try:
            project = reg.create_project(
                body["name"], description=body.get("description"), owner=owner
            )
        except KeyError:
            return web.json_response({"error": "project needs a name"}, status=400)
        except PolyaxonTPUError as e:
            return web.json_response({"error": str(e)}, status=400)
        _audit(request, EventTypes.PROJECT_CREATED, project=project["name"])
        return web.json_response(project, status=201)

    @routes.get(f"{API_PREFIX}/projects")
    async def list_projects(request):
        results = [
            p
            for p in reg.list_projects()
            if not _project_denied(request, p["name"])
        ]
        return web.json_response({"results": results})

    @routes.get(f"{API_PREFIX}/projects/{{name}}")
    async def get_project(request):
        _require_project(request, request.match_info["name"])
        project = reg.get_project(request.match_info["name"])
        if project is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such project"}),
                content_type="application/json",
            )
        return web.json_response(project)

    @routes.delete(f"{API_PREFIX}/projects/{{name}}")
    async def delete_project(request):
        _require_project_owner(request, request.match_info["name"])
        try:
            # Orchestrator-level: cascades to the project's archived runs
            # and GCs their artifacts; refuses while live runs exist.
            removed = orch.delete_project(
                request.match_info["name"], actor=request.get("actor")
            )
        except PolyaxonTPUError as e:
            return web.json_response({"error": str(e)}, status=400)
        if not removed:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such project"}),
                content_type="application/json",
            )
        return web.json_response({"ok": True})

    @routes.post(f"{API_PREFIX}/projects/{{name}}/collaborators")
    async def add_collaborator(request):
        name = request.match_info["name"]
        _require_project_owner(request, name)
        body = await request.json()
        username = body.get("username")
        if not username:
            return web.json_response(
                {"error": "collaborator needs a username"}, status=400
            )
        if reg.get_project(name) is None:
            raise _json_error(web.HTTPNotFound, "no such project")
        reg.add_collaborator(name, username)
        _audit(
            request, EventTypes.PROJECT_SHARED, project=name, username=username
        )
        return web.json_response(reg.get_project(name), status=201)

    @routes.delete(f"{API_PREFIX}/projects/{{name}}/collaborators/{{username}}")
    async def remove_collaborator(request):
        name = request.match_info["name"]
        _require_project_owner(request, name)
        if not reg.remove_collaborator(name, request.match_info["username"]):
            raise _json_error(web.HTTPNotFound, "not a collaborator")
        _audit(
            request,
            EventTypes.PROJECT_UNSHARED,
            project=name,
            username=request.match_info["username"],
        )
        return web.json_response({"ok": True})

    # -- CI (reference api/ci/: per-project trigger config) --------------------
    @routes.put(f"{API_PREFIX}/projects/{{name}}/ci")
    async def set_ci(request):
        name = request.match_info["name"]
        _require_project_owner(request, name)
        body = await request.json()
        spec = body.get("spec") or body.get("content")
        if not spec:
            return web.json_response(
                {"error": "CI needs a 'spec' to run on new code"}, status=400
            )
        try:
            ci = orch.set_project_ci(name, spec, actor=request.get("actor"))
        except PolyaxonTPUError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(ci, status=201)

    @routes.get(f"{API_PREFIX}/projects/{{name}}/ci")
    async def get_ci(request):
        name = request.match_info["name"]
        _require_project(request, name)
        ci = reg.get_project_ci(name)
        if ci is None:
            raise _json_error(web.HTTPNotFound, f"no CI configured for {name!r}")
        return web.json_response(ci)

    @routes.delete(f"{API_PREFIX}/projects/{{name}}/ci")
    async def delete_ci(request):
        name = request.match_info["name"]
        _require_project_owner(request, name)
        if not orch.delete_project_ci(name, actor=request.get("actor")):
            raise _json_error(web.HTTPNotFound, f"no CI configured for {name!r}")
        return web.json_response({"ok": True})

    @routes.post(f"{API_PREFIX}/projects/{{name}}/ci/trigger")
    async def trigger_ci(request):
        """Manual code-push check (the reference repo-upload trigger).
        ``context`` is a SERVER-side directory — owner/admin only, like
        every surface that reads the service host's filesystem."""
        name = request.match_info["name"]
        _require_project_owner(request, name)
        body = await request.json() if request.can_read_body else {}
        try:
            run = orch.trigger_ci(
                name, context=body.get("context"), actor=request.get("actor")
            )
        except PolyaxonTPUError as e:
            return web.json_response({"error": str(e)}, status=400)
        if run is None:
            return web.json_response({"triggered": False})
        return web.json_response(
            {"triggered": True, "run": run_to_dict(run)}, status=201
        )

    # -- saved searches (reference api/searches/) -------------------------------
    @routes.post(f"{API_PREFIX}/searches")
    async def create_search(request):
        from polyaxon_tpu.query import QueryError, compile_to_sql, parse_query

        body = await request.json()
        try:
            # Validate at save time — a stored search must never 400 later.
            compile_to_sql(parse_query(body["query"]))
            search = reg.create_search(
                body["name"], body["query"], owner=request.get("actor")
            )
        except KeyError:
            return web.json_response(
                {"error": "search needs name and query"}, status=400
            )
        except (QueryError, PolyaxonTPUError) as e:
            return web.json_response({"error": str(e)}, status=400)
        _audit(request, EventTypes.SEARCH_CREATED, search=search["name"])
        return web.json_response(search, status=201)

    @routes.get(f"{API_PREFIX}/searches")
    async def list_searches(request):
        return web.json_response({"results": reg.list_searches()})

    @routes.delete(f"{API_PREFIX}/searches/{{name}}")
    async def delete_search(request):
        if not reg.delete_search(request.match_info["name"]):
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such search"}),
                content_type="application/json",
            )
        _audit(request, EventTypes.SEARCH_DELETED, search=request.match_info["name"])
        return web.json_response({"ok": True})

    @routes.get(f"{API_PREFIX}/searches/{{name}}/runs")
    async def execute_search(request):
        from polyaxon_tpu.query import apply_query, compile_to_sql, parse_query

        search = reg.get_search(request.match_info["name"])
        if search is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such search"}),
                content_type="application/json",
            )
        from polyaxon_tpu.query import filters_archived

        search_conds = parse_query(search["query"])
        clauses, params, residual = compile_to_sql(search_conds)
        limit = _int_param(request, "limit", 100)
        runs = reg.list_runs(
            extra_where=(clauses, params) if clauses else None,
            limit=None if residual else limit,
            # A search over `archived:` owns that dimension; otherwise
            # the live-only default applies.
            archived=None if filters_archived(search_conds) else False,
        )
        if residual:
            runs = apply_query(runs, conditions=residual)[:limit]
        return web.json_response({"results": [run_to_dict(r) for r in runs]})

    # -- bookmarks (reference api/bookmarks/) ----------------------------------
    def _bookmark_owner(request) -> str:
        # '' == anonymous, shared with local-CLI bookmarks on the same
        # base dir; authenticated users get per-user bookmarks.
        actor = request.get("actor")
        return "" if actor in (None, "anonymous") else actor

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/bookmark")
    async def add_bookmark(request):
        run = _run_or_404(request)
        reg.add_bookmark(run.id, owner=_bookmark_owner(request))
        _audit(request, EventTypes.BOOKMARK_ADDED, run_id=run.id)
        return web.json_response({"ok": True}, status=201)

    @routes.delete(f"{API_PREFIX}/runs/{{run_id}}/bookmark")
    async def remove_bookmark(request):
        run = _run_or_404(request)
        if not reg.remove_bookmark(run.id, owner=_bookmark_owner(request)):
            raise web.HTTPNotFound(
                text=json.dumps({"error": "not bookmarked"}),
                content_type="application/json",
            )
        _audit(request, EventTypes.BOOKMARK_REMOVED, run_id=run.id)
        return web.json_response({"ok": True})

    @routes.get(f"{API_PREFIX}/bookmarks")
    async def list_bookmarks(request):
        runs = reg.list_bookmarked_runs(owner=_bookmark_owner(request))
        return web.json_response({"results": [run_to_dict(r) for r in runs]})

    # -- runtime options (reference options API / cluster settings) -----------
    @routes.get(f"{API_PREFIX}/options")
    async def list_options(request):
        # The full typed registry with resolved values. Admin-gated: values
        # include operational secrets-adjacent settings (hosts, key paths).
        _require_admin(request)
        from polyaxon_tpu.conf.options import options_payload

        return web.json_response({"results": options_payload(orch.conf)})

    @routes.put(f"{API_PREFIX}/options/{{key}}")
    async def set_option(request):
        _require_admin(request)
        from polyaxon_tpu.conf.options import display_value, option_by_key
        from polyaxon_tpu.conf.service import ConfError

        key = request.match_info["key"]
        opt = option_by_key(key)
        if opt is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"unknown option {key!r}"}),
                content_type="application/json",
            )
        try:
            body = await request.json()
            orch.conf.set(key, body["value"])
        except (KeyError, TypeError, ValueError, ConfError) as e:
            # Covers malformed JSON bodies too (JSONDecodeError is a
            # ValueError) — bad input is a 400, never a 500.
            return web.json_response({"error": str(e)}, status=400)
        _audit(request, "platform.option_set", key=key)
        return web.json_response(
            {"key": key, "value": display_value(opt, orch.conf.get(key))}
        )

    @routes.get(f"{API_PREFIX}/activities")
    async def list_activities(request):
        # The audit feed (reference activitylogs/): who did what, when.
        # Admin-gated — it carries usernames and every actor's actions,
        # the same data GET /users restricts.
        _require_admin(request)
        rows = reg.get_activities(
            event_type=request.rel_url.query.get("event_type"),
            limit=_int_param(request, "limit", 100),
        )
        return web.json_response({"results": rows})

    # -- devices (accelerator inventory) --------------------------------------
    @routes.get(f"{API_PREFIX}/devices")
    async def list_devices(request):
        # Cluster inventory (reference nodes API, ``api/nodes/``).
        return web.json_response({"results": reg.list_devices()})

    @routes.post(f"{API_PREFIX}/devices")
    async def register_device(request):
        body = await request.json()
        try:
            device = orch.register_device(
                body["name"],
                body["accelerator"],
                int(body["chips"]),
                num_hosts=int(body.get("num_hosts", 1)),
                actor=request.get("actor"),
            )
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response(
                {"error": f"device needs name/accelerator/chips: {e}"}, status=400
            )
        return web.json_response(device, status=201)

    @routes.delete(f"{API_PREFIX}/devices/{{name}}")
    async def remove_device(request):
        removed = reg.remove_device(request.match_info["name"])
        if not removed:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such device"}),
                content_type="application/json",
            )
        return web.json_response({"ok": True})

    # -- versions (reference api/versions/: cli/platform compatibility) -------
    @routes.get(f"{API_PREFIX}/version")
    async def version(request):
        from polyaxon_tpu.version import __version__

        import jax as _jax

        return web.json_response(
            {
                "platform": __version__,
                # Clients older than this may speak an incompatible spec
                # dialect (the reference's min/latest CLI gate).
                "min_cli": "0.1.0",
                "jax": _jax.__version__,
            }
        )

    # -- usage analytics (reference tracker/, kept in-house) -------------------
    @routes.get(f"{API_PREFIX}/analytics")
    async def analytics(request):
        """Platform usage rollup: event counts per day + entity summary.
        Admin-only — aggregate usage is operator data."""
        _require_admin(request)
        from polyaxon_tpu.tracker import usage_rollup

        days = _int_param(request, "days", 14)
        return web.json_response(usage_rollup(reg, days=days))

    # -- query vocabulary (dashboard autocomplete) ----------------------------
    @routes.get(f"{API_PREFIX}/query/fields")
    async def query_fields(request):
        """The completable query-DSL vocabulary: plain columns, the
        metric./declarations. keys present in current runs, and known
        status values.  Parity: the reference client's autocomplete
        component fed from its query spec (``client/src/components/
        autocomplete/``); here the backend that owns the grammar
        (``query/builder.py``) serves it."""
        from polyaxon_tpu.lifecycles import StatusOptions
        from polyaxon_tpu.query.builder import _FIELDS

        metric_keys, param_keys = set(), set()
        decided: Dict[str, bool] = {}
        for r in reg.list_runs(limit=500, archived=False):
            # Same per-project ACL as every listing surface: keys harvested
            # from restricted projects must not leak into completions.
            if r.project not in decided:
                decided[r.project] = not _project_denied(request, r.project)
            if not decided[r.project]:
                continue
            metric_keys.update(
                k for k in r.last_metric if not k.startswith("sys/")
            )
            param_keys.update(r.spec_data.get("declarations", {}) or {})
        statuses = sorted(
            v
            for k, v in vars(StatusOptions).items()
            if k.isupper() and isinstance(v, str)
        )
        return web.json_response(
            {
                "fields": sorted(_FIELDS) + ["archived", "tags"],
                "metric_keys": sorted(metric_keys),
                "param_keys": sorted(param_keys),
                "statuses": statuses,
                "ops": [":", ":~", ":>", ":>=", ":<", ":<=", "|", ".."],
            }
        )

    # -- live streaming (WS) --------------------------------------------------
    ws_tails_active = [0]  # closure-shared gauge source across tail handlers

    async def _ws_tail(request, fetch, poll: float = 0.5, scoped: bool = True):
        """Generic WS tail loop: push new rows until the run is done.

        ``scoped=False`` is the cluster-feed variant (no run in the path):
        ``fetch`` gets None for the run id and the loop never sees a
        terminal run, so it streams until the client hangs up.

        Fan-out is batch-capped (``POLYAXON_TPU_WS_TAIL_MAX_BATCH``): a
        cold tail over a huge history drains in bounded bursts — the
        cursor only advances over rows actually sent, so the remainder is
        re-fetched immediately (no poll sleep while a backlog stands).
        ``ws_tail_backlog_rows`` exports the standing depth; a client that
        hangs up mid-drain counts its unsent rows as drops."""
        run = _run_or_404(request) if scoped else None
        stats = orch.stats
        max_batch = knob_int("POLYAXON_TPU_WS_TAIL_MAX_BATCH")
        # Select ONLY the fixed ``bearer`` name (browsers abort the
        # handshake if the server selects none of the offered protocols,
        # so the dashboard offers ['bearer', 'bearer.<token>']).  Echoing
        # the client's full offer would reflect the bearer.<token> auth
        # subprotocol — the secret — into the Sec-WebSocket-Protocol
        # RESPONSE header, where proxies and devtools log it.
        ws = web.WebSocketResponse(heartbeat=30, protocols=("bearer",))
        await ws.prepare(request)
        cursor = 0
        backlog = 0
        ws_tails_active[0] += 1
        stats.gauge("ws_tail_active", float(ws_tails_active[0]))
        try:
            while not ws.closed:
                # The run can be DELETEd out from under a live tail; close
                # the stream cleanly instead of crashing the handler.
                try:
                    rows = fetch(run.id if run else None, cursor)
                    current = reg.get_run(run.id) if run else None
                except PolyaxonTPUError:
                    await ws.send_json({"event": "deleted"})
                    break
                backlog = max(0, len(rows) - max_batch) if max_batch > 0 else 0
                if backlog:
                    rows = rows[:max_batch]
                stats.gauge("ws_tail_backlog_rows", float(backlog))
                for row in rows:
                    cursor = max(cursor, row.get("id", cursor))
                    await ws.send_json(row)
                if rows:
                    stats.incr("ws_tail_rows_total", len(rows))
                if current is not None and current.is_done and not rows:
                    await ws.send_json({"event": "done", "status": current.status})
                    break
                if backlog:
                    continue  # deferred rows re-fetch now, not after poll
                try:
                    msg = await asyncio.wait_for(ws.receive(), timeout=poll)
                    if msg.type in (WSMsgType.CLOSE, WSMsgType.CLOSING, WSMsgType.ERROR):
                        break
                except asyncio.TimeoutError:
                    pass
        finally:
            ws_tails_active[0] -= 1
            stats.gauge("ws_tail_active", float(ws_tails_active[0]))
            if backlog:
                stats.incr("ws_tail_dropped_rows_total", backlog)
            await ws.close()
        return ws

    @routes.get("/ws/v1/runs/{run_id}/logs")
    async def ws_logs(request):
        return await _ws_tail(
            request, lambda rid, cur: reg.get_logs(rid, since_id=cur)
        )

    @routes.get("/ws/v1/runs/{run_id}/metrics")
    async def ws_metrics(request):
        return await _ws_tail(
            request, lambda rid, cur: reg.get_metrics(rid, since_id=cur)
        )

    @routes.get("/ws/v1/alerts")
    async def ws_alerts(request):
        # Cluster-wide live alert tail: every lifecycle transition is a
        # fresh row id, so the generic cursor loop streams exactly the
        # pending→firing→resolved edges (ACL-filtered like the REST feed).
        state = request.query.get("state")
        severity = request.query.get("severity")
        return await _ws_tail(
            request,
            lambda _rid, cur: _visible_alert_rows(
                request,
                reg.get_alerts(since_id=cur, state=state, severity=severity),
            ),
            scoped=False,
        )

    @routes.get("/ws/v1/metrics")
    async def ws_cluster_metrics(request):
        # Cluster-wide live metric tail over the persisted sample feed:
        # every flushed scrape row is a fresh id, so the generic cursor
        # loop streams raw samples as the write-behind lands them.
        # Row visibility mirrors the alert feed: run-labeled samples are
        # project-gated, cluster samples (run_id NULL) are open to any
        # authed caller.
        name = request.query.get("series") or request.query.get("name")
        agg = request.query.get("agg", "raw")
        decided: Dict[int, bool] = {}

        def _visible_metric_rows(rows):
            out = []
            for row in rows:
                rid = row.get("run_id")
                if rid is None:
                    out.append(row)
                    continue
                if rid not in decided:
                    try:
                        target = reg.get_run(rid)
                        decided[rid] = not _project_denied(request, target.project)
                    except PolyaxonTPUError:
                        decided[rid] = False
                if decided[rid]:
                    out.append(row)
            return out

        return await _ws_tail(
            request,
            lambda _rid, cur: _visible_metric_rows(
                reg.get_metric_samples(
                    since_id=cur, name=name, agg=None if agg == "all" else agg
                )
            ),
            scoped=False,
        )

    # -- users (per-user tokens; reference scopes/ + user models) --------------
    def _actor_for_token(token: str):
        import hmac

        if auth_token and hmac.compare_digest(
            token.encode("utf-8", "surrogateescape"), auth_token.encode()
        ):
            return ("root", "admin")
        user = reg.get_user_by_token(token)
        if user is not None:
            return (user["username"], user["role"])
        return None

    def _resolve_actor(request):
        """(actor, role) for the supplied bearer token; None = bad token.

        The shared bootstrap token maps to the 'root' admin; user tokens
        are looked up hashed in the registry.  WS upgrades may carry the
        token as a ``bearer.<token>`` subprotocol instead — the browser
        WebSocket API cannot set an Authorization header, and a ``?token=``
        query param would land the secret in access logs/history (the same
        reason the dashboard login is a form).
        """
        supplied = request.headers.get("Authorization", "")
        if supplied.startswith("Bearer "):
            return _actor_for_token(supplied[len("Bearer "):])
        if request.path.startswith("/ws/"):
            for proto in request.headers.get("Sec-WebSocket-Protocol", "").split(","):
                proto = proto.strip()
                if proto.startswith("bearer."):
                    return _actor_for_token(proto[len("bearer."):])
        return None

    def _require_admin(request):
        if request.get("role") != "admin":
            raise web.HTTPForbidden(
                text=json.dumps({"error": "admin role required"}),
                content_type="application/json",
            )

    @routes.post(f"{API_PREFIX}/users")
    async def create_user(request):
        _require_admin(request)
        body = await request.json()
        try:
            user, token = reg.create_user(
                body["username"], role=body.get("role", "user")
            )
        except (KeyError, PolyaxonTPUError) as e:
            return web.json_response({"error": str(e)}, status=400)
        # The token is shown exactly once; only its hash is stored.
        _audit(request, EventTypes.USER_CREATED, username=user["username"])
        return web.json_response({**user, "token": token}, status=201)

    @routes.get(f"{API_PREFIX}/users")
    async def list_users(request):
        _require_admin(request)
        return web.json_response({"results": reg.list_users()})

    @routes.delete(f"{API_PREFIX}/users/{{username}}")
    async def remove_user(request):
        _require_admin(request)
        if not reg.remove_user(request.match_info["username"]):
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such user"}),
                content_type="application/json",
            )
        _audit(request, EventTypes.USER_DELETED, username=request.match_info["username"])
        return web.json_response({"ok": True})

    # -- SSO (reference polyaxon/sso/ provider wizards) ------------------------
    from polyaxon_tpu.api.sso import (
        CALLBACK_HTML,
        SSOError,
        StateStore,
        authenticate,
        authorize_redirect_url,
        resolve_provider,
    )

    sso_states = StateStore()

    def _sso_redirect_uri(request) -> str:
        base = orch.conf.get("sso.redirect_base") or f"{request.scheme}://{request.host}"
        return f"{base.rstrip('/')}/auth/sso/callback"

    def _sso_provider_or_error(request):
        try:
            provider = resolve_provider(orch.conf)
        except SSOError as e:
            # Half-configured SSO (oidc without endpoint URLs) must fail
            # with the same clean JSON shape as every other misconfig.
            raise _json_error(web.HTTPBadRequest, str(e))
        if provider is None:
            raise _json_error(web.HTTPNotFound, "SSO is not configured")
        return provider

    @routes.get("/auth/sso/login")
    async def sso_login(request):
        provider = _sso_provider_or_error(request)
        state = sso_states.issue()
        resp = web.HTTPFound(
            authorize_redirect_url(
                provider,
                client_id=orch.conf.get("sso.client_id"),
                redirect_uri=_sso_redirect_uri(request),
                state=state,
            )
        )
        # Bind the state to THIS browser: server-side issuance alone can't
        # stop a login-CSRF where an attacker feeds a victim a callback
        # URL carrying the attacker's own valid state+code (session
        # fixation into the attacker's account).
        resp.set_cookie(
            "px_sso_state", state, httponly=True, samesite="Lax", max_age=600
        )
        raise resp

    @routes.get("/auth/sso/callback")
    async def sso_callback(request):
        provider = _sso_provider_or_error(request)
        q = request.rel_url.query
        state = q.get("state")
        if not sso_states.redeem(state):
            return web.json_response(
                {"error": "invalid or expired SSO state"}, status=403
            )
        if request.cookies.get("px_sso_state") != state:
            return web.json_response(
                {"error": "SSO state does not match this browser's login"},
                status=403,
            )
        code = q.get("code")
        if not code:
            return web.json_response({"error": "missing code"}, status=400)
        try:
            username = await authenticate(
                provider,
                code=code,
                client_id=orch.conf.get("sso.client_id"),
                client_secret=orch.conf.get("sso.client_secret") or "",
                redirect_uri=_sso_redirect_uri(request),
            )
        except SSOError as e:
            return web.json_response({"error": str(e)}, status=502)
        # Provisioning gate: a verified provider identity is NOT platform
        # membership — on a public provider that would open the door to
        # every account there.  Existing same-provider users log in;
        # everyone else needs the allowlist (or the explicit auto_create
        # opt-in).
        existing = reg.get_user(username)
        is_returning = (
            existing is not None
            and existing.get("sso_provider") == provider.name
        )
        allowed = {
            u.strip()
            for u in (orch.conf.get("sso.allowed_users") or "").split(",")
            if u.strip()
        }
        if not is_returning and username not in allowed and not orch.conf.get(
            "sso.auto_create"
        ):
            return web.json_response(
                {
                    "error": f"{provider.name} user {username!r} is not "
                    "authorized for this platform (ask an admin to add you "
                    "to sso.allowed_users)"
                },
                status=403,
            )
        try:
            user, token = reg.ensure_sso_user(provider.name, username)
        except PolyaxonTPUError as e:
            # A colliding local/foreign-provider account: never taken over.
            return web.json_response({"error": str(e)}, status=409)
        if user.get("created"):
            orch.auditor.record(
                EventTypes.USER_CREATED, username=username, sso=provider.name
            )
        resp = web.Response(
            text=CALLBACK_HTML.format(token=token), content_type="text/html"
        )
        resp.del_cookie("px_sso_state")
        return resp

    @web.middleware
    async def auth_middleware(request, handler):
        # "/" (the static dashboard shell — no data in it), the health
        # endpoint, and the SSO entry/callback (the way IN) stay open; the
        # dashboard's API fetches carry the bearer token from
        # localStorage.  Auth is required when a bootstrap token is
        # configured OR any user exists (checked per request — users can
        # be minted at runtime).
        open_paths = ("/", f"{API_PREFIX}/status")
        required = bool(auth_token) or reg.has_users()
        request["auth_required"] = required
        if request.path.startswith("/auth/sso/"):
            request["actor"], request["role"] = None, None
            return await handler(request)
        if required and request.path not in open_paths:
            resolved = _resolve_actor(request)
            if resolved is None:
                return web.json_response({"error": "unauthorized"}, status=401)
            request["actor"], request["role"] = resolved
        elif required:
            # Open path under auth (probes): identity unknown, no powers.
            request["actor"], request["role"] = None, None
        else:
            # Open mode (dev/tests): every caller is the anonymous admin.
            request["actor"], request["role"] = "anonymous", "admin"
        return await handler(request)

    @web.middleware
    async def telemetry_middleware(request, handler):
        # Per-endpoint API latency keyed by the ROUTE TEMPLATE
        # (``/api/v1/runs/{run_id}``), never the resolved path — raw run
        # ids in a label would grow one series per run.  WS upgrades are
        # excluded from the latency histogram (a tail handler's "latency"
        # is the session length, which would swamp the REST p99); they
        # carry their own ws_tail_* series instead.
        stats = orch.stats
        if request.path.startswith("/ws/"):
            return await handler(request)
        t0 = time.perf_counter()
        code = 500
        try:
            resp = await handler(request)
            code = resp.status
            return resp
        except web.HTTPException as e:
            code = e.status
            raise
        finally:
            resource = getattr(request.match_info.route, "resource", None)
            canonical = getattr(resource, "canonical", None)
            route = canonical if canonical else "unmatched"
            elapsed = time.perf_counter() - t0
            stats.observe(
                labeled_key("api_request_s", method=request.method, route=route),
                elapsed,
            )
            stats.incr(
                labeled_key(
                    "api_request_total",
                    code=_STATUS_CLASSES.get(code // 100, "other"),
                    method=request.method,
                    route=route,
                )
            )

    app = web.Application(middlewares=[telemetry_middleware, auth_middleware])
    app.add_routes(routes)
    app["orchestrator"] = orch
    return app


def serve(
    base_dir: str,
    host: str = "127.0.0.1",
    port: int = 8000,
    orch: Optional[Orchestrator] = None,
    auth_token: Optional[str] = None,
) -> None:
    """Run the service: orchestrator loop in a thread + aiohttp in the main loop."""
    from aiohttp import web

    orch = orch or Orchestrator(base_dir)
    orch.start()
    app = create_app(
        orch, auth_token=auth_token or knob_str("POLYAXON_TPU_AUTH_TOKEN") or None
    )
    try:
        web.run_app(app, host=host, port=port, print=logger.info)
    finally:
        orch.stop()
