"""REST + WebSocket API over the orchestrator.

Parity: the reference's DRF surface (``api/experiments/views.py`` — list/
detail :120-280, stop/restart/resume/copy :281-368, statuses :468, metric
ingestion :495-509) and its Sanic streams service (``streams/api.py:14-45``,
``streams/resources/experiments.py:22-113`` — WS log/metric tailing).
TPU-native collapse: one aiohttp app over the embedded orchestrator; live
tailing reads the registry's cursor-friendly rows (statuses/metrics/logs
are ordinary ordered rows), no RabbitMQ/Redis fan-out needed.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional

from polyaxon_tpu.db.registry import Run, RunRegistry
from polyaxon_tpu.exceptions import PolyaxonTPUError
from polyaxon_tpu.orchestrator import Orchestrator

logger = logging.getLogger(__name__)

API_PREFIX = "/api/v1"


def run_to_dict(run: Run) -> Dict[str, Any]:
    return {
        "id": run.id,
        "uuid": run.uuid,
        "kind": run.kind,
        "name": run.name,
        "project": run.project,
        "status": run.status,
        "group_id": run.group_id,
        "pipeline_id": run.pipeline_id,
        "original_id": run.original_id,
        "cloning_strategy": run.cloning_strategy,
        "restarts": run.restarts,
        "tags": run.tags,
        "last_metric": run.last_metric,
        "service_url": run.service_url,
        "is_done": run.is_done,
        "created_at": run.created_at,
        "started_at": run.started_at,
        "finished_at": run.finished_at,
        "spec": run.spec_data,
    }


def create_app(orch: Orchestrator, auth_token: Optional[str] = None):
    """``auth_token`` enables bearer-token access control (reference
    ``scopes/`` permission classes + ephemeral/internal tokens, collapsed
    to one shared-secret scheme); ``/api/v1/status`` stays open for health
    probes, like the reference's ``/status`` endpoint."""
    from aiohttp import WSMsgType, web

    routes = web.RouteTableDef()
    reg: RunRegistry = orch.registry

    def _int_param(request, name: str, default: Optional[int] = None) -> Optional[int]:
        raw = request.rel_url.query.get(name)
        if raw is None:
            return default
        try:
            return int(raw)
        except ValueError:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": f"query param {name!r} must be an integer"}),
                content_type="application/json",
            )

    def _run_or_404(request) -> Run:
        try:
            return reg.get_run(int(request.match_info["run_id"]))
        except PolyaxonTPUError:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"run {request.match_info['run_id']} not found"}),
                content_type="application/json",
            )

    @routes.get("/")
    async def dashboard(request):
        from polyaxon_tpu.api.dashboard import DASHBOARD_HTML

        return web.Response(text=DASHBOARD_HTML, content_type="text/html")

    @routes.get(f"{API_PREFIX}/status")
    async def status(request):
        # Health surface (reference checks/ + api/index/status.py).
        from polyaxon_tpu.checks import run_health_checks

        report = run_health_checks(orch)
        code = 200 if report["healthy"] else 503
        return web.json_response(report, status=code)

    # -- runs CRUD + actions --------------------------------------------------
    @routes.post(f"{API_PREFIX}/runs")
    async def create_run(request):
        body = await request.json()
        try:
            run = orch.submit(
                body.get("spec") or body.get("content"),
                project=body.get("project", "default"),
                name=body.get("name"),
                tags=body.get("tags"),
            )
        except PolyaxonTPUError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(run_to_dict(run), status=201)

    @routes.get(f"{API_PREFIX}/runs")
    async def list_runs(request):
        q = request.rel_url.query
        statuses = q.getall("status", []) or None
        limit = _int_param(request, "limit", 100)
        offset = _int_param(request, "offset", 0)
        # With a DSL filter the full candidate set is fetched (the filter
        # must run BEFORE pagination or matches past the first page
        # vanish); without one, pagination pushes down to SQL.
        has_query = "q" in q
        runs = reg.list_runs(
            kind=q.get("kind"),
            project=q.get("project"),
            group_id=_int_param(request, "group_id"),
            pipeline_id=_int_param(request, "pipeline_id"),
            statuses=statuses,
            limit=None if has_query else limit,
            offset=0 if has_query else offset,
        )
        if has_query:  # search DSL, e.g. q=status:running,metric.loss:<0.5
            from polyaxon_tpu.query import QueryError, apply_query

            try:
                runs = apply_query(runs, q["q"])
            except QueryError as e:
                return web.json_response({"error": str(e)}, status=400)
            runs = runs[offset : offset + limit]
        return web.json_response({"results": [run_to_dict(r) for r in runs]})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}")
    async def get_run(request):
        return web.json_response(run_to_dict(_run_or_404(request)))

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/stop")
    async def stop_run(request):
        run = _run_or_404(request)
        orch.stop_run(run.id)
        return web.json_response({"ok": True})

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/restart")
    async def restart_run(request):
        run = _run_or_404(request)
        clone = orch.clone_run(run.id, strategy="restart")
        return web.json_response(run_to_dict(clone), status=201)

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/resume")
    async def resume_run(request):
        run = _run_or_404(request)
        clone = orch.clone_run(run.id, strategy="resume")
        return web.json_response(run_to_dict(clone), status=201)

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/copy")
    async def copy_run(request):
        run = _run_or_404(request)
        clone = orch.clone_run(run.id, strategy="copy")
        return web.json_response(run_to_dict(clone), status=201)

    # -- sub-resources --------------------------------------------------------
    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/statuses")
    async def get_statuses(request):
        run = _run_or_404(request)
        return web.json_response({"results": reg.get_statuses(run.id)})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/metrics")
    async def get_metrics(request):
        run = _run_or_404(request)
        since = _int_param(request, "since_id", 0)
        return web.json_response({"results": reg.get_metrics(run.id, since_id=since)})

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/metrics")
    async def post_metrics(request):
        # In-job metric ingestion (reference ExperimentMetricListView).
        run = _run_or_404(request)
        body = await request.json()
        reg.add_metric(run.id, body.get("values", {}), step=body.get("step"))
        return web.json_response({"ok": True}, status=201)

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/logs")
    async def get_logs(request):
        run = _run_or_404(request)
        rows = reg.get_logs(
            run.id,
            since_id=_int_param(request, "since_id", 0),
            limit=_int_param(request, "limit"),
        )
        return web.json_response({"results": rows})

    @routes.post(f"{API_PREFIX}/runs/{{run_id}}/heartbeat")
    async def post_heartbeat(request):
        run = _run_or_404(request)
        reg.ping_heartbeat(run.id)
        return web.json_response({"ok": True})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/processes")
    async def get_processes(request):
        run = _run_or_404(request)
        return web.json_response({"results": reg.get_processes(run.id)})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/artifacts")
    async def list_artifacts(request):
        # Outputs browsing (reference stores-managed outputs endpoints):
        # local run dir first, artifact store as the durable fallback.
        run = _run_or_404(request)
        # Store listing may shell out to gsutil — keep it off the event loop.
        results = await asyncio.to_thread(orch.list_artifacts, run.id)
        return web.json_response({"results": results})

    @routes.get(f"{API_PREFIX}/runs/{{run_id}}/artifacts/{{key:.+}}")
    async def get_artifact(request):
        run = _run_or_404(request)
        key = request.match_info["key"]
        local = orch.artifact_local_path(run.id, key)
        if local is not None:
            return web.FileResponse(local)  # sendfile, zero-copy
        # Store fallback: the open (gsutil cp to a temp file) blocks for the
        # transfer — keep it off the event loop — then stream chunks so a
        # multi-GB checkpoint never sits in control-plane memory.
        f = await asyncio.to_thread(orch.open_artifact, run.id, key)
        if f is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"artifact {key!r} not found"}),
                content_type="application/json",
            )
        resp = web.StreamResponse(
            headers={"Content-Type": "application/octet-stream"}
        )
        await resp.prepare(request)
        try:
            while True:
                chunk = await asyncio.to_thread(f.read, 1 << 20)
                if not chunk:
                    break
                await resp.write(chunk)
        finally:
            f.close()
        await resp.write_eof()
        return resp

    # -- devices (accelerator inventory) --------------------------------------
    @routes.get(f"{API_PREFIX}/devices")
    async def list_devices(request):
        # Cluster inventory (reference nodes API, ``api/nodes/``).
        return web.json_response({"results": reg.list_devices()})

    @routes.post(f"{API_PREFIX}/devices")
    async def register_device(request):
        body = await request.json()
        try:
            device = orch.register_device(
                body["name"],
                body["accelerator"],
                int(body["chips"]),
                num_hosts=int(body.get("num_hosts", 1)),
            )
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response(
                {"error": f"device needs name/accelerator/chips: {e}"}, status=400
            )
        return web.json_response(device, status=201)

    @routes.delete(f"{API_PREFIX}/devices/{{name}}")
    async def remove_device(request):
        removed = reg.remove_device(request.match_info["name"])
        if not removed:
            raise web.HTTPNotFound(
                text=json.dumps({"error": "no such device"}),
                content_type="application/json",
            )
        return web.json_response({"ok": True})

    # -- live streaming (WS) --------------------------------------------------
    async def _ws_tail(request, fetch, poll: float = 0.5):
        """Generic WS tail loop: push new rows until the run is done."""
        run = _run_or_404(request)
        ws = web.WebSocketResponse(heartbeat=30)
        await ws.prepare(request)
        cursor = 0
        try:
            while not ws.closed:
                rows = fetch(run.id, cursor)
                for row in rows:
                    cursor = max(cursor, row.get("id", cursor))
                    await ws.send_json(row)
                current = reg.get_run(run.id)
                if current.is_done and not rows:
                    await ws.send_json({"event": "done", "status": current.status})
                    break
                try:
                    msg = await asyncio.wait_for(ws.receive(), timeout=poll)
                    if msg.type in (WSMsgType.CLOSE, WSMsgType.CLOSING, WSMsgType.ERROR):
                        break
                except asyncio.TimeoutError:
                    pass
        finally:
            await ws.close()
        return ws

    @routes.get("/ws/v1/runs/{run_id}/logs")
    async def ws_logs(request):
        return await _ws_tail(
            request, lambda rid, cur: reg.get_logs(rid, since_id=cur)
        )

    @routes.get("/ws/v1/runs/{run_id}/metrics")
    async def ws_metrics(request):
        return await _ws_tail(
            request, lambda rid, cur: reg.get_metrics(rid, since_id=cur)
        )

    @web.middleware
    async def auth_middleware(request, handler):
        # "/" (the static dashboard shell — no data in it) and the health
        # endpoint stay open; the dashboard's API fetches carry the bearer
        # token the user supplies once via ?token=.
        open_paths = ("/", f"{API_PREFIX}/status")
        if auth_token and request.path not in open_paths:
            import hmac

            supplied = request.headers.get("Authorization", "")
            # Compare bytes: compare_digest(str, str) raises on non-ASCII,
            # which would turn a garbage header into a 500 instead of a 401.
            expected = f"Bearer {auth_token}".encode()
            if not hmac.compare_digest(
                supplied.encode("utf-8", "surrogateescape"), expected
            ):
                return web.json_response({"error": "unauthorized"}, status=401)
        return await handler(request)

    app = web.Application(middlewares=[auth_middleware] if auth_token else [])
    app.add_routes(routes)
    app["orchestrator"] = orch
    return app


def serve(
    base_dir: str,
    host: str = "127.0.0.1",
    port: int = 8000,
    orch: Optional[Orchestrator] = None,
    auth_token: Optional[str] = None,
) -> None:
    """Run the service: orchestrator loop in a thread + aiohttp in the main loop."""
    import os

    from aiohttp import web

    orch = orch or Orchestrator(base_dir)
    orch.start()
    app = create_app(
        orch, auth_token=auth_token or os.environ.get("POLYAXON_TPU_AUTH_TOKEN")
    )
    try:
        web.run_app(app, host=host, port=port, print=logger.info)
    finally:
        orch.stop()
