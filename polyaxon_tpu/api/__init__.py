from polyaxon_tpu.api.app import create_app, run_to_dict, serve

__all__ = ["create_app", "run_to_dict", "serve"]
