"""Single-page dashboard served at ``/``.

Parity (minimal): the reference's React dashboard (``client/``, 551 TS
files — runs tables, status chips, metric charts, log viewers).  This is
the embedded equivalent: one dependency-free HTML page polling the REST
API — runs table with status/metrics, per-run status history, live log
tail, and a canvas metric chart.
"""

DASHBOARD_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8"/>
<title>polyaxon-tpu</title>
<style>
  :root { --bg:#101418; --panel:#1a2027; --text:#dde3ea; --dim:#8a949e;
          --accent:#4da3ff; --ok:#3fb950; --bad:#f85149; --warn:#d29922; }
  body { background:var(--bg); color:var(--text);
         font:14px/1.5 -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin:0; padding:24px; }
  h1 { font-size:18px; margin:0 0 16px; }
  h1 span { color:var(--dim); font-weight:normal; }
  table { border-collapse:collapse; width:100%; background:var(--panel);
          border-radius:8px; overflow:hidden; }
  th, td { text-align:left; padding:8px 12px; }
  th { color:var(--dim); font-weight:600; border-bottom:1px solid #2a323c; }
  tr.row:hover { background:#222a33; cursor:pointer; }
  .chip { padding:2px 8px; border-radius:10px; font-size:12px; }
  .chip.succeeded { background:#1f3d2b; color:var(--ok); }
  .chip.failed { background:#442224; color:var(--bad); }
  .chip.running, .chip.starting, .chip.scheduled { background:#1d3048; color:var(--accent); }
  .chip.stopped, .chip.skipped { background:#3a3325; color:var(--warn); }
  .chip.created { background:#2a323c; color:var(--dim); }
  #detail { margin-top:20px; display:none; }
  .panel { background:var(--panel); border-radius:8px; padding:16px; margin-top:12px; }
  pre { margin:0; white-space:pre-wrap; color:var(--dim); max-height:280px; overflow:auto; }
  canvas { width:100%; height:160px; }
  input { background:var(--panel); color:var(--text); border:1px solid #2a323c;
          border-radius:6px; padding:6px 10px; width:340px; margin-bottom:12px; }
</style>
</head>
<body>
<h1>polyaxon-tpu <span id="count"></span></h1>
<input id="query" placeholder='filter: status:running, metric.loss:<0.5' />
<table>
  <thead><tr><th>ID</th><th>Kind</th><th>Name</th><th>Project</th>
  <th>Status</th><th>Last metric</th><th>Restarts</th></tr></thead>
  <tbody id="runs"></tbody>
</table>
<div id="detail">
  <h1 id="detail-title"></h1>
  <div class="panel"><canvas id="chart" width="900" height="160"></canvas></div>
  <div class="panel"><pre id="logs"></pre></div>
</div>
<script>
let selected = null;
// Bearer token for authed deployments: ?token=... once, then localStorage.
const urlToken = new URLSearchParams(location.search).get('token');
if (urlToken) localStorage.setItem('px_token', urlToken);
const TOKEN = localStorage.getItem('px_token');
const HDRS = TOKEN ? {Authorization: 'Bearer ' + TOKEN} : {};
const apiFetch = url => fetch(url, {headers: HDRS});
const esc = s => String(s ?? '').replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const names = {};
const fmtMetric = m => Object.entries(m||{}).filter(([k])=>!k.startsWith('sys/'))
  .map(([k,v])=>`${esc(k)}=${typeof v==='number'?v.toPrecision(4):esc(v)}`).join(' ');
async function refresh() {
  const q = document.getElementById('query').value.trim();
  const url = '/api/v1/runs' + (q ? '?q=' + encodeURIComponent(q) : '');
  const resp = await apiFetch(url);
  if (!resp.ok) {
    if (resp.status === 401)
      document.getElementById('count').textContent = '— unauthorized (append ?token=...)';
    return;
  }
  const data = await resp.json();
  document.getElementById('count').textContent = `— ${data.results.length} runs`;
  document.getElementById('runs').innerHTML = data.results.map(r => {
    names[r.id] = r.name || ('run ' + r.id);
    return `
    <tr class="row" onclick="select(${Number(r.id)})">
      <td>${Number(r.id)}</td><td>${esc(r.kind)}</td><td>${esc(r.name||'')}</td>
      <td>${esc(r.project)}</td>
      <td><span class="chip ${esc(r.status)}">${esc(r.status)}</span></td>
      <td>${fmtMetric(r.last_metric)}</td><td>${Number(r.restarts)}</td></tr>`;
  }).join('');
  if (selected) await refreshDetail();
}
async function select(id) {
  selected = id;
  document.getElementById('detail').style.display = 'block';
  document.getElementById('detail-title').textContent = `#${id} ${names[id]||''}`;
  await refreshDetail();
}
async function refreshDetail() {
  const [metrics, logs] = await Promise.all([
    apiFetch(`/api/v1/runs/${selected}/metrics`).then(r=>r.json()),
    apiFetch(`/api/v1/runs/${selected}/logs?limit=200`).then(r=>r.json())]);
  document.getElementById('logs').textContent =
    logs.results.map(l=>l.line).join('\\n') || '(no logs)';
  drawChart(metrics.results);
}
function drawChart(rows) {
  const c = document.getElementById('chart'), ctx = c.getContext('2d');
  ctx.clearRect(0,0,c.width,c.height);
  const series = {};
  rows.forEach(r => Object.entries(r.values).forEach(([k,v]) => {
    if (typeof v==='number' && !k.startsWith('sys/'))
      (series[k] = series[k]||[]).push(v);
  }));
  const colors = ['#4da3ff','#3fb950','#d29922','#f85149','#bc8cff'];
  Object.entries(series).slice(0,5).forEach(([name, vals], si) => {
    if (vals.length < 2) return;
    const min = Math.min(...vals), max = Math.max(...vals), span = (max-min)||1;
    ctx.strokeStyle = colors[si%colors.length]; ctx.beginPath();
    vals.forEach((v,i) => {
      const x = 40 + i*(c.width-60)/(vals.length-1);
      const y = c.height-20 - (v-min)/span*(c.height-40);
      i ? ctx.lineTo(x,y) : ctx.moveTo(x,y);
    });
    ctx.stroke();
    ctx.fillStyle = colors[si%colors.length];
    ctx.fillText(name, 44, 14+12*si);
  });
}
document.getElementById('query').addEventListener('change', refresh);
refresh(); setInterval(refresh, 2000);
</script>
</body>
</html>
"""
