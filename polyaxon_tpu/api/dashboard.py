"""Single-page dashboard served at ``/``.

Parity: the reference's React dashboard (``client/``, 551 TS files — runs
tables, status chips, metric charts, log viewers, per-entity pages, the
experiment-groups sweep pages and run comparison).  This is the embedded
equivalent: one dependency-free HTML page over the REST API — tabs for
runs (with live detail: metric chart, log tail, status history,
stop/restart actions, service links, and a SWEEP panel for groups: trials
table + metric-vs-param scatter off ``/runs?group_id=``), a bookmark-based
run-compare tab (overlaid metric series + last-metric table), accelerator
inventory with packed-chip accounting, projects, saved searches, and the
audit activity feed.

Auth bootstrap is a token FORM (stored in localStorage) rather than a
``?token=`` query parameter — URLs land in browser history and access
logs, so the secret must never ride one (round-3 finding).
"""

DASHBOARD_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8"/>
<title>polyaxon-tpu</title>
<style>
  :root { --bg:#101418; --panel:#1a2027; --text:#dde3ea; --dim:#8a949e;
          --accent:#4da3ff; --ok:#3fb950; --bad:#f85149; --warn:#d29922; }
  body { background:var(--bg); color:var(--text);
         font:14px/1.5 -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin:0; padding:24px; }
  h1 { font-size:18px; margin:0 0 12px; }
  h1 span { color:var(--dim); font-weight:normal; }
  h2 { font-size:14px; margin:0 0 8px; color:var(--dim); }
  nav { margin-bottom:16px; }
  nav a { color:var(--dim); margin-right:16px; cursor:pointer;
          text-decoration:none; padding-bottom:4px; }
  nav a.active { color:var(--text); border-bottom:2px solid var(--accent); }
  table { border-collapse:collapse; width:100%; background:var(--panel);
          border-radius:8px; overflow:hidden; }
  th, td { text-align:left; padding:8px 12px; }
  th { color:var(--dim); font-weight:600; border-bottom:1px solid #2a323c; }
  tr.row:hover { background:#222a33; cursor:pointer; }
  .chip { padding:2px 8px; border-radius:10px; font-size:12px; }
  .chip.succeeded { background:#1f3d2b; color:var(--ok); }
  .chip.failed, .chip.upstream_failed { background:#442224; color:var(--bad); }
  .chip.running, .chip.starting, .chip.scheduled { background:#1d3048; color:var(--accent); }
  .chip.stopped, .chip.skipped, .chip.warning, .chip.queued { background:#3a3325; color:var(--warn); }
  .chip.created { background:#2a323c; color:var(--dim); }
  #detail { margin-top:20px; display:none; }
  .panel { background:var(--panel); border-radius:8px; padding:16px; margin-top:12px; }
  pre { margin:0; white-space:pre-wrap; color:var(--dim); max-height:280px; overflow:auto; }
  canvas { width:100%; height:160px; }
  input, select { background:var(--panel); color:var(--text); border:1px solid #2a323c;
          border-radius:6px; padding:6px 10px; margin-bottom:12px; }
  #query { width:340px; }
  button { background:#253141; color:var(--text); border:1px solid #2a323c;
           border-radius:6px; padding:4px 12px; cursor:pointer; margin-right:8px; }
  button:hover { background:#2d3c50; }
  a.svc { color:var(--accent); }
  .dim { color:var(--dim); }
  #login { display:none; margin-bottom:12px; }
  #qwrap { position:relative; display:inline-block; }
  #qsuggest { position:absolute; top:34px; left:0; z-index:10; display:none;
              background:var(--panel); border:1px solid #2a323c; border-radius:6px;
              min-width:340px; max-height:260px; overflow:auto; }
  #qsuggest div { padding:5px 12px; cursor:pointer; }
  #qsuggest div.sel, #qsuggest div:hover { background:#2d3c50; }
  #qsuggest span { color:var(--dim); float:right; margin-left:16px; }
  #logs-state { float:right; font-size:12px; }
</style>
</head>
<body>
<h1>polyaxon-tpu <span id="count"></span></h1>
<div id="login">
  <form onsubmit="saveToken(event)">
    <input id="token-input" type="password" placeholder="API token" autocomplete="off"/>
    <button type="submit">sign in</button>
    <span class="dim">unauthorized — paste a token (stored locally, never in the URL)</span>
  </form>
</div>
<nav>
  <a id="tab-runs" class="active" onclick="showTab('runs')">Runs</a>
  <a id="tab-compare" onclick="showTab('compare')">Compare</a>
  <a id="tab-devices" onclick="showTab('devices')">Devices</a>
  <a id="tab-projects" onclick="showTab('projects')">Projects</a>
  <a id="tab-searches" onclick="showTab('searches')">Searches</a>
  <a id="tab-activity" onclick="showTab('activity')">Activity</a>
  <a id="tab-archives" onclick="showTab('archives')">Archives</a>
  <a id="tab-analytics" onclick="showTab('analytics')">Analytics</a>
</nav>

<div id="view-runs">
  <span id="qwrap">
    <input id="query" placeholder='filter: status:running, metric.loss:<0.5'
           autocomplete="off" />
    <div id="qsuggest"></div>
  </span>
  <table>
    <thead><tr><th>ID</th><th>Kind</th><th>Name</th><th>Project</th>
    <th>Status</th><th>Last metric</th><th>Restarts</th><th>Service</th><th></th></tr></thead>
    <tbody id="runs"></tbody>
  </table>
  <div id="detail">
    <h1 id="detail-title"></h1>
    <div class="panel">
      <button onclick="runAction('stop')">stop</button>
      <button onclick="runAction('restart')">restart</button>
      <button onclick="runAction('resume')">resume</button>
      <button onclick="toggleBookmark()" id="bookmark-btn">bookmark</button>
      <span id="statuses" class="dim"></span>
    </div>
    <div class="panel" id="sweep-panel" style="display:none">
      <h2>Sweep trials <span id="sweep-count"></span></h2>
      <div>
        <select id="sweep-x" onchange="drawSweep()"></select>
        <select id="sweep-y" onchange="drawSweep()"></select>
      </div>
      <canvas id="sweep-chart" width="900" height="200"></canvas>
      <table><thead><tr><th>ID</th><th>Status</th><th>Params</th>
        <th>Last metric</th></tr></thead>
        <tbody id="trials"></tbody></table>
    </div>
    <div class="panel">
      <h2>Metrics
        <select id="view-select" onchange="applyView()"><option value="">(all)</option></select>
        <input id="view-name" placeholder="view name" style="width:110px"/>
        <button onclick="saveView()">save view</button>
      </h2>
      <div id="metric-picks" class="dim"></div>
      <div id="chart-legend" class="dim"></div>
      <canvas id="chart" width="900" height="160"></canvas>
    </div>
    <div class="panel">
      <h2>Logs <span id="logs-state" class="dim"></span></h2>
      <pre id="logs"></pre>
    </div>
  </div>
</div>

<div id="view-compare" style="display:none">
  <div class="panel">
    <h2>Bookmarked runs — last metrics</h2>
    <table><thead id="cmp-head"></thead><tbody id="cmp-rows"></tbody></table>
  </div>
  <div class="panel">
    <h2>Metric over steps <select id="cmp-metric" onchange="drawCompare()"></select></h2>
    <canvas id="cmp-chart" width="900" height="220"></canvas>
    <div id="cmp-legend" class="dim"></div>
  </div>
</div>

<div id="view-devices" style="display:none">
  <table>
    <thead><tr><th>ID</th><th>Name</th><th>Accelerator</th><th>Chips used</th>
    <th>Hosts</th><th>Held by</th></tr></thead>
    <tbody id="devices"></tbody>
  </table>
</div>

<div id="view-projects" style="display:none">
  <table>
    <thead><tr><th>Name</th><th>Runs</th><th>Description</th></tr></thead>
    <tbody id="projects"></tbody>
  </table>
</div>

<div id="view-searches" style="display:none">
  <table>
    <thead><tr><th>Name</th><th>Query</th><th>Owner</th><th></th></tr></thead>
    <tbody id="searches"></tbody>
  </table>
</div>

<div id="view-activity" style="display:none">
  <table>
    <thead><tr><th>When</th><th>Event</th><th>Actor</th><th>Context</th></tr></thead>
    <tbody id="activity"></tbody>
  </table>
</div>

<div id="view-archives" style="display:none">
  <table>
    <thead><tr><th>ID</th><th>Kind</th><th>Name</th><th>Project</th>
    <th>Status</th><th>Archived</th><th></th></tr></thead>
    <tbody id="archives"></tbody>
  </table>
</div>

<div id="view-analytics" style="display:none">
  <div class="panel">
    <h2>Platform summary</h2>
    <div id="analytics-summary"></div>
  </div>
  <div class="panel">
    <h2>Events per day (14d)</h2>
    <table><thead id="analytics-head"></thead><tbody id="analytics-rows"></tbody></table>
  </div>
  <div id="analytics-denied" class="dim" style="display:none">
    analytics are admin-only
  </div>
</div>

<script>
let selected = null;
let selectedKind = null;
let tab = 'runs';
let searchCache = [];
let trialCache = [];
let compareCache = [];   // [{run, series: {metric: [[step, v], ...]}}]
// Bearer token lives in localStorage only — never in the URL (history +
// access-log leak). The login form below populates it on 401.
const TOKEN = localStorage.getItem('px_token');
const HDRS = TOKEN ? {Authorization: 'Bearer ' + TOKEN} : {};
const apiFetch = (url, opts) => fetch(url, {...(opts||{}), headers: HDRS});
const esc = s => String(s ?? '').replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
const names = {};
const fmtMetric = m => Object.entries(m||{}).filter(([k])=>!k.startsWith('sys/'))
  .map(([k,v])=>`${esc(k)}=${typeof v==='number'?v.toPrecision(4):esc(v)}`).join(' ');
const fmtTs = t => new Date(t*1000).toLocaleTimeString();
const COLORS = ['#4da3ff','#3fb950','#d29922','#f85149','#bc8cff','#56d4dd'];

function saveToken(ev) {
  ev.preventDefault();
  const v = document.getElementById('token-input').value.trim();
  if (v) { localStorage.setItem('px_token', v); location.reload(); }
}

function showTab(name) {
  tab = name;
  for (const t of ['runs','compare','devices','projects','searches',
                   'activity','archives','analytics']) {
    document.getElementById('view-'+t).style.display = t===name?'block':'none';
    document.getElementById('tab-'+t).className = t===name?'active':'';
  }
  refresh();
}

async function refresh() {
  // Capture the tab before awaiting: a mid-flight tab switch must not
  // render this payload into another tab's table.
  const t = tab;
  if (t === 'runs') return refreshRuns();
  if (t === 'compare') return refreshCompare();
  if (t === 'archives') return refreshArchives();
  if (t === 'analytics') return refreshAnalytics();
  const resp = await apiFetch('/api/v1/' + (t === 'activity' ? 'activities' : t));
  if (!resp.ok) return authNote(resp);
  if (t !== tab) return;
  const data = (await resp.json()).results;
  if (t === 'devices')
    document.getElementById('devices').innerHTML = data.map(d => `
      <tr><td>${Number(d.id)}</td><td>${esc(d.name)}</td><td>${esc(d.accelerator)}</td>
      <td>${Number(d.used_chips||0)}/${Number(d.chips)}</td><td>${Number(d.num_hosts)}</td>
      <td>${(d.holders||[]).length ? (d.holders||[]).map(h=>'#'+Number(h)).join(' ')
          : '<span class="dim">free</span>'}</td></tr>`).join('');
  if (t === 'projects')
    document.getElementById('projects').innerHTML = data.map(p => `
      <tr><td>${esc(p.name)}</td><td>${Number(p.num_runs)}</td>
      <td class="dim">${esc(p.description||'')}</td></tr>`).join('');
  if (t === 'searches') {
    // Index-addressed buttons: names are arbitrary user strings and must
    // never be interpolated into inline JS (quote-breakout XSS).
    searchCache = data;
    document.getElementById('searches').innerHTML = data.map((s, i) => `
      <tr><td>${esc(s.name)}</td><td class="dim">${esc(s.query)}</td>
      <td class="dim">${esc(s.owner||'')}</td>
      <td><button onclick="runSearchIdx(${Number(i)})">run</button></td></tr>`).join('');
  }
  if (t === 'activity')
    document.getElementById('activity').innerHTML = data.map(a => `
      <tr><td class="dim">${fmtTs(a.created_at)}</td><td>${esc(a.event_type)}</td>
      <td>${esc(a.context.actor||'')}</td>
      <td class="dim">${esc(Object.entries(a.context).filter(([k])=>k!=='actor')
        .map(([k,v])=>k+'='+v).join(' '))}</td></tr>`).join('');
}

async function refreshArchives() {
  const resp = await apiFetch('/api/v1/archives');
  if (!resp.ok) return authNote(resp);
  if (tab !== 'archives') return;
  const data = (await resp.json()).results;
  document.getElementById('archives').innerHTML = data.map(r => `
    <tr><td>${Number(r.id)}</td><td>${esc(r.kind)}</td><td>${esc(r.name||'')}</td>
    <td>${esc(r.project)}</td>
    <td><span class="chip ${esc(r.status)}">${esc(r.status)}</span></td>
    <td class="dim">${new Date(r.archived_at*1000).toLocaleString()}</td>
    <td>
      <button onclick="archiveAction(${Number(r.id)}, 'restore')">restore</button>
      <button onclick="archiveAction(${Number(r.id)}, 'delete')">delete</button>
    </td></tr>`).join('')
    || '<tr><td class="dim" colspan="7">nothing archived</td></tr>';
}

async function archiveAction(id, action) {
  let resp;
  if (action === 'delete') {
    // Deletion purges rows, outputs, and store artifacts — unrecoverable.
    if (!confirm(`Permanently delete run #${id} and all its data?`)) return;
    resp = await apiFetch(`/api/v1/runs/${id}`, {method: 'DELETE'});
  } else {
    resp = await apiFetch(`/api/v1/runs/${id}/restore`, {method: 'POST'});
  }
  if (!resp.ok) {
    const err = await resp.json().catch(() => ({}));
    alert(`${action} failed: ${err.error || resp.status}`);
  }
  refreshArchives();
}

let analyticsLast = 0;
async function refreshAnalytics() {
  // Day-granularity data polled by the global 2s loop: throttle to 30s
  // (same cadence as the query vocabulary) — and don't re-issue a 403
  // every tick for non-admins.
  if (Date.now() - analyticsLast < 30000) return;
  analyticsLast = Date.now();
  const resp = await apiFetch('/api/v1/analytics');
  const denied = document.getElementById('analytics-denied');
  if (resp.status === 403) {
    // Clear any aggregates a previously-authorized token rendered.
    document.getElementById('analytics-summary').innerHTML = '';
    document.getElementById('analytics-head').innerHTML = '';
    document.getElementById('analytics-rows').innerHTML = '';
    denied.style.display = 'block';
    return;
  }
  if (!resp.ok) return authNote(resp);
  denied.style.display = 'none';
  if (tab !== 'analytics') return;
  const d = await resp.json();
  const summary = [
    ...Object.entries(d.runs_by_kind).map(([k,v]) => `${esc(k)} runs: <b>${Number(v)}</b>`),
    `users: <b>${Number(d.num_users)}</b>`,
    `projects: <b>${Number(d.num_projects)}</b>`,
    `devices: <b>${Number(d.num_devices)}</b>`,
  ];
  document.getElementById('analytics-summary').innerHTML =
    summary.join(' &nbsp;·&nbsp; ');
  const days = Object.keys(d.events_per_day).sort();
  const types = [...new Set(days.flatMap(day => Object.keys(d.events_per_day[day])))].sort();
  document.getElementById('analytics-head').innerHTML =
    `<tr><th>Day</th>${types.map(t=>`<th>${esc(t)}</th>`).join('')}</tr>`;
  document.getElementById('analytics-rows').innerHTML = days.map(day => `
    <tr><td class="dim">${esc(day)}</td>
    ${types.map(t => `<td>${Number(d.events_per_day[day][t]||0)||''}</td>`).join('')}
    </tr>`).join('') || '<tr><td class="dim">no activity yet</td></tr>';
}

function authNote(resp) {
  if (resp.status === 401) {
    document.getElementById('count').textContent = '— unauthorized';
    document.getElementById('login').style.display = 'block';
  }
}

function runSearchIdx(i) {
  // Execute by plugging the saved query into the filter box — set it
  // BEFORE switching tabs so showTab's implicit refresh already uses it
  // (two racing fetches could otherwise show unfiltered results).
  const s = searchCache[i];
  if (!s) return;
  document.getElementById('query').value = s.query;
  showTab('runs');
}

async function refreshRuns() {
  const q = document.getElementById('query').value.trim();
  const url = '/api/v1/runs' + (q ? '?q=' + encodeURIComponent(q) : '');
  const resp = await apiFetch(url);
  if (!resp.ok) return authNote(resp);
  const data = await resp.json();
  document.getElementById('count').textContent = `— ${data.results.length} runs`;
  document.getElementById('runs').innerHTML = data.results.map(r => {
    names[r.id] = r.name || ('run ' + r.id);
    return `
    <tr class="row" onclick="select(${Number(r.id)}, '${esc(r.kind)}')">
      <td>${Number(r.id)}</td><td>${esc(r.kind)}</td><td>${esc(r.name||'')}</td>
      <td>${esc(r.project)}</td>
      <td><span class="chip ${esc(r.status)}">${esc(r.status)}</span></td>
      <td>${fmtMetric(r.last_metric)}</td><td>${Number(r.restarts)}</td>
      <td>${r.service_url ? `<a class="svc" href="${esc(r.service_url)}"
        target="_blank" onclick="event.stopPropagation()">open</a>` : ''}</td>
      <td><button onclick="event.stopPropagation(); bookmark(${Number(r.id)})">☆</button></td></tr>`;
  }).join('');
  if (selected) await refreshDetail();
}

async function select(id, kind) {
  selected = id;
  selectedKind = kind;
  document.getElementById('detail').style.display = 'block';
  document.getElementById('detail-title').textContent = `#${id} ${names[id]||''}`;
  document.getElementById('sweep-panel').style.display =
    kind === 'group' ? 'block' : 'none';
  if (kind === 'group') {
    // Groups produce no log rows of their own — don't hold a WS tail.
    if (logSocket) { logSocket.onclose = null; logSocket.close(); logSocket = null; }
    document.getElementById('logs').textContent = '';
    document.getElementById('logs-state').textContent = 'sweep (see trials)';
  } else {
    openLogStream(id);
  }
  chartSelection = null;
  document.getElementById('view-select').value = '';
  await loadChartViews();
  await refreshDetail();
}

// Live log tail over the existing WS channel (no polling). The bearer
// token rides a subprotocol — the browser WebSocket API can't set an
// Authorization header, and the token must never enter a URL.
let logSocket = null;
function openLogStream(id) {
  if (logSocket) { logSocket.onclose = null; logSocket.close(); }
  const pre = document.getElementById('logs');
  const state = document.getElementById('logs-state');
  pre.textContent = '';
  state.textContent = 'connecting…';
  const proto = location.protocol === 'https:' ? 'wss://' : 'ws://';
  const url = `${proto}${location.host}/ws/v1/runs/${id}/logs`;
  // Offer the fixed 'bearer' name alongside the token-bearing one: the
  // server selects only 'bearer', so the token never appears in the
  // handshake RESPONSE headers.
  const ws = TOKEN ? new WebSocket(url, ['bearer', 'bearer.' + TOKEN]) : new WebSocket(url);
  logSocket = ws;
  ws.onopen = () => { state.textContent = 'live'; };
  ws.onmessage = ev => {
    const row = JSON.parse(ev.data);
    if (row.event === 'done') { state.textContent = `done (${row.status})`; return; }
    if (row.event === 'deleted') { state.textContent = 'run deleted'; return; }
    if (row.event) return;  // future server frames must not render as text
    const stick = pre.scrollTop + pre.clientHeight >= pre.scrollHeight - 4;
    const prefix = row.process_id != null ? `p${row.process_id}| ` : '';
    pre.textContent += prefix + row.line + '\\n';
    if (stick) pre.scrollTop = pre.scrollHeight;
  };
  ws.onclose = () => {
    // 'connecting…' here means the handshake failed (401/404/refused).
    if (state.textContent === 'live') state.textContent = 'disconnected';
    else if (state.textContent === 'connecting…') state.textContent = 'unavailable';
  };
}

async function runAction(action) {
  if (!selected) return;
  await apiFetch(`/api/v1/runs/${selected}/${action}`, {method:'POST'});
  await refreshRuns();
}

async function bookmark(id) {
  await apiFetch(`/api/v1/runs/${id}/bookmark`, {method:'POST'});
}

async function toggleBookmark() {
  if (selected) await bookmark(selected);
}

async function refreshDetail() {
  // Logs stream over the WS channel (openLogStream); only metrics/
  // statuses/trials poll here.
  const wants = [
    apiFetch(`/api/v1/runs/${selected}/metrics`).then(r=>r.json()),
    apiFetch(`/api/v1/runs/${selected}/statuses`).then(r=>r.json())];
  if (selectedKind === 'group')
    wants.push(apiFetch(`/api/v1/runs?group_id=${selected}&limit=500`).then(r=>r.json()));
  const [metrics, statuses, trials] = await Promise.all(wants);
  document.getElementById('statuses').textContent =
    statuses.results.map(s=>s.status).join(' → ');
  drawChart(metrics.results);
  if (trials) renderSweep(trials.results);
}

function trialParams(r) {
  return (r.spec && r.spec.declarations) || {};
}

function renderSweep(trials) {
  trialCache = trials;
  document.getElementById('sweep-count').textContent = `(${trials.length})`;
  document.getElementById('trials').innerHTML = trials.map(t => `
    <tr><td>${Number(t.id)}</td>
    <td><span class="chip ${esc(t.status)}">${esc(t.status)}</span></td>
    <td class="dim">${esc(Object.entries(trialParams(t))
      .map(([k,v])=>k+'='+v).join(' '))}</td>
    <td>${fmtMetric(t.last_metric)}</td></tr>`).join('');
  // Param/metric axis choices from the union across trials.
  const params = new Set(), metrics = new Set();
  trials.forEach(t => {
    Object.entries(trialParams(t)).forEach(([k,v]) => {
      if (typeof v === 'number') params.add(k);
    });
    Object.entries(t.last_metric||{}).forEach(([k,v]) => {
      if (typeof v === 'number' && !k.startsWith('sys/')) metrics.add(k);
    });
  });
  fillSelect('sweep-x', [...params]);
  fillSelect('sweep-y', [...metrics]);
  drawSweep();
}

function fillSelect(id, options) {
  const el = document.getElementById(id);
  const keep = el.value;
  el.innerHTML = options.map(o => `<option>${esc(o)}</option>`).join('');
  if (options.includes(keep)) el.value = keep;
}

function drawSweep() {
  const xk = document.getElementById('sweep-x').value;
  const yk = document.getElementById('sweep-y').value;
  const c = document.getElementById('sweep-chart'), ctx = c.getContext('2d');
  ctx.clearRect(0,0,c.width,c.height);
  if (!xk || !yk) return;
  const pts = trialCache
    .map(t => [trialParams(t)[xk], (t.last_metric||{})[yk], t.status])
    .filter(([x,y]) => typeof x === 'number' && typeof y === 'number');
  if (!pts.length) return;
  const xs = pts.map(p=>p[0]), ys = pts.map(p=>p[1]);
  const xmin = Math.min(...xs), xspan = (Math.max(...xs)-xmin)||1;
  const ymin = Math.min(...ys), yspan = (Math.max(...ys)-ymin)||1;
  ctx.fillStyle = '#8a949e';
  ctx.fillText(`${xk} →`, c.width-80, c.height-6);
  ctx.fillText(`↑ ${yk}`, 6, 14);
  pts.forEach(([x,y,status]) => {
    const px = 40 + (x-xmin)/xspan*(c.width-70);
    const py = c.height-24 - (y-ymin)/yspan*(c.height-44);
    ctx.fillStyle = status === 'succeeded' ? '#3fb950'
      : status === 'failed' ? '#f85149' : '#4da3ff';
    ctx.beginPath(); ctx.arc(px, py, 4, 0, 7); ctx.fill();
  });
  ctx.fillStyle = '#8a949e';
  ctx.fillText(String(ymin.toPrecision(3)), 4, c.height-24);
  ctx.fillText(String((ymin+yspan).toPrecision(3)), 4, 26);
}

async function refreshCompare() {
  const resp = await apiFetch('/api/v1/bookmarks');
  if (!resp.ok) return authNote(resp);
  const runs = (await resp.json()).results.slice(0, 6);
  compareCache = await Promise.all(runs.map(async r => {
    const m = await apiFetch(`/api/v1/runs/${r.id}/metrics`).then(x=>x.json());
    const series = {};
    m.results.forEach((row, i) => Object.entries(row.values).forEach(([k,v]) => {
      if (typeof v==='number' && !k.startsWith('sys/'))
        (series[k] = series[k]||[]).push([row.step ?? i, v]);
    }));
    return {run: r, series};
  }));
  if (tab !== 'compare') return;
  // Last-metric table: one column per metric key in the union.
  const keys = [...new Set(compareCache.flatMap(
    c => Object.keys(c.run.last_metric||{}).filter(k=>!k.startsWith('sys/'))))];
  document.getElementById('cmp-head').innerHTML =
    `<tr><th>Run</th><th>Status</th>${keys.map(k=>`<th>${esc(k)}</th>`).join('')}</tr>`;
  document.getElementById('cmp-rows').innerHTML = compareCache.map(c => `
    <tr><td>#${Number(c.run.id)} ${esc(c.run.name||'')}</td>
    <td><span class="chip ${esc(c.run.status)}">${esc(c.run.status)}</span></td>
    ${keys.map(k => {
      const v = (c.run.last_metric||{})[k];
      return `<td>${typeof v==='number'?esc(v.toPrecision(4)):''}</td>`;
    }).join('')}</tr>`).join('')
    || '<tr><td class="dim">bookmark runs (☆ in the Runs tab) to compare them</td></tr>';
  fillSelect('cmp-metric',
    [...new Set(compareCache.flatMap(c => Object.keys(c.series)))]);
  drawCompare();
}

function drawCompare() {
  const key = document.getElementById('cmp-metric').value;
  const c = document.getElementById('cmp-chart'), ctx = c.getContext('2d');
  ctx.clearRect(0,0,c.width,c.height);
  const active = compareCache.filter(x => (x.series[key]||[]).length > 1);
  if (!active.length) return;
  const all = active.flatMap(x => x.series[key]);
  const xmin = Math.min(...all.map(p=>p[0])), xspan = (Math.max(...all.map(p=>p[0]))-xmin)||1;
  const ymin = Math.min(...all.map(p=>p[1])), yspan = (Math.max(...all.map(p=>p[1]))-ymin)||1;
  active.forEach((x, si) => {
    ctx.strokeStyle = COLORS[si%COLORS.length]; ctx.beginPath();
    x.series[key].forEach(([s,v], i) => {
      const px = 40 + (s-xmin)/xspan*(c.width-60);
      const py = c.height-20 - (v-ymin)/yspan*(c.height-40);
      i ? ctx.lineTo(px,py) : ctx.moveTo(px,py);
    });
    ctx.stroke();
  });
  document.getElementById('cmp-legend').innerHTML = active.map((x, si) =>
    `<span style="color:${COLORS[si%COLORS.length]}">■</span> #${Number(x.run.id)} ${esc(x.run.name||'')}`
  ).join(' &nbsp; ');
}

// Saved chart views (reference ChartViewModel): a named metric selection
// per run. chartSelection null = auto (first 6 series).
let chartSelection = null;
let lastChartRows = [];
let chartViews = [];

async function loadChartViews() {
  const resp = await apiFetch(`/api/v1/runs/${selected}/chart_views`);
  if (!resp.ok) { chartViews = []; return; }
  chartViews = (await resp.json()).results;
  const sel = document.getElementById('view-select');
  const keep = sel.value;
  sel.innerHTML = '<option value="">(all)</option>' + chartViews.map(v =>
    `<option value="${Number(v.id)}">${esc(v.name)}</option>`).join('');
  if ([...sel.options].some(o => o.value === keep)) sel.value = keep;
}

function applyView() {
  const id = document.getElementById('view-select').value;
  const view = chartViews.find(v => String(v.id) === id);
  chartSelection = view ? new Set(view.charts) : null;
  drawChart(lastChartRows);
}

async function saveView() {
  const name = document.getElementById('view-name').value.trim();
  if (!selected || !name) return;
  const charts = chartSelection ? [...chartSelection] : [...chartMetricNames];
  await apiFetch(`/api/v1/runs/${selected}/chart_views`, {
    method: 'POST',
    body: JSON.stringify({name, charts}),
  });
  await loadChartViews();
  document.getElementById('view-select').value =
    String((chartViews.find(v => v.name === name)||{}).id ?? '');
}

// Index-addressed (same rule as runSearchIdx): metric names are arbitrary
// user strings and must never be interpolated into inline JS.
let chartMetricNames = [];
function toggleMetricIdx(i) {
  const name = chartMetricNames[i];
  if (name === undefined) return;
  if (!chartSelection) chartSelection = new Set(chartMetricNames);
  if (chartSelection.has(name)) chartSelection.delete(name);
  else chartSelection.add(name);
  document.getElementById('view-select').value = '';
  drawChart(lastChartRows);
}

function drawChart(rows) {
  lastChartRows = rows;
  const c = document.getElementById('chart'), ctx = c.getContext('2d');
  ctx.clearRect(0,0,c.width,c.height);
  // [step, value] series keyed by metric name (step falls back to index).
  const series = {};
  rows.forEach((r, i) => Object.entries(r.values).forEach(([k,v]) => {
    if (typeof v==='number' && !k.startsWith('sys/'))
      (series[k] = series[k]||[]).push([r.step ?? i, v]);
  }));
  // Per-metric toggles (the saved-view building blocks).
  chartMetricNames = Object.keys(series);
  const picks = document.getElementById('metric-picks');
  picks.innerHTML = chartMetricNames.map((k, i) => {
    const on = !chartSelection || chartSelection.has(k);
    return `<label style="margin-right:12px"><input type="checkbox" ` +
      `${on?'checked':''} onchange="toggleMetricIdx(${Number(i)})"/> ${esc(k)}</label>`;
  }).join('');
  const entries = Object.entries(series)
    .filter(([k]) => !chartSelection || chartSelection.has(k))
    .slice(0,6)
    .filter(([,pts]) => pts.length > 1);
  const legend = document.getElementById('chart-legend');
  if (!entries.length) { legend.innerHTML = ''; return; }
  const L = 44, R = 10, Tp = 8, Bm = 22;
  // Shared x (steps); per-series y normalization — ranges live in the
  // legend so mixed scales (loss vs lr) stay readable on one canvas.
  const allx = entries.flatMap(([,pts]) => pts.map(p=>p[0]));
  const xmin = Math.min(...allx), xspan = (Math.max(...allx)-xmin)||1;
  ctx.strokeStyle = '#2a323c';
  ctx.beginPath();
  ctx.moveTo(L, Tp); ctx.lineTo(L, c.height-Bm);
  ctx.lineTo(c.width-R, c.height-Bm); ctx.stroke();
  ctx.fillStyle = '#8a949e';
  ctx.fillText(String(xmin), L, c.height-8);
  ctx.fillText(String(xmin+xspan), c.width-R-30, c.height-8);
  ctx.fillText('step →', (c.width-L)/2, c.height-8);
  entries.forEach(([name, pts], si) => {
    const ys = pts.map(p=>p[1]);
    const min = Math.min(...ys), max = Math.max(...ys), span = (max-min)||1;
    ctx.strokeStyle = COLORS[si%COLORS.length]; ctx.beginPath();
    pts.forEach(([s,v], i) => {
      const x = L + (s-xmin)/xspan*(c.width-L-R);
      const y = c.height-Bm - (v-min)/span*(c.height-Tp-Bm);
      i ? ctx.lineTo(x,y) : ctx.moveTo(x,y);
    });
    ctx.stroke();
  });
  legend.innerHTML = entries.map(([name, pts], si) => {
    const ys = pts.map(p=>p[1]);
    const last = ys[ys.length-1], min = Math.min(...ys), max = Math.max(...ys);
    return `<span style="color:${COLORS[si%COLORS.length]}">■</span> ` +
      `${esc(name)} <b>${esc(last.toPrecision(4))}</b> ` +
      `<span class="dim">[${esc(min.toPrecision(3))} … ${esc(max.toPrecision(3))}]</span>`;
  }).join(' &nbsp; ');
}

// -- query autocomplete off the backend's own grammar ------------------------
let vocab = null;
let suggestSel = -1;
async function loadVocab() {
  try {
    const resp = await apiFetch('/api/v1/query/fields');
    if (resp.ok) vocab = await resp.json();
  } catch (e) { /* autocomplete stays off without the vocabulary */ }
}
function querySuggestions(text) {
  if (!vocab) return [];
  // Complete the segment after the last comma: a bare prefix completes
  // field names; 'status:<prefix>' completes status values.
  const seg = text.slice(text.lastIndexOf(',')+1).trimStart();
  const colon = seg.indexOf(':');
  if (colon >= 0) {
    const field = seg.slice(0, colon), val = seg.slice(colon+1).replace(/^[~]/,'');
    if (field !== 'status') return [];
    return vocab.statuses.filter(s => s.startsWith(val))
      .map(s => ({text: s, hint: 'status', insert: `status:${s}`}));
  }
  const opts = [
    ...vocab.fields.map(f => ({text: f, hint: 'field'})),
    ...vocab.metric_keys.map(k => ({text: `metric.${k}`, hint: 'metric'})),
    ...vocab.param_keys.map(k => ({text: `declarations.${k}`, hint: 'param'})),
  ];
  return opts.filter(o => o.text.startsWith(seg) && o.text !== seg)
    .map(o => ({...o, insert: o.text + ':'}));
}
function renderSuggest() {
  const input = document.getElementById('query');
  const box = document.getElementById('qsuggest');
  const items = querySuggestions(input.value).slice(0, 12);
  if (!items.length) { box.style.display = 'none'; suggestSel = -1; return; }
  if (suggestSel >= items.length) suggestSel = items.length-1;
  box.innerHTML = items.map((o, i) =>
    `<div class="${i===suggestSel?'sel':''}" onmousedown="pickSuggest(${i})">` +
    `${esc(o.text)}<span>${esc(o.hint)}</span></div>`).join('');
  box.style.display = 'block';
  box.dataset.items = JSON.stringify(items);
}
function pickSuggest(i) {
  const box = document.getElementById('qsuggest');
  const items = JSON.parse(box.dataset.items || '[]');
  if (!items[i]) return;
  const input = document.getElementById('query');
  const cut = input.value.lastIndexOf(',')+1;
  const lead = input.value.slice(0, cut) + (cut ? ' ' : '');
  input.value = lead + items[i].insert;
  box.style.display = 'none'; suggestSel = -1;
  input.focus();
  if (items[i].hint === 'status') refreshRuns();
}
{
  const input = document.getElementById('query');
  input.addEventListener('input', () => { suggestSel = -1; renderSuggest(); });
  input.addEventListener('keydown', ev => {
    const box = document.getElementById('qsuggest');
    if (box.style.display !== 'block') return;
    const n = JSON.parse(box.dataset.items || '[]').length;
    if (ev.key === 'ArrowDown') { suggestSel = (suggestSel+1)%n; renderSuggest(); ev.preventDefault(); }
    else if (ev.key === 'ArrowUp') { suggestSel = (suggestSel-1+n)%n; renderSuggest(); ev.preventDefault(); }
    else if (ev.key === 'Tab' || (ev.key === 'Enter' && suggestSel >= 0)) {
      pickSuggest(suggestSel < 0 ? 0 : suggestSel); ev.preventDefault();
    }
    else if (ev.key === 'Escape') { box.style.display = 'none'; }
  });
  input.addEventListener('blur', () =>
    setTimeout(() => document.getElementById('qsuggest').style.display='none', 150));
}
document.getElementById('query').addEventListener('change', refreshRuns);
loadVocab(); setInterval(loadVocab, 30000);
refresh(); setInterval(refresh, 2000);
</script>
</body>
</html>
"""
