from polyaxon_tpu.schemas.environments import (
    EnvironmentConfig,
    MeshConfig,
    ResourcesConfig,
    RestartPolicyConfig,
    TopologyConfig,
)
from polyaxon_tpu.schemas.hptuning import (
    BOConfig,
    EarlyStoppingConfig,
    GridSearchConfig,
    HPTuningConfig,
    HyperbandConfig,
    RandomSearchConfig,
    SearchMetricConfig,
)
from polyaxon_tpu.schemas.matrix import MatrixConfig
from polyaxon_tpu.schemas.polyaxonfile import PolyaxonFile
from polyaxon_tpu.schemas.run import BuildConfig, RunConfig
from polyaxon_tpu.schemas.specifications import (
    BaseSpecification,
    ExperimentSpecification,
    GroupSpecification,
    JobSpecification,
    Kinds,
    PipelineSpecification,
    ServiceSpecification,
    specification_for_kind,
)

__all__ = [
    "MatrixConfig",
    "HPTuningConfig",
    "GridSearchConfig",
    "RandomSearchConfig",
    "HyperbandConfig",
    "BOConfig",
    "EarlyStoppingConfig",
    "SearchMetricConfig",
    "TopologyConfig",
    "EnvironmentConfig",
    "MeshConfig",
    "ResourcesConfig",
    "RestartPolicyConfig",
    "RunConfig",
    "BuildConfig",
    "Kinds",
    "BaseSpecification",
    "ExperimentSpecification",
    "GroupSpecification",
    "JobSpecification",
    "ServiceSpecification",
    "PipelineSpecification",
    "specification_for_kind",
    "PolyaxonFile",
]
