"""Hyperparameter-tuning section of a group spec.

Capability parity with ``polyaxon_schemas`` ``HPTuningConfig`` /
``SearchAlgorithms`` / ``EarlyStoppingConfig`` (re-exported by reference
``polyaxon/schemas/__init__.py:30-45``) as consumed by
``polyaxon/hpsearch/search_managers/*`` and
``polyaxon/db/models/experiment_groups.py:310-409``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field, field_validator, model_validator

from polyaxon_tpu.schemas.matrix import MatrixConfig


class Optimization:
    MAXIMIZE = "maximize"
    MINIMIZE = "minimize"
    VALUES = (MAXIMIZE, MINIMIZE)


class SearchAlgorithms:
    GRID = "grid"
    RANDOM = "random"
    HYPERBAND = "hyperband"
    BO = "bo"
    VALUES = (GRID, RANDOM, HYPERBAND, BO)


class _Base(BaseModel):
    model_config = ConfigDict(extra="forbid")


class SearchMetricConfig(_Base):
    """The target metric a search optimizes (e.g. loss / accuracy)."""

    name: str
    optimization: str = Optimization.MAXIMIZE

    @field_validator("optimization")
    @classmethod
    def _check_opt(cls, v: str) -> str:
        v = v.lower()
        if v not in Optimization.VALUES:
            raise ValueError(f"optimization must be one of {Optimization.VALUES}")
        return v


class EarlyStoppingConfig(_Base):
    """Stop the whole sweep once a metric crosses a threshold.

    Parity: reference group early-stopping check
    ``db/models/experiment_groups.py:326-344`` consumed before each start wave
    (``hpsearch/tasks/base.py:64-78``).
    """

    metric: SearchMetricConfig
    value: float
    policy: str = "all"  # reserved for future policies

    def passed(self, value: Optional[float]) -> bool:
        if value is None:
            return False
        if self.metric.optimization == Optimization.MAXIMIZE:
            return value >= self.value
        return value <= self.value


class GridSearchConfig(_Base):
    n_experiments: Optional[int] = Field(default=None, ge=1)


class RandomSearchConfig(_Base):
    n_experiments: int = Field(ge=1)
    seed: Optional[int] = None


class HyperbandConfig(_Base):
    """Hyperband bracket config.

    Parity: ``hpsearch/search_managers/hyperband.py:9-147`` — ``max_iterations``
    is R (max resource per trial), ``eta`` the down-sampling rate, ``resource``
    names the budget hyperparameter injected into suggestions.
    """

    max_iterations: int = Field(ge=1)
    eta: float = Field(gt=1)
    resource: SearchMetricConfig  # name + (ab)used: optimization unused
    metric: SearchMetricConfig
    resume: bool = False
    seed: Optional[int] = None


class GaussianProcessConfig(_Base):
    kernel: str = "matern"  # matern | rbf
    length_scale: float = 1.0
    nu: float = 1.5
    n_restarts_optimizer: int = 0

    @field_validator("kernel")
    @classmethod
    def _check_kernel(cls, v: str) -> str:
        if v not in ("matern", "rbf"):
            raise ValueError("kernel must be 'matern' or 'rbf'")
        return v


class UtilityFunctionConfig(_Base):
    """Acquisition function config (UCB kappa / EI-POI eps)."""

    acquisition_function: str = "ucb"  # ucb | ei | poi
    kappa: float = 2.576
    eps: float = 0.0
    gaussian_process: GaussianProcessConfig = GaussianProcessConfig()
    n_warmup: int = 200
    n_iter: int = 10

    @field_validator("acquisition_function")
    @classmethod
    def _check_acq(cls, v: str) -> str:
        if v not in ("ucb", "ei", "poi"):
            raise ValueError("acquisition_function must be ucb|ei|poi")
        return v


class BOConfig(_Base):
    """Bayesian-optimization config.

    Parity: ``hpsearch/search_managers/bayesian_optimization/manager.py:7-41``.
    """

    n_initial_trials: int = Field(ge=1)
    n_iterations: int = Field(ge=1)
    metric: SearchMetricConfig
    utility_function: UtilityFunctionConfig = UtilityFunctionConfig()
    seed: Optional[int] = None


class HPTuningConfig(_Base):
    """The ``hptuning`` section: matrix + exactly one search algorithm."""

    matrix: Dict[str, MatrixConfig]
    concurrency: int = Field(default=1, ge=1)
    grid_search: Optional[GridSearchConfig] = None
    random_search: Optional[RandomSearchConfig] = None
    hyperband: Optional[HyperbandConfig] = None
    bo: Optional[BOConfig] = None
    early_stopping: List[EarlyStoppingConfig] = Field(default_factory=list)
    seed: Optional[int] = None

    model_config = ConfigDict(extra="forbid", arbitrary_types_allowed=True)

    @field_validator("matrix", mode="before")
    @classmethod
    def _coerce_matrix(cls, v: Any) -> Dict[str, MatrixConfig]:
        if not isinstance(v, dict) or not v:
            raise ValueError("matrix must be a non-empty mapping")
        out = {}
        for name, entry in v.items():
            out[name] = entry if isinstance(entry, MatrixConfig) else MatrixConfig.from_dict(entry)
        return out

    @model_validator(mode="after")
    def _one_algorithm(self) -> "HPTuningConfig":
        set_algos = [
            a
            for a in ("grid_search", "random_search", "hyperband", "bo")
            if getattr(self, a) is not None
        ]
        if len(set_algos) > 1:
            raise ValueError(f"At most one search algorithm allowed, got {set_algos}")
        if self.hyperband is not None:
            resource = self.hyperband.resource.name
            if resource in self.matrix:
                raise ValueError(
                    f"Hyperband resource param {resource!r} must not appear in matrix"
                )
        if self.bo is not None:
            for name, m in self.matrix.items():
                if m.is_continuous and m.min is None:
                    raise ValueError(
                        f"BO requires bounded params; {name!r} ({m.op}) is unbounded"
                    )
        return self

    @property
    def search_algorithm(self) -> str:
        if self.grid_search is not None:
            return SearchAlgorithms.GRID
        if self.random_search is not None:
            return SearchAlgorithms.RANDOM
        if self.hyperband is not None:
            return SearchAlgorithms.HYPERBAND
        if self.bo is not None:
            return SearchAlgorithms.BO
        return SearchAlgorithms.GRID

    def to_dict(self) -> Dict[str, Any]:
        data = self.model_dump(exclude_none=True, exclude={"matrix"})
        data["matrix"] = {k: m.to_dict() for k, m in self.matrix.items()}
        return data
