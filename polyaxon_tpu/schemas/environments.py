"""Environment section: TPU topology, mesh, resources, restart policy.

This is the TPU-native replacement for the reference's per-framework
environment sections (``tensorflow: {n_workers, n_ps}``, ``horovod``,
``pytorch``, ``mxnet`` — consumed by ``polyaxon/polypod/{tensorflow,horovod,
pytorch,mxnet}.py``) and its k8s resources/node-selector blocks
(``polyaxon/polypod/templates/resources.py:40-45`` already sketched a
``resources.tpu`` key; ``tpu.py:6-11`` the TPU pod annotations).

Instead of replica counts per framework role, users declare a *topology*:
an accelerator slice plus a named mesh (axis → size).  The compiler turns
this into a gang plan (process count, coordinator, per-process env) and a
``jax.sharding.Mesh`` recipe; parallelism strategies (ddp/fsdp/tp/pp/
sp_ring/ulysses/ep) are sharding templates, not env-var dialects.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from pydantic import BaseModel, ConfigDict, Field, field_validator, model_validator

#: Known accelerator slice shapes: name -> (num_chips, num_hosts).
#: Chips-per-host follows the platform generation (v4/v5p: 4, v5e/v6e: 8,
#: cpu: virtual devices on one host for dev/test).
ACCELERATOR_CATALOG: Dict[str, Tuple[int, int]] = {
    "cpu": (8, 1),
    "cpu-1": (1, 1),
    "v4-8": (4, 1),
    "v4-16": (8, 2),
    "v4-32": (16, 4),
    "v5e-1": (1, 1),
    "v5e-4": (4, 1),
    "v5e-8": (8, 1),
    "v5e-16": (16, 2),
    "v5e-32": (32, 4),
    "v5e-64": (64, 8),
    "v5e-128": (128, 16),
    "v5e-256": (256, 32),
    "v5p-8": (4, 1),
    "v5p-16": (8, 2),
    "v5p-32": (16, 4),
    "v6e-8": (8, 1),
    "v6e-16": (16, 2),
    "v6e-32": (32, 4),
}

#: Canonical mesh axis names understood by the sharding templates
#: (polyaxon_tpu.parallel). Order matters: outermost (DCN-friendly) first,
#: innermost (ICI-bandwidth-hungry: tensor) last.
CANONICAL_AXES = ("replica", "data", "fsdp", "pipeline", "expert", "sequence", "tensor")

STRATEGIES = (
    "ddp", "fsdp", "tp", "tp_dp", "pp", "pp_tp", "sp_ring", "ulysses", "ep",
    "custom",
)


class MeshConfig(BaseModel):
    """Ordered logical mesh: axis name -> size. One axis may be -1 (infer)."""

    axes: Dict[str, int]

    model_config = ConfigDict(extra="forbid")

    @field_validator("axes")
    @classmethod
    def _check_axes(cls, v: Dict[str, int]) -> Dict[str, int]:
        if not v:
            raise ValueError("mesh must declare at least one axis")
        wildcards = [k for k, s in v.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError(f"At most one -1 axis allowed, got {wildcards}")
        for k, s in v.items():
            if s != -1 and s < 1:
                raise ValueError(f"Axis {k!r} must be >= 1 or -1, got {s}")
        return v

    @property
    def names(self) -> List[str]:
        return list(self.axes)

    def resolve(self, num_devices: int) -> Dict[str, int]:
        """Fill a -1 wildcard axis and check the product matches devices."""
        axes = dict(self.axes)
        wildcard = next((k for k, s in axes.items() if s == -1), None)
        known = math.prod(s for s in axes.values() if s != -1)
        if wildcard is not None:
            if num_devices % known != 0:
                raise ValueError(
                    f"Cannot infer axis {wildcard!r}: {num_devices} devices not "
                    f"divisible by {known}"
                )
            axes[wildcard] = num_devices // known
        elif known != num_devices:
            raise ValueError(
                f"Mesh product {known} != device count {num_devices} ({axes})"
            )
        return axes


class TopologyConfig(BaseModel):
    """Accelerator slice + logical mesh + parallelism strategy."""

    accelerator: str = "cpu"
    num_hosts: Optional[int] = Field(default=None, ge=1)
    num_devices: Optional[int] = Field(default=None, ge=1)
    mesh: Optional[MeshConfig] = None
    #: Multi-slice (DCN/megascale): per-slice topology above, slice count
    #: here. The dcn_axis becomes the leading mesh axis spanning slices —
    #: keep it a data-like axis (default "replica") so only the gradient
    #: all-reduce rides DCN while bandwidth-hungry axes stay on ICI.
    num_slices: int = Field(default=1, ge=1)
    dcn_axis: str = "replica"
    strategy: str = "ddp"
    #: Extra knobs for templates (e.g. microbatches for pp, ring chunk size).
    strategy_options: Dict[str, Any] = Field(default_factory=dict)

    model_config = ConfigDict(extra="forbid")

    @field_validator("mesh", mode="before")
    @classmethod
    def _coerce_mesh(cls, v: Any) -> Any:
        if isinstance(v, dict) and "axes" not in v:
            return {"axes": v}
        return v

    @field_validator("strategy")
    @classmethod
    def _check_strategy(cls, v: str) -> str:
        if v not in STRATEGIES:
            raise ValueError(f"strategy must be one of {STRATEGIES}")
        return v

    @model_validator(mode="after")
    def _fill_from_catalog(self) -> "TopologyConfig":
        cat = ACCELERATOR_CATALOG.get(self.accelerator)
        if cat is not None:
            chips, hosts = cat
            if self.num_devices is None:
                self.num_devices = chips
            if self.num_hosts is None:
                self.num_hosts = hosts
        else:
            if self.num_devices is None or self.num_hosts is None:
                raise ValueError(
                    f"Unknown accelerator {self.accelerator!r}: set num_devices "
                    f"and num_hosts explicitly (known: {sorted(ACCELERATOR_CATALOG)})"
                )
        if self.num_devices % self.num_hosts != 0:
            raise ValueError(
                f"num_devices ({self.num_devices}) must be divisible by "
                f"num_hosts ({self.num_hosts})"
            )
        if self.mesh is not None:
            self.mesh.resolve(self.num_devices)  # raises if inconsistent
        if self.num_slices > 1:
            # The cross-slice axis must be data-like: anything else (tensor/
            # sequence/pipeline) would put bandwidth-hungry collectives on
            # the slow DCN link. And it must not collide with a per-slice
            # axis — this check runs against the RESOLVED mesh so the
            # default {'data': N} case is covered too.
            data_like = ("replica", "data", "fsdp")
            if self.dcn_axis not in data_like:
                raise ValueError(
                    f"dcn_axis {self.dcn_axis!r} must be a data-like axis "
                    f"{data_like}: cross-slice (DCN) bandwidth only suits "
                    "batch-gradient traffic"
                )
            if self.dcn_axis in self.resolved_mesh():
                raise ValueError(
                    f"dcn_axis {self.dcn_axis!r} collides with a per-slice "
                    "(ICI) mesh axis; the cross-slice axis must differ"
                )
        return self

    def resolved_mesh(self) -> Dict[str, int]:
        """Per-slice (ICI) axis->size mapping (default: pure data parallel)."""
        if self.mesh is None:
            return {"data": int(self.num_devices)}
        return self.mesh.resolve(int(self.num_devices))

    def resolved_dcn(self) -> Dict[str, int]:
        """The cross-slice (DCN) axes; empty for single-slice runs."""
        if self.num_slices <= 1:
            return {}
        return {self.dcn_axis: int(self.num_slices)}

    @property
    def devices_per_host(self) -> int:
        return int(self.num_devices) // int(self.num_hosts)


class ResourcesConfig(BaseModel):
    """Host-process resource requests (the reference's k8s resources block)."""

    cpu: Optional[float] = None
    memory_mb: Optional[int] = None
    tpu: Optional[int] = None

    model_config = ConfigDict(extra="forbid")


class RestartPolicyConfig(BaseModel):
    """Gang restart policy.

    Parity: reference ``polypod/templates/restart_policy.py`` (max_restarts on
    pods).  Gang semantics here: any process failure tears down and restarts
    the whole gang (jax.distributed worlds are all-or-nothing).
    """

    max_restarts: int = Field(default=0, ge=0)
    backoff_seconds: float = Field(default=1.0, ge=0)

    model_config = ConfigDict(extra="forbid")


class EnvironmentConfig(BaseModel):
    topology: TopologyConfig = Field(default_factory=TopologyConfig)
    resources: Optional[ResourcesConfig] = None
    restart_policy: RestartPolicyConfig = Field(default_factory=RestartPolicyConfig)
    seed: Optional[int] = None
    env_vars: Dict[str, str] = Field(default_factory=dict)

    model_config = ConfigDict(extra="forbid")
