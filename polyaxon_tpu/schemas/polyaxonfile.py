"""Polyaxonfile loading: YAML/JSON document -> typed specification.

Parity: external ``polyaxon_schemas`` ``PolyaxonFile`` (re-exported by
reference ``polyaxon/schemas/__init__.py:20``), as validated server-side by
``polyaxon/libs/spec_validation.py``.  A group is auto-detected when an
``hptuning`` (or legacy ``matrix``) section is present, mirroring the
reference CLI behavior.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Union

import yaml

from polyaxon_tpu.exceptions import SchemaError
from polyaxon_tpu.schemas.specifications import (
    BaseSpecification,
    Kinds,
    specification_for_kind,
)


class PolyaxonFile:
    """Load + validate a spec document from a path, string, or dict."""

    def __init__(self, data: Dict[str, Any]) -> None:
        if not isinstance(data, dict):
            raise SchemaError(f"Spec document must be a mapping, got {type(data)}")
        self._data = self._normalize(dict(data))
        spec_cls = specification_for_kind(self._data["kind"])
        self.specification: BaseSpecification = spec_cls.from_dict(self._data)

    @staticmethod
    def _normalize(data: Dict[str, Any]) -> Dict[str, Any]:
        if "matrix" in data and "hptuning" not in data:
            # legacy top-level matrix section → hptuning.matrix
            data["hptuning"] = {"matrix": data.pop("matrix")}
        if "kind" not in data:
            data["kind"] = Kinds.GROUP if "hptuning" in data else Kinds.EXPERIMENT
        if data.get("kind") == Kinds.EXPERIMENT and "hptuning" in data:
            data["kind"] = Kinds.GROUP
        return data

    @classmethod
    def from_path(cls, path: Union[str, os.PathLike]) -> "PolyaxonFile":
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
        return cls.from_str(raw)

    @classmethod
    def from_str(cls, raw: str) -> "PolyaxonFile":
        raw = raw.strip()
        if not raw:
            raise SchemaError("Empty spec document")
        if raw.startswith("{"):
            try:
                return cls(json.loads(raw))
            except json.JSONDecodeError as e:
                raise SchemaError(f"Invalid JSON spec: {e}") from e
        try:
            data = yaml.safe_load(raw)
        except yaml.YAMLError as e:
            raise SchemaError(f"Invalid YAML spec: {e}") from e
        return cls(data)

    @classmethod
    def load(cls, source: Union[str, os.PathLike, Dict[str, Any]]) -> "PolyaxonFile":
        if isinstance(source, dict):
            return cls(source)
        if isinstance(source, (str, os.PathLike)) and os.path.exists(str(source)):
            return cls.from_path(source)
        if isinstance(source, str):
            return cls.from_str(source)
        raise SchemaError(f"Cannot load spec from {source!r}")

    @property
    def kind(self) -> str:
        return self.specification.kind

    def to_dict(self) -> Dict[str, Any]:
        return self.specification.to_dict()
