"""Hyperparameter matrix ops.

Capability parity with the external ``polyaxon_schemas`` ``MatrixConfig``
(re-exported by reference ``polyaxon/schemas/__init__.py:1-60`` and consumed
by every hpsearch search manager, e.g.
``polyaxon/hpsearch/search_managers/grid.py:7-31``).

Supported ops — grid-able: ``values``, ``range``, ``linspace``, ``logspace``,
``geomspace``; distributions: ``pvalues``, ``uniform``, ``quniform``,
``loguniform``, ``qloguniform``, ``normal``, ``qnormal``, ``lognormal``,
``qlognormal``.

Range-like arguments accept ``[start, stop, step_or_num]`` lists,
``"start:stop:step_or_num"`` strings, or ``{start:, stop:, step:|num:}``
dicts.  All sampling is numpy-Generator based and deterministic under a seed.
"""

from __future__ import annotations

import numbers
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from polyaxon_tpu.exceptions import SchemaError

GRID_OPS = ("values", "range", "linspace", "logspace", "geomspace")
DIST_OPS = (
    "pvalues",
    "uniform",
    "quniform",
    "loguniform",
    "qloguniform",
    "normal",
    "qnormal",
    "lognormal",
    "qlognormal",
)
ALL_OPS = GRID_OPS + DIST_OPS


def _parse_3(value: Any, keys: Sequence[str]) -> List[float]:
    """Normalize range-ish params to [a, b, c] floats."""
    if isinstance(value, str):
        parts = value.split(":")
    elif isinstance(value, dict):
        missing = [k for k in keys if k not in value]
        if missing:
            raise SchemaError(f"Missing keys {missing} in {value!r}")
        parts = [value[k] for k in keys]
    elif isinstance(value, (list, tuple)):
        parts = list(value)
    else:
        raise SchemaError(f"Cannot parse range argument {value!r}")
    if len(parts) != 3:
        raise SchemaError(f"Expected 3 elements (got {len(parts)}): {value!r}")
    try:
        return [float(p) for p in parts]
    except (TypeError, ValueError) as e:
        raise SchemaError(f"Non-numeric range argument {value!r}") from e


def _parse_2(value: Any, keys: Sequence[str] = ("low", "high")) -> List[float]:
    if isinstance(value, str):
        parts = value.split(":")
    elif isinstance(value, dict):
        parts = [value[k] for k in keys if k in value]
    elif isinstance(value, (list, tuple)):
        parts = list(value)
    else:
        raise SchemaError(f"Cannot parse argument {value!r}")
    if len(parts) != 2:
        raise SchemaError(f"Expected 2 elements (got {len(parts)}): {value!r}")
    try:
        return [float(p) for p in parts]
    except (TypeError, ValueError) as e:
        raise SchemaError(f"Non-numeric argument {value!r}") from e


def _quantize(sample: float, q: float) -> float:
    return float(np.round(sample / q) * q)


class MatrixConfig:
    """One hyperparameter's search space: exactly one op + its argument."""

    def __init__(self, op: str, params: Any) -> None:
        if op not in ALL_OPS:
            raise SchemaError(f"Unknown matrix op {op!r}; one of {ALL_OPS}")
        self.op = op
        self.params = params
        self._validate()

    # -- construction --------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "MatrixConfig":
        if not isinstance(data, dict):
            raise SchemaError(f"Matrix entry must be a mapping, got {data!r}")
        ops = [k for k in data if k in ALL_OPS]
        if len(ops) != 1:
            raise SchemaError(
                f"Matrix entry must contain exactly one op from {ALL_OPS}, got {list(data)}"
            )
        return cls(ops[0], data[ops[0]])

    def to_dict(self) -> Dict[str, Any]:
        return {self.op: self.params}

    def _validate(self) -> None:
        op, p = self.op, self.params
        if op == "values":
            if not isinstance(p, (list, tuple)) or not p:
                raise SchemaError(f"`values` needs a non-empty list, got {p!r}")
        elif op == "pvalues":
            pairs = [tuple(v) for v in p]
            probs = [pr for _, pr in pairs]
            if not np.isclose(sum(probs), 1.0):
                raise SchemaError(f"`pvalues` probabilities must sum to 1, got {sum(probs)}")
            self.params = pairs
        elif op == "range":
            self.params = _parse_3(p, ("start", "stop", "step"))
            if self.params[2] == 0:
                raise SchemaError("`range` step must be non-zero")
        elif op in ("linspace", "logspace", "geomspace"):
            self.params = _parse_3(p, ("start", "stop", "num"))
            if int(self.params[2]) < 1:
                raise SchemaError(f"`{op}` num must be >= 1")
        elif op in ("uniform", "loguniform"):
            self.params = _parse_2(p)
        elif op in ("quniform", "qloguniform"):
            self.params = _parse_3(p, ("low", "high", "q"))
        elif op in ("normal", "lognormal"):
            self.params = _parse_2(p, ("loc", "scale"))
        elif op in ("qnormal", "qlognormal"):
            self.params = _parse_3(p, ("loc", "scale", "q"))

    # -- introspection -------------------------------------------------------
    @property
    def is_distribution(self) -> bool:
        return self.op in DIST_OPS

    @property
    def is_categorical(self) -> bool:
        if self.op == "pvalues":
            return True
        return self.op == "values" and any(
            not isinstance(v, numbers.Number) for v in self.params
        )

    @property
    def is_discrete(self) -> bool:
        return not self.is_distribution or self.op == "pvalues"

    @property
    def is_continuous(self) -> bool:
        return not self.is_discrete

    @property
    def is_uniform(self) -> bool:
        return self.op == "uniform"

    @property
    def min(self) -> Optional[float]:
        if self.is_categorical:
            return None
        if self.op == "values":
            return float(min(self.params))
        if self.op in ("range", "linspace", "logspace", "geomspace"):
            return float(min(self.to_numpy()))
        if self.op in ("uniform", "loguniform"):
            return self.params[0]
        if self.op in ("quniform", "qloguniform"):
            return self.params[0]
        return None  # unbounded (normal family)

    @property
    def max(self) -> Optional[float]:
        if self.is_categorical:
            return None
        if self.op == "values":
            return float(max(self.params))
        if self.op in ("range", "linspace", "logspace", "geomspace"):
            return float(max(self.to_numpy()))
        if self.op in ("uniform", "loguniform"):
            return self.params[1]
        if self.op in ("quniform", "qloguniform"):
            return self.params[1]
        return None

    @property
    def length(self) -> Optional[int]:
        """Cardinality for grid-able ops, None for continuous distributions."""
        if self.op in ("values", "pvalues"):
            return len(self.params)
        if self.op == "range":
            start, stop, step = self.params
            return len(np.arange(start, stop, step))
        if self.op in ("linspace", "logspace", "geomspace"):
            return int(self.params[2])
        return None

    # -- materialization -----------------------------------------------------
    def to_numpy(self) -> np.ndarray:
        """Enumerate grid values; raises for continuous distributions."""
        op, p = self.op, self.params
        if op == "values":
            return np.asarray(p)
        if op == "pvalues":
            return np.asarray([v for v, _ in p])
        if op == "range":
            return np.arange(p[0], p[1], p[2])
        if op == "linspace":
            return np.linspace(p[0], p[1], int(p[2]))
        if op == "logspace":
            return np.logspace(p[0], p[1], int(p[2]))
        if op == "geomspace":
            return np.geomspace(p[0], p[1], int(p[2]))
        raise SchemaError(f"Op {self.op!r} is a distribution; use sample()")

    def sample(self, rng: Optional[np.random.Generator] = None) -> Any:
        """Draw one value (grid ops sample uniformly from their grid)."""
        rng = rng if rng is not None else np.random.default_rng()
        op, p = self.op, self.params
        if op in GRID_OPS:
            vals = self.to_numpy()
            pick = vals[int(rng.integers(len(vals)))]
            return pick.item() if hasattr(pick, "item") else pick
        if op == "pvalues":
            idx = rng.choice(len(p), p=[pr for _, pr in p])
            return p[int(idx)][0]
        if op == "uniform":
            return float(rng.uniform(p[0], p[1]))
        if op == "quniform":
            return _quantize(rng.uniform(p[0], p[1]), p[2])
        if op == "loguniform":
            return float(np.exp(rng.uniform(np.log(p[0]), np.log(p[1]))))
        if op == "qloguniform":
            return _quantize(np.exp(rng.uniform(np.log(p[0]), np.log(p[1]))), p[2])
        if op == "normal":
            return float(rng.normal(p[0], p[1]))
        if op == "qnormal":
            return _quantize(rng.normal(p[0], p[1]), p[2])
        if op == "lognormal":
            return float(rng.lognormal(p[0], p[1]))
        if op == "qlognormal":
            return _quantize(rng.lognormal(p[0], p[1]), p[2])
        raise SchemaError(f"Unhandled op {op!r}")

    def __repr__(self) -> str:
        return f"MatrixConfig({self.op}={self.params!r})"

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, MatrixConfig)
            and self.op == other.op
            and self.params == other.params
        )
