"""Typed specifications: the compiled, validated form of a polyaxonfile.

Capability parity with the external ``polyaxon_schemas`` Specification
classes re-exported by reference ``polyaxon/schemas/__init__.py:46-60``
(``ExperimentSpecification``, ``GroupSpecification``, ``JobSpecification``,
``NotebookSpecification``, ``TensorboardSpecification``, ...) and with the
framework cluster-definition logic in ``polypod/tensorflow.py:10-123``
(cluster_def / per-task resources).  TPU-native difference: ``cluster_def``
becomes a *gang plan* (num_hosts × devices/host + mesh axes) instead of
{master/worker/ps: addresses}.
"""

from __future__ import annotations

import copy
import re
from typing import Any, Dict, List, Optional, Tuple

from pydantic import BaseModel, ConfigDict, Field, field_validator

from polyaxon_tpu.exceptions import SchemaError
from polyaxon_tpu.schemas.environments import EnvironmentConfig
from polyaxon_tpu.schemas.hptuning import HPTuningConfig
from polyaxon_tpu.schemas.run import BuildConfig, RunConfig


class Kinds:
    EXPERIMENT = "experiment"
    GROUP = "group"
    JOB = "job"
    BUILD = "build"
    NOTEBOOK = "notebook"
    TENSORBOARD = "tensorboard"
    #: Generic long-running service; the built-in entrypoint is the LM
    #: inference server (checkpoint → REST /generate).
    SERVICE = "service"
    PIPELINE = "pipeline"
    VALUES = (
        EXPERIMENT, GROUP, JOB, BUILD, NOTEBOOK, TENSORBOARD, SERVICE,
        PIPELINE,
    )


_TEMPLATE_RE = re.compile(r"\{\{\s*([\w.]+)\s*\}\}")


def interpolate(value: Any, params: Dict[str, Any]) -> Any:
    """Substitute ``{{ name }}`` templates with declaration values.

    Dotted names traverse nested dicts.  A string that is exactly one
    template resolves to the raw value (keeping its type); mixed strings
    render values inline.  Parity: the reference's jinja declarations
    (``tests/fixtures_static/advanced_file.yml``), restricted to variable
    substitution (no for/if — control flow belongs in python entrypoints).
    """

    def lookup(name: str) -> Any:
        node: Any = params
        for part in name.split("."):
            if not isinstance(node, dict) or part not in node:
                raise SchemaError(f"Unknown template variable {name!r}")
            node = node[part]
        return node

    if isinstance(value, str):
        exact = _TEMPLATE_RE.fullmatch(value.strip())
        if exact:
            return lookup(exact.group(1))
        return _TEMPLATE_RE.sub(lambda m: str(lookup(m.group(1))), value)
    if isinstance(value, dict):
        return {k: interpolate(v, params) for k, v in value.items()}
    if isinstance(value, list):
        return [interpolate(v, params) for v in value]
    return value


class BaseSpecification(BaseModel):
    """Common document shape. ``declarations`` are the run's hyperparameters."""

    version: int = 1
    kind: str
    name: Optional[str] = None
    description: Optional[str] = None
    tags: List[str] = Field(default_factory=list)
    declarations: Dict[str, Any] = Field(default_factory=dict)
    environment: EnvironmentConfig = Field(default_factory=EnvironmentConfig)
    build: Optional[BuildConfig] = None

    model_config = ConfigDict(extra="forbid")

    @field_validator("version")
    @classmethod
    def _check_version(cls, v: int) -> int:
        if v != 1:
            raise ValueError(f"Unsupported spec version {v}")
        return v

    # -- gang plan (cluster_def equivalent) -----------------------------------
    @property
    def gang_def(self) -> Tuple[int, int]:
        """(num_hosts, devices_per_host) — replaces reference cluster_def."""
        topo = self.environment.topology
        return int(topo.num_hosts), topo.devices_per_host

    @property
    def mesh_axes(self) -> Dict[str, int]:
        return self.environment.topology.resolved_mesh()

    def to_dict(self) -> Dict[str, Any]:
        data = self.model_dump(exclude_none=True)
        return data

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "BaseSpecification":
        try:
            return cls.model_validate(data)
        except Exception as e:  # normalize pydantic errors to SchemaError
            raise SchemaError(str(e)) from e


class ExperimentSpecification(BaseSpecification):
    kind: str = Kinds.EXPERIMENT
    run: RunConfig

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: str) -> str:
        if v != Kinds.EXPERIMENT:
            raise ValueError(f"Expected kind=experiment, got {v!r}")
        return v

    def resolved_run(self) -> RunConfig:
        """Run section with declarations interpolated."""
        data = self.run.model_dump()
        return RunConfig.model_validate(interpolate(data, self.declarations))


class JobSpecification(ExperimentSpecification):
    """Generic run-once job (reference ``polypod/job.py``): same shape as an
    experiment but without metric/hptuning semantics."""

    kind: str = Kinds.JOB

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: str) -> str:
        if v not in (Kinds.JOB, Kinds.BUILD):
            raise ValueError(f"Expected kind=job|build, got {v!r}")
        return v


class ServiceSpecification(BaseSpecification):
    """Long-running service (notebook / tensorboard / dashboard).

    Parity: reference ``polypod/notebook.py:35``, ``polypod/tensorboard.py:32``.
    """

    kind: str = Kinds.NOTEBOOK
    run: Optional[RunConfig] = None
    port: int = 0  # 0 = auto-assign

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: str) -> str:
        if v not in (Kinds.NOTEBOOK, Kinds.TENSORBOARD, Kinds.SERVICE):
            raise ValueError(
                f"Expected kind=notebook|tensorboard|service, got {v!r}"
            )
        return v

    def resolved_run(self) -> RunConfig:
        """Run section with declarations interpolated (same contract as
        experiments — services routinely template their serving port).

        A service spec with no run section gets its built-in entrypoint:
        tensorboard over a target run's outputs
        (reference ``polypod/tensorboard.py:32``), JupyterLab for
        notebooks (reference ``polypod/notebook.py:35``), or the LM
        inference server for ``kind: service`` (serving is capability
        beyond the reference).
        """
        if self.run is None:
            builtins_by_kind = {
                Kinds.TENSORBOARD: "polyaxon_tpu.builtins.services:tensorboard",
                Kinds.NOTEBOOK: "polyaxon_tpu.builtins.services:jupyter",
                Kinds.SERVICE: "polyaxon_tpu.builtins.services:lm_server",
            }
            entrypoint = builtins_by_kind.get(self.kind)
            if entrypoint is None:
                raise ValueError(f"Service spec {self.kind!r} has no run section")
            return RunConfig(entrypoint=entrypoint)
        data = self.run.model_dump()
        return RunConfig.model_validate(interpolate(data, self.declarations))


class GroupSpecification(BaseSpecification):
    """An hptuning sweep over an experiment template.

    Parity: reference ``GroupSpecification`` + the bridge used by hpsearch:
    ``spec.get_experiment_spec(matrix_declaration)``
    (``hpsearch/tasks/base.py:33-55``).
    """

    kind: str = Kinds.GROUP
    run: RunConfig
    hptuning: HPTuningConfig

    model_config = ConfigDict(extra="forbid", arbitrary_types_allowed=True)

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: str) -> str:
        if v != Kinds.GROUP:
            raise ValueError(f"Expected kind=group, got {v!r}")
        return v

    def to_dict(self) -> Dict[str, Any]:
        # model_dump leaves MatrixConfig instances embedded (the field is
        # arbitrary-typed); route through HPTuningConfig.to_dict so the result
        # is json-serializable.
        data = super().to_dict()
        data["hptuning"] = self.hptuning.to_dict()
        return data

    def get_experiment_spec(self, matrix_declaration: Dict[str, Any]) -> ExperimentSpecification:
        """Materialize one trial: group spec minus hptuning, declarations
        merged with the suggestion (suggestion wins)."""
        data = self.model_dump(exclude_none=True, exclude={"hptuning"})
        data["kind"] = Kinds.EXPERIMENT
        data["declarations"] = {**copy.deepcopy(self.declarations), **matrix_declaration}
        return ExperimentSpecification.model_validate(data)

    @property
    def matrix_space(self) -> Optional[int]:
        """Grid cardinality, None if any param is a continuous distribution."""
        total = 1
        for m in self.hptuning.matrix.values():
            n = m.length
            if n is None:
                return None
            total *= n
        return total


class PipelineSpecification(BaseSpecification):
    """DAG-of-operations spec (reference ``polyflow`` + ``db/models/pipelines.py``).

    ``ops`` is a list of {name, template|run sections, dependencies: [names]}.
    """

    kind: str = Kinds.PIPELINE
    ops: List[Dict[str, Any]] = Field(default_factory=list)
    concurrency: Optional[int] = None

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v: str) -> str:
        if v != Kinds.PIPELINE:
            raise ValueError(f"Expected kind=pipeline, got {v!r}")
        return v

    @field_validator("ops")
    @classmethod
    def _check_ops(cls, v: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        names = [op.get("name") for op in v]
        if any(n is None for n in names):
            raise ValueError("every pipeline op needs a name")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate op names in pipeline: {names}")
        known = set(names)
        for op in v:
            for dep in op.get("dependencies", []):
                if dep not in known:
                    raise ValueError(f"op {op['name']!r} depends on unknown op {dep!r}")
        return v


_KIND_TO_SPEC = {
    Kinds.EXPERIMENT: ExperimentSpecification,
    Kinds.GROUP: GroupSpecification,
    Kinds.JOB: JobSpecification,
    Kinds.BUILD: JobSpecification,
    Kinds.NOTEBOOK: ServiceSpecification,
    Kinds.TENSORBOARD: ServiceSpecification,
    Kinds.SERVICE: ServiceSpecification,
    Kinds.PIPELINE: PipelineSpecification,
}


def specification_for_kind(kind: str) -> type:
    try:
        return _KIND_TO_SPEC[kind]
    except KeyError:
        raise SchemaError(f"Unknown kind {kind!r}; one of {Kinds.VALUES}") from None
