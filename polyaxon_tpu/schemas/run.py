"""Run + build sections of a spec.

The reference's ``run: {cmd: ...}`` launched a user container; its ``build``
section produced a Docker image (``polyaxon/dockerizer/``).  TPU-native
equivalents: ``run`` is either a shell command or an in-process python
entrypoint ``module:function`` (preferred — the trainer then runs inside the
managed ``jax.distributed`` world); ``build`` is a content-addressed code
snapshot (no containers).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field, model_validator

_ENTRYPOINT_RE = re.compile(r"^[A-Za-z_][\w.]*:[A-Za-z_]\w*$")


class RunConfig(BaseModel):
    """What to execute on every gang process."""

    cmd: Optional[str] = None
    entrypoint: Optional[str] = None  # "package.module:function"
    #: Extra kwargs passed to the entrypoint (beyond declarations).
    kwargs: Dict[str, Any] = Field(default_factory=dict)

    model_config = ConfigDict(extra="forbid")

    @model_validator(mode="after")
    def _exactly_one(self) -> "RunConfig":
        if bool(self.cmd) == bool(self.entrypoint):
            raise ValueError("run must set exactly one of cmd / entrypoint")
        if self.entrypoint and not _ENTRYPOINT_RE.match(self.entrypoint):
            raise ValueError(
                f"entrypoint must look like 'pkg.module:function', got {self.entrypoint!r}"
            )
        return self


class BuildConfig(BaseModel):
    """Code snapshot config (dockerizer equivalent, container-free).

    Parity: reference ``polyaxon/dockerizer/dockerizer/initializer/*`` download
    + extract + generate; here: snapshot ``context`` into the content-addressed
    artifact store, so runs are reproducible and restartable byte-for-byte.
    """

    context: str = "."
    include: List[str] = Field(default_factory=lambda: ["**/*.py", "**/*.yaml", "**/*.yml"])
    exclude: List[str] = Field(default_factory=lambda: ["**/__pycache__/**", ".git/**"])
    ref: Optional[str] = None  # pre-existing snapshot hash to reuse

    model_config = ConfigDict(extra="forbid")
