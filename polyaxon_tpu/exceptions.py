"""Framework exception hierarchy.

Parity: the reference scatters these across packages
(``polyaxon/libs/exceptions.py``, DRF validation errors, schema
``ValidationError`` from marshmallow). Here they are one hierarchy.
"""


class PolyaxonTPUError(Exception):
    """Base class for all framework errors."""


class SchemaError(PolyaxonTPUError):
    """A spec/polyaxonfile failed validation."""


class CompilerError(PolyaxonTPUError):
    """A spec could not be compiled into an executable plan."""


class LifecycleError(PolyaxonTPUError):
    """An illegal status transition was requested."""


class StoreError(PolyaxonTPUError):
    """Artifact/log store operation failed."""


class SpawnerError(PolyaxonTPUError):
    """Gang spawn / teardown failed."""


class RuntimeLayerError(PolyaxonTPUError):
    """Mesh/sharding/runtime setup failed."""


class QueryError(PolyaxonTPUError):
    """Search/filter query DSL parse or build failed."""


class NotFoundError(PolyaxonTPUError):
    """Entity not found in the run registry."""
