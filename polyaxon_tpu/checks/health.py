"""Health-check framework.

Parity: reference ``checks/`` (postgres/redis/rabbitmq/disk/memory probes +
per-service worker round-trips, ``checks/worker.py:14-40``) surfaced at
``/status`` (``api/index/status.py``).  TPU-native: the moving parts are
the sqlite registry, the task bus, the store filesystem, and the
accelerator backend — each gets a probe; the report is the ``/status``
payload.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Callable, Dict, Tuple


def check_registry(orch) -> Tuple[bool, str]:
    try:
        orch.registry.count_by_status()
        return True, "ok"
    except Exception as e:  # pragma: no cover - exercised via fault tests
        return False, f"registry error: {e}"


def check_bus(orch) -> Tuple[bool, str]:
    bus = orch.bus
    n_errors = len(getattr(bus, "errors", ()))
    detail = f"{bus.pending()} pending, {n_errors} dead-lettered tasks"
    # Dead-lettered tasks are diagnostic, not fatal — the bus itself is
    # healthy as long as it can report.
    return True, detail


def check_stores(orch) -> Tuple[bool, str]:
    base = orch.layout.base_dir
    if not os.access(base, os.W_OK):
        return False, f"store base dir {base} not writable"
    usage = shutil.disk_usage(base)
    free_frac = usage.free / usage.total
    if free_frac < 0.05:
        return False, f"disk nearly full ({free_frac:.1%} free)"
    return True, f"{free_frac:.0%} free"


def check_heartbeats(orch) -> Tuple[bool, str]:
    """Running runs with stale heartbeats — the zombie cron's worklist,
    surfaced here as diagnostic detail (the cron, not /status, acts on
    it; a wedged worker doesn't make the control plane unhealthy)."""
    ttl = getattr(getattr(orch, "ctx", None), "heartbeat_ttl", None) or 600.0
    stale = orch.registry.zombie_runs(ttl)
    if not stale:
        return True, "no stale heartbeats"
    ids = ", ".join(str(r.id) for r in stale[:5])
    more = f" (+{len(stale) - 5} more)" if len(stale) > 5 else ""
    return True, (
        f"{len(stale)} running run(s) with heartbeat older than "
        f"{ttl:.0f}s: {ids}{more}"
    )


def check_compile_cache(orch) -> Tuple[bool, str]:
    """Persistent compile cache readiness: the per-layout cache dir must
    be creatable and writable (workers of every gang root their cache
    there).  Whether THIS process enabled it is diagnostic only — the
    control plane never compiles; workers arm it at boot."""
    from polyaxon_tpu.runtime.compilecache import cache_status

    cache_dir = orch.layout.compile_cache_dir
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
    except OSError as e:
        return False, f"cache dir {cache_dir} not creatable: {e}"
    if not os.access(cache_dir, os.W_OK):
        return False, f"cache dir {cache_dir} not writable"
    try:
        entries = sum(1 for _ in cache_dir.iterdir())
    except OSError:
        entries = 0
    st = cache_status()
    local = (
        f"enabled at {st.cache_dir}"
        if st.enabled
        else f"this process: {st.reason}"
    )
    return True, f"{entries} cached executable(s) at {cache_dir}; {local}"


def check_alerts(orch) -> Tuple[bool, str]:
    """Alert-engine liveness: is the rule engine ticking, and are rule
    evaluations erroring (counted, never raised — so /status is the place
    they surface).  Unhealthy only when gangs are live but the engine has
    not ticked for many multiples of its interval — an idle control plane
    legitimately never ticks."""
    engine = getattr(orch, "alerts", None)
    if engine is None:
        return True, "alert engine not wired"
    st = engine.status()
    errors = f", {st['eval_errors']} rule-eval error(s)" if st["eval_errors"] else ""
    gangs = getattr(getattr(orch, "ctx", None), "gangs", None) or {}
    if not st["ticks"]:
        if gangs:
            return False, (
                f"{len(gangs)} live gang(s) but the engine has never ticked"
            )
        return True, f"{len(st['rules'])} rules armed, no live runs yet{errors}"
    age = time.time() - st["last_tick_at"]
    if gangs and age > max(10.0, 10 * st["interval_s"]):
        return False, (
            f"last tick {age:.0f}s ago with {len(gangs)} live gang(s){errors}"
        )
    return True, (
        f"{len(st['rules'])} rules, {st['ticks']} ticks, "
        f"last {age:.1f}s ago{errors}"
    )


def check_remediation(orch) -> Tuple[bool, str]:
    """Remediation-engine posture: wired, enabled, and whether its
    reactions are erroring (counted, never raised — same contract as the
    alert engine).  Reaction errors with zero successful actions mean the
    reflex arc is broken, not merely noisy."""
    engine = getattr(orch, "remediation", None)
    if engine is None:
        return True, "remediation engine not wired"
    try:
        st = engine.status()
    except Exception as e:
        return False, f"status() failed: {type(e).__name__}: {e}"
    if not st["enabled"]:
        return True, "disabled (POLYAXON_TPU_REMEDIATION_ENABLED=0)"
    if st["errors"] and not st["actions"]:
        return False, f"{st['errors']} reaction error(s), no action succeeded"
    evict = "on" if st["evict_enabled"] else "off"
    errors = f", {st['errors']} reaction error(s)" if st["errors"] else ""
    return True, (
        f"enabled, {st['actions']} action(s), budget {st['budget']}/run, "
        f"evict {evict}{errors}"
    )


def check_fleet(orch) -> Tuple[bool, str]:
    """Serving-fleet posture: replica states, ejections, and shed rate
    per registered fleet.  No fleets is fine (most control planes serve
    nothing); a fleet whose every replica is unroutable is not — traffic
    is being refused while the registry thinks the runs are healthy."""
    fleets = getattr(orch, "fleets", None) or []
    if not fleets:
        return True, "no serving fleets registered"
    parts = []
    ok = True
    for fleet in fleets:
        try:
            st = fleet.status()
        except Exception as e:
            ok = False
            parts.append(f"{getattr(fleet, 'name', '?')}: status() failed: {e}")
            continue
        router = st.get("router") or {}
        by_state = router.get("by_state") or {}
        n_ready = int(router.get("n_ready") or 0)
        total = sum(by_state.values())
        counters = router.get("counters") or {}
        if total and not n_ready:
            ok = False
        states = ", ".join(f"{k}={v}" for k, v in sorted(by_state.items()))
        parts.append(
            f"{st.get('name', '?')}: {n_ready}/{total} ready"
            + (f" ({states})" if states else "")
            + f", ejections {counters.get('ejections', 0)}"
            + f", shed rate {router.get('shed_rate', 0.0):.2%}"
            + (
                f", {len(st.get('open_ops') or {})} drain/replace open"
                if st.get("open_ops")
                else ""
            )
        )
    return ok, "; ".join(parts)


def check_autoscaler(orch) -> Tuple[bool, str]:
    """Autoscaler posture per fleet: state, last decision, and budget
    headroom.  No autoscaled fleet is fine (fixed-size fleets are a
    choice); an autoscaler with zero budget remaining is diagnostic —
    the fleet can no longer self-size and an operator should know."""
    fleets = getattr(orch, "fleets", None) or []
    scalers = [
        f.autoscaler
        for f in fleets
        if getattr(f, "autoscaler", None) is not None
    ]
    if not scalers:
        return True, "no fleet autoscaler attached"
    parts = []
    for scaler in scalers:
        try:
            st = scaler.status()
        except Exception as e:
            return False, f"status() failed: {type(e).__name__}: {e}"
        last = st.get("last_decision") or {}
        decision = (
            f"last {last.get('direction')}:{last.get('outcome')}"
            if last
            else "no decisions yet"
        )
        parts.append(
            f"{st['fleet']}: {st['state']}"
            + ("" if st["enabled"] else " (disabled)")
            + f", target {st['target_replicas']} "
            + f"[{st['min_replicas']}..{st['max_replicas']}]"
            + f", shed {st['shed_rate']:.2%}, occ {st['occupancy']:.2f}"
            + f", {decision}, budget {st['budget_remaining']}/{st['budget']}"
        )
    return True, "; ".join(parts)


def check_static_analysis(orch) -> Tuple[bool, str]:
    """graft-lint posture: what the last recorded run found, and whether
    it is stale.  Never-run and stale are diagnostic (ok=True) — a fresh
    deployment hasn't linted yet and that shouldn't page anyone; recorded
    *unsuppressed findings* are a real defect signal (ok=False)."""
    from polyaxon_tpu.analysis.reporter import read_state, state_file_path
    from polyaxon_tpu.conf.knobs import knob_float

    state = read_state()
    if state is None:
        return True, (
            f"never run (no state at {state_file_path()}; "
            "run `python -m polyaxon_tpu.analysis` or `make lint`)"
        )
    rules = ", ".join(
        f"{rid} v{meta['version']}"
        for rid, meta in sorted((state.get("rules") or {}).items())
    )
    age = time.time() - float(state.get("ts", 0.0))
    stale_after = knob_float("POLYAXON_TPU_LINT_STALE_S")
    unsuppressed = int(state.get("unsuppressed", 0))
    suppressed = int(state.get("suppressed", 0))
    if unsuppressed:
        by_rule = state.get("by_rule") or {}
        worst = ", ".join(f"{k}={v}" for k, v in sorted(by_rule.items()))
        return False, (
            f"last run recorded {unsuppressed} unsuppressed finding(s) "
            f"({worst}) {age:.0f}s ago [{rules}]"
        )
    freshness = (
        f"stale ({age / 86400.0:.1f}d old)" if age > stale_after
        else f"{age:.0f}s old"
    )
    return True, (
        f"clean, {suppressed} suppressed finding(s), {freshness} [{rules}]"
    )


def check_devices(orch) -> Tuple[bool, str]:
    """Accelerator visibility — only meaningful in-process on a worker/bench
    host; the control plane itself may legitimately be CPU-only."""
    try:
        import jax

        n = jax.local_device_count()
        kind = jax.devices()[0].device_kind
        return True, f"{n}x {kind}"
    except Exception as e:
        return False, f"no accelerator backend: {e}"


CHECKS: Dict[str, Callable] = {
    "registry": check_registry,
    "bus": check_bus,
    "stores": check_stores,
    "heartbeats": check_heartbeats,
    "compile_cache": check_compile_cache,
    "alerts": check_alerts,
    "remediation": check_remediation,
    "fleet": check_fleet,
    "autoscaler": check_autoscaler,
    "static_analysis": check_static_analysis,
}


def run_health_checks(orch, include_devices: bool = False) -> Dict[str, Any]:
    checks = dict(CHECKS)
    if include_devices:
        checks["devices"] = check_devices
    results = {}
    healthy = True
    for name, fn in checks.items():
        try:
            ok, detail = fn(orch)
        except Exception as e:  # a probe crashing is itself a failure
            ok, detail = False, f"probe crashed: {e}"
        results[name] = {"ok": ok, "detail": detail}
        healthy = healthy and ok
    return {"healthy": healthy, "checks": results, "at": time.time()}


def task_counter_snapshot(orch, top: int = 20) -> Dict[str, int]:
    """Top task counters from an in-memory stats backend ({} otherwise).

    Uses the backend's locked ``snapshot()``: the bus thread inserts keys
    concurrently and iterating the live mapping would race.
    """
    stats = getattr(orch, "stats", None)
    snapshot = getattr(stats, "snapshot", None)
    if snapshot is None:
        return {}
    counters = snapshot().get("counters") or {}
    return dict(sorted(counters.items(), key=lambda kv: -kv[1])[:top])
