from polyaxon_tpu.checks.health import run_health_checks, task_counter_snapshot

__all__ = ["run_health_checks", "task_counter_snapshot"]
