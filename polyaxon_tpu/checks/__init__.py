from polyaxon_tpu.checks.health import run_health_checks

__all__ = ["run_health_checks"]
