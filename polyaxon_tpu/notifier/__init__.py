from polyaxon_tpu.notifier.service import Notifier
from polyaxon_tpu.notifier.actions import (
    Action,
    CallbackAction,
    LogAction,
    WebhookAction,
)

__all__ = ["Action", "CallbackAction", "LogAction", "Notifier", "WebhookAction"]
