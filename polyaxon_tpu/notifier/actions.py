"""Notification actions.

Parity: reference ``notifier/actions/`` + ``actions/registry/webhooks/``
(Slack/Discord/HipChat/Mattermost/PagerDuty webhook senders + email).  The
provider-specific payload dialects collapse to one generic JSON webhook
with a payload-shaping hook (a Slack shaper is included as the worked
example); the in-process ``CallbackAction`` replaces email for embedded
deployments.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

Payload = Dict[str, Any]


class Action:
    """One notification sink. Subclasses implement ``_execute``."""

    name = "action"
    #: True = the notifier dispatches this action on a background thread —
    #: required for network sinks, which must never stall the task-bus
    #: thread that records events (the reference offloaded these to a
    #: celery worker hop).
    async_dispatch = False

    def execute(self, payload: Payload) -> bool:
        try:
            self._execute(payload)
            return True
        except Exception:
            # Notification failure must never break orchestration.
            logger.exception("Notification action %s failed", self.name)
            return False

    def _execute(self, payload: Payload) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class LogAction(Action):
    name = "log"

    def __init__(self, level: int = logging.INFO) -> None:
        self.level = level

    def _execute(self, payload: Payload) -> None:
        logger.log(self.level, "event %s: %s", payload.get("event_type"), payload)


class CallbackAction(Action):
    name = "callback"

    def __init__(self, fn: Callable[[Payload], None]) -> None:
        self.fn = fn

    def _execute(self, payload: Payload) -> None:
        self.fn(payload)


def _event_summary(payload: Payload) -> str:
    event = payload.get("event_type", "event")
    ctx = {k: v for k, v in payload.items() if k != "event_type"}
    detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
    return f"polyaxon-tpu {event} {detail}"


def slack_shaper(payload: Payload) -> Payload:
    """Shape a platform event as a Slack webhook message."""
    event = payload.get("event_type", "event")
    ctx = {k: v for k, v in payload.items() if k != "event_type"}
    detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
    return {"text": f":robot_face: polyaxon-tpu *{event}* {detail}"}


def discord_shaper(payload: Payload) -> Payload:
    """Discord webhook dialect (reference discord_webhook.py)."""
    return {"content": _event_summary(payload)}


def mattermost_shaper(payload: Payload) -> Payload:
    """Mattermost incoming-webhook dialect (reference mattermost_webhook.py)."""
    event = payload.get("event_type", "event")
    ctx = {k: v for k, v in payload.items() if k != "event_type"}
    detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
    return {"text": f"**{event}** {detail}", "username": "polyaxon-tpu"}


def pagerduty_shaper(routing_key: str) -> Callable[[Payload], Payload]:
    """PagerDuty Events-API-v2 dialect (reference pagerduty_webhook.py).

    A factory: PagerDuty needs the integration routing key in the body.
    Failure-ish events page as errors, everything else as info.
    """

    def shape(payload: Payload) -> Payload:
        event = payload.get("event_type", "")
        severity = (
            "error"
            if event.endswith((".failed", ".zombie"))
            else "info"
        )
        return {
            "routing_key": routing_key,
            "event_action": "trigger",
            "payload": {
                "summary": _event_summary(payload),
                "source": "polyaxon-tpu",
                "severity": severity,
                "custom_details": {
                    k: v for k, v in payload.items() if k != "event_type"
                },
            },
        }

    return shape


#: Named webhook dialects selectable from conf (notifier.webhook_kind).
SHAPERS: Dict[str, Callable[[Payload], Payload]] = {
    "slack": slack_shaper,
    "discord": discord_shaper,
    "mattermost": mattermost_shaper,
}


class EmailAction(Action):
    """SMTP notification (reference ``actions/registry/email_action.py``).

    ``transport`` is injectable for tests; the default speaks smtplib with
    optional STARTTLS + login.
    """

    name = "email"
    async_dispatch = True

    def __init__(
        self,
        *,
        host: str,
        sender: str,
        recipients,
        port: int = 25,
        use_tls: bool = False,
        username: Optional[str] = None,
        password: Optional[str] = None,
        timeout: float = 10.0,
        transport: Optional[Callable[[str, Payload], None]] = None,
    ) -> None:
        self.host = host
        self.port = port
        self.sender = sender
        self.recipients = list(recipients)
        self.use_tls = use_tls
        self.username = username
        self.password = password
        self.timeout = timeout
        self._transport = transport

    def _execute(self, payload: Payload) -> None:
        from email.message import EmailMessage

        msg = EmailMessage()
        msg["Subject"] = _event_summary(payload)[:120]
        msg["From"] = self.sender
        msg["To"] = ", ".join(self.recipients)
        msg.set_content(json.dumps(payload, indent=2, default=str))
        if self._transport is not None:
            self._transport(msg.as_string(), payload)
            return
        import smtplib

        with smtplib.SMTP(self.host, self.port, timeout=self.timeout) as smtp:
            if self.use_tls:
                smtp.starttls()
            if self.username:
                smtp.login(self.username, self.password or "")
            smtp.send_message(msg)


class WebhookAction(Action):
    """Generic JSON webhook, hardened for alert duty.

    Connection-level failures (refused, DNS, timeout, 5xx) retry with
    exponential backoff up to ``max_attempts``; a 4xx is the receiver
    rejecting the payload and retrying would just repeat it.  After the
    final failure a dead-letter line carries the payload summary — a lost
    page must be visible in the control-plane log, never silent.
    """

    name = "webhook"
    async_dispatch = True

    def __init__(
        self,
        url: str,
        shaper: Optional[Callable[[Payload], Payload]] = None,
        timeout: float = 5.0,
        headers: Optional[Dict[str, str]] = None,
        max_attempts: int = 3,
        backoff_s: float = 0.5,
    ) -> None:
        self.url = url
        self.shaper = shaper
        self.timeout = timeout
        self.headers = {"Content-Type": "application/json", **(headers or {})}
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = backoff_s

    def _post_once(self, data: bytes) -> None:
        req = urllib.request.Request(self.url, data=data, headers=self.headers)
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass

    def _execute(self, payload: Payload) -> None:
        body = self.shaper(payload) if self.shaper else payload
        data = json.dumps(body, default=str).encode()
        delay = self.backoff_s
        for attempt in range(1, self.max_attempts + 1):
            try:
                self._post_once(data)
                return
            except urllib.error.HTTPError as exc:
                if exc.code < 500 or attempt >= self.max_attempts:
                    self._dead_letter(payload, attempt, exc)
                    raise
            except (urllib.error.URLError, ConnectionError, OSError) as exc:
                if attempt >= self.max_attempts:
                    self._dead_letter(payload, attempt, exc)
                    raise
            time.sleep(delay)
            delay *= 2

    def _dead_letter(
        self, payload: Payload, attempts: int, exc: Exception
    ) -> None:
        logger.error(
            "webhook dead-letter: %s undeliverable to %s after %d attempt(s)"
            " (%s): %s",
            payload.get("event_type", "event"),
            self.url,
            attempts,
            exc,
            json.dumps(payload, default=str)[:2000],
        )
