"""Notification actions.

Parity: reference ``notifier/actions/`` + ``actions/registry/webhooks/``
(Slack/Discord/HipChat/Mattermost/PagerDuty webhook senders + email).  The
provider-specific payload dialects collapse to one generic JSON webhook
with a payload-shaping hook (a Slack shaper is included as the worked
example); the in-process ``CallbackAction`` replaces email for embedded
deployments.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

Payload = Dict[str, Any]


class Action:
    """One notification sink. Subclasses implement ``_execute``."""

    name = "action"
    #: True = the notifier dispatches this action on a background thread —
    #: required for network sinks, which must never stall the task-bus
    #: thread that records events (the reference offloaded these to a
    #: celery worker hop).
    async_dispatch = False

    def execute(self, payload: Payload) -> bool:
        try:
            self._execute(payload)
            return True
        except Exception:
            # Notification failure must never break orchestration.
            logger.exception("Notification action %s failed", self.name)
            return False

    def _execute(self, payload: Payload) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class LogAction(Action):
    name = "log"

    def __init__(self, level: int = logging.INFO) -> None:
        self.level = level

    def _execute(self, payload: Payload) -> None:
        logger.log(self.level, "event %s: %s", payload.get("event_type"), payload)


class CallbackAction(Action):
    name = "callback"

    def __init__(self, fn: Callable[[Payload], None]) -> None:
        self.fn = fn

    def _execute(self, payload: Payload) -> None:
        self.fn(payload)


def slack_shaper(payload: Payload) -> Payload:
    """Shape a platform event as a Slack webhook message."""
    event = payload.get("event_type", "event")
    ctx = {k: v for k, v in payload.items() if k != "event_type"}
    detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
    return {"text": f":robot_face: polyaxon-tpu *{event}* {detail}"}


class WebhookAction(Action):
    name = "webhook"
    async_dispatch = True

    def __init__(
        self,
        url: str,
        shaper: Optional[Callable[[Payload], Payload]] = None,
        timeout: float = 5.0,
        headers: Optional[Dict[str, str]] = None,
    ) -> None:
        self.url = url
        self.shaper = shaper
        self.timeout = timeout
        self.headers = {"Content-Type": "application/json", **(headers or {})}

    def _execute(self, payload: Payload) -> None:
        body = self.shaper(payload) if self.shaper else payload
        req = urllib.request.Request(
            self.url, data=json.dumps(body, default=str).encode(), headers=self.headers
        )
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass
