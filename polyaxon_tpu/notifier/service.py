"""Notifier: auditor subscriber fanning events to actions.

Parity: reference ``notifier/service.py`` — consumes the EVENTS_NOTIFY fan
-out and dispatches to configured actions, filtered per event type.  Here
it subscribes to the auditor directly (the celery hop collapses away).
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Optional, Sequence

from polyaxon_tpu.events import Event
from polyaxon_tpu.notifier.actions import Action


class Notifier:
    """Subscribe to an :class:`~polyaxon_tpu.auditor.Auditor`.

    Actions flagged ``async_dispatch`` (network sinks) run on daemon
    threads so a slow/unreachable endpoint can't stall the bus thread
    recording the event.
    """

    def __init__(
        self,
        actions: Sequence[Action],
        event_types: Optional[Iterable[str]] = None,
    ) -> None:
        self.actions: List[Action] = list(actions)
        #: None = all events; else a whitelist
        self.event_types = set(event_types) if event_types is not None else None

    def __call__(self, event: Event) -> None:
        if self.event_types is not None and event.event_type not in self.event_types:
            return
        payload = {"event_type": event.event_type, **event.context}
        for action in self.actions:
            if action.async_dispatch:
                threading.Thread(
                    target=action.execute,
                    args=(payload,),
                    name=f"notify-{action.name}",
                    daemon=True,
                ).start()
            else:
                action.execute(payload)
