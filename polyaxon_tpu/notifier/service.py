"""Notifier: auditor subscriber fanning events to actions.

Parity: reference ``notifier/service.py`` — consumes the EVENTS_NOTIFY fan
-out and dispatches to configured actions, filtered per event type.  Here
it subscribes to the auditor directly (the celery hop collapses away).
"""

from __future__ import annotations

import threading
from typing import Iterable, List, Optional, Sequence

from polyaxon_tpu.events import Event
from polyaxon_tpu.notifier.actions import Action


class Notifier:
    """Subscribe to an :class:`~polyaxon_tpu.auditor.Auditor`.

    Actions flagged ``async_dispatch`` (network sinks) run on daemon
    threads so a slow/unreachable endpoint can't stall the bus thread
    recording the event.
    """

    def __init__(
        self,
        actions: Sequence[Action],
        event_types: Optional[Iterable[str]] = None,
    ) -> None:
        self.actions: List[Action] = list(actions)
        #: None = all events; else a whitelist
        self.event_types = set(event_types) if event_types is not None else None
        self._inflight: List[threading.Thread] = []

    def __call__(self, event: Event) -> None:
        if self.event_types is not None and event.event_type not in self.event_types:
            return
        payload = {"event_type": event.event_type, **event.context}
        for action in self.actions:
            if action.async_dispatch:
                t = threading.Thread(
                    target=action.execute,
                    args=(payload,),
                    name=f"notify-{action.name}",
                    daemon=True,
                )
                t.start()
                self._inflight = [x for x in self._inflight if x.is_alive()]
                self._inflight.append(t)
            else:
                action.execute(payload)

    def flush(self, timeout: float = 5.0) -> None:
        """Wait for in-flight async notifications (call before exit, or the
        terminal-event webhook dies with the process)."""
        import time

        deadline = time.time() + timeout
        for t in self._inflight:
            t.join(timeout=max(0.0, deadline - time.time()))
        self._inflight = [x for x in self._inflight if x.is_alive()]
