"""Notifier: auditor subscriber fanning events to actions.

Parity: reference ``notifier/service.py`` — consumes the EVENTS_NOTIFY fan
-out and dispatches to configured actions, filtered per event type.  Here
it subscribes to the auditor directly (the celery hop collapses away).

:class:`AlertRouter` is the alert-engine flavor: same dispatch machinery,
but the action set is *named sinks* selected per event by a
severity → sinks routing map (``critical:webhook,email;info:log``) — a
page-worthy alert and an informational one should not share a channel.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence

from polyaxon_tpu.events import Event
from polyaxon_tpu.notifier.actions import Action
from polyaxon_tpu.stats.metrics import labeled_key

logger = logging.getLogger(__name__)


class Notifier:
    """Subscribe to an :class:`~polyaxon_tpu.auditor.Auditor`.

    Actions flagged ``async_dispatch`` (network sinks) run on daemon
    threads so a slow/unreachable endpoint can't stall the bus thread
    recording the event.  With a ``stats`` backend attached, every
    dispatch lands on a ``notifier_dispatch{action,outcome}`` counter —
    exported as ``polyaxon_tpu_notifier_dispatch_total`` on ``/metrics``,
    so delivery failures are graphable, not just greppable.
    """

    def __init__(
        self,
        actions: Sequence[Action],
        event_types: Optional[Iterable[str]] = None,
        *,
        stats: Any = None,
    ) -> None:
        self.actions: List[Action] = list(actions)
        #: None = all events; else a whitelist
        self.event_types = set(event_types) if event_types is not None else None
        self.stats = stats
        self._inflight: List[threading.Thread] = []

    def __call__(self, event: Event) -> None:
        if self.event_types is not None and event.event_type not in self.event_types:
            return
        payload = {"event_type": event.event_type, **event.context}
        self._dispatch(self.actions, payload)

    def _dispatch(self, actions: Sequence[Action], payload: Dict[str, Any]) -> None:
        for action in actions:
            if action.async_dispatch:
                t = threading.Thread(
                    target=self._run_action,
                    args=(action, payload),
                    name=f"notify-{action.name}",
                    daemon=True,
                )
                t.start()
                self._inflight = [x for x in self._inflight if x.is_alive()]
                self._inflight.append(t)
            else:
                self._run_action(action, payload)

    def _run_action(self, action: Action, payload: Dict[str, Any]) -> bool:
        ok = action.execute(payload)
        if self.stats is not None:
            self.stats.incr(
                labeled_key(
                    "notifier_dispatch",
                    action=action.name,
                    outcome="ok" if ok else "error",
                )
            )
        return ok

    def flush(self, timeout: float = 5.0) -> None:
        """Wait for in-flight async notifications (call before exit, or the
        terminal-event webhook dies with the process)."""
        import time

        deadline = time.time() + timeout
        for t in self._inflight:
            t.join(timeout=max(0.0, deadline - time.time()))
        self._inflight = [x for x in self._inflight if x.is_alive()]


#: Routing fallback: severities not named in the map go to every sink.
ROUTE_ALL = "*"


def parse_alert_routes(spec: Optional[str]) -> Dict[str, List[str]]:
    """``"critical:webhook,email;warning:webhook;info:log"`` → map.

    Empty/None means route everything everywhere (the safe default for a
    deployment with one webhook configured).  Unknown sink names are kept
    here and warned about at dispatch time — conf validation must not
    depend on which sinks happen to be constructed.
    """
    routes: Dict[str, List[str]] = {}
    if not spec:
        return routes
    for clause in str(spec).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        sev, _, sinks = clause.partition(":")
        routes[sev.strip().lower()] = [
            s.strip() for s in sinks.split(",") if s.strip()
        ]
    return routes


class AlertRouter(Notifier):
    """Severity-routed alert fan-out over named sinks.

    Subscribes to the auditor for ``alert.firing`` / ``alert.resolved``
    events; the payload's ``severity`` picks the sink subset.  Resolved
    notifications follow the same route as their firing — the channel
    that got paged is the channel that learns it's over.
    """

    def __init__(
        self,
        sinks: Mapping[str, Action],
        *,
        routes: Optional[Dict[str, List[str]]] = None,
        event_types: Optional[Iterable[str]] = None,
        stats: Any = None,
    ) -> None:
        if event_types is None:
            from polyaxon_tpu.events import EventTypes

            event_types = (EventTypes.ALERT_FIRING, EventTypes.ALERT_RESOLVED)
        super().__init__(list(sinks.values()), event_types, stats=stats)
        self.sinks: Dict[str, Action] = dict(sinks)
        self.routes: Dict[str, List[str]] = dict(routes or {})

    def sinks_for(self, severity: str) -> List[Action]:
        names = self.routes.get(
            str(severity).lower(), self.routes.get(ROUTE_ALL)
        )
        if names is None:
            return list(self.sinks.values())
        out: List[Action] = []
        for name in names:
            sink = self.sinks.get(name)
            if sink is None:
                logger.warning(
                    "Alert route names unknown sink %r (have: %s)",
                    name,
                    sorted(self.sinks),
                )
            else:
                out.append(sink)
        return out

    def __call__(self, event: Event) -> None:
        if self.event_types is not None and event.event_type not in self.event_types:
            return
        payload = {"event_type": event.event_type, **event.context}
        self._dispatch(self.sinks_for(payload.get("severity", "")), payload)
