from polyaxon_tpu.executor.handlers import ExecutorHandlers

__all__ = ["ExecutorHandlers"]
