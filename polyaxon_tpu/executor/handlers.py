"""Event → follow-up-action state machine.

Parity: reference ``executor/handlers/experiment.py:12-118`` (and the other
per-entity handlers): EXPERIMENT_CREATED → send build task; build done →
start; SUCCEEDED/FAILED/DONE → stop/cleanup and, for grouped experiments,
kick the next hpsearch wave.  The handler layer only *sends named tasks* —
it never touches the spawner directly — so orchestration policy stays in
one written-down place.
"""

from __future__ import annotations

import logging

from polyaxon_tpu.events import Event, EventTypes
from polyaxon_tpu.workers import HPTasks, PipelineTasks, SchedulerTasks, TaskBus

logger = logging.getLogger(__name__)


class ExecutorHandlers:
    """Subscribes to the auditor; translates events into bus sends."""

    def __init__(self, bus: TaskBus) -> None:
        self.bus = bus
        self._table = {
            EventTypes.EXPERIMENT_CREATED: self._experiment_created,
            EventTypes.EXPERIMENT_RESUMED: self._experiment_created,
            # EXPERIMENT_RESTARTED is audit-only: the monitor task schedules
            # the relaunch itself (with the restart-policy backoff); reacting
            # here would dispatch a second, backoff-free START.
            EventTypes.EXPERIMENT_BUILD_DONE: self._experiment_build_done,
            EventTypes.EXPERIMENT_DONE: self._experiment_done,
            EventTypes.GROUP_CREATED: self._group_created,
            EventTypes.PIPELINE_CREATED: self._pipeline_created,
            EventTypes.OPERATION_DONE: self._operation_done,
        }

    def __call__(self, event: Event) -> None:
        handler = self._table.get(event.event_type)
        if handler is not None:
            handler(event)

    # -- experiments ----------------------------------------------------------
    def _experiment_created(self, event: Event) -> None:
        # CREATED → build (code snapshot). The build task itself decides
        # whether a snapshot is needed and chains to start (the reference's
        # image-exists short-circuit, scheduler/dockerizer_scheduler.py:30-88).
        self.bus.send(SchedulerTasks.EXPERIMENTS_BUILD, {"run_id": event.context["run_id"]})

    def _experiment_build_done(self, event: Event) -> None:
        self.bus.send(SchedulerTasks.EXPERIMENTS_START, {"run_id": event.context["run_id"]})

    def _experiment_done(self, event: Event) -> None:
        run_id = event.context["run_id"]
        group_id = event.context.get("group_id")
        pipeline_id = event.context.get("pipeline_id")
        # Cleanup/stop of any leftover gang state.
        self.bus.send(SchedulerTasks.EXPERIMENTS_STOP, {"run_id": run_id, "cleanup": True})
        if group_id is not None:
            # Next hpsearch wave (reference: HP_START on experiment done).
            self.bus.send(HPTasks.START, {"group_id": group_id})
        if pipeline_id is not None:
            self.bus.send(PipelineTasks.CHECK, {"pipeline_id": pipeline_id})

    # -- groups ---------------------------------------------------------------
    def _group_created(self, event: Event) -> None:
        self.bus.send(HPTasks.CREATE, {"group_id": event.context["group_id"]})

    # -- pipelines ------------------------------------------------------------
    def _pipeline_created(self, event: Event) -> None:
        self.bus.send(PipelineTasks.START, {"pipeline_id": event.context["pipeline_id"]})

    def _operation_done(self, event: Event) -> None:
        self.bus.send(PipelineTasks.CHECK, {"pipeline_id": event.context["pipeline_id"]})
