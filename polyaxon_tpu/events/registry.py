"""Declarative event registry.

Parity: the reference's ``events/`` package — ``Event``/``Attribute`` classes
(``events/event.py:17,41``) plus ~20 per-subject registry modules
(``events/registry/{experiment,experiment_group,pipeline,...}.py``).  The
TPU-native version keeps the two load-bearing pieces — stable dotted event
names and a serializable payload — and drops the marshmallow-style attribute
declarations (payloads are plain dicts; the registry db stores them as JSON).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict


def _subject_events(subject: str, *actions: str) -> Dict[str, str]:
    return {a.upper(): f"{subject}.{a}" for a in actions}


class EventTypes:
    """Dotted event-type names, ``<subject>.<action>``."""

    # experiments (reference events/registry/experiment.py)
    EXPERIMENT_CREATED = "experiment.created"
    EXPERIMENT_RESUMED = "experiment.resumed"
    EXPERIMENT_RESTARTED = "experiment.restarted"
    EXPERIMENT_COPIED = "experiment.copied"
    EXPERIMENT_BUILD_STARTED = "experiment.build_started"
    EXPERIMENT_BUILD_DONE = "experiment.build_done"
    EXPERIMENT_NEW_STATUS = "experiment.new_status"
    EXPERIMENT_NEW_METRIC = "experiment.new_metric"
    EXPERIMENT_SUCCEEDED = "experiment.succeeded"
    EXPERIMENT_FAILED = "experiment.failed"
    EXPERIMENT_STOPPED = "experiment.stopped"
    EXPERIMENT_DONE = "experiment.done"
    EXPERIMENT_ZOMBIE = "experiment.zombie"
    EXPERIMENT_COMMAND_SENT = "experiment.command_sent"
    # remediation (the monitor/remediation.py detection→action loop)
    EXPERIMENT_REMEDIATION = "experiment.remediation"
    EXPERIMENT_EVICTED = "experiment.evicted"
    EXPERIMENT_PROFILE_REQUESTED = "experiment.profile_requested"
    EXPERIMENT_ARTIFACTS_SYNCED = "experiment.artifacts_synced"
    EXPERIMENT_ARCHIVED = "experiment.archived"
    EXPERIMENT_RESTORED = "experiment.restored"
    EXPERIMENT_DELETED = "experiment.deleted"

    # groups (events/registry/experiment_group.py)
    GROUP_CREATED = "group.created"
    GROUP_NEW_STATUS = "group.new_status"
    GROUP_ITERATION = "group.iteration"
    GROUP_DONE = "group.done"
    GROUP_STOPPED = "group.stopped"

    # jobs / services
    JOB_CREATED = "job.created"
    JOB_NEW_STATUS = "job.new_status"
    JOB_DONE = "job.done"

    # pipelines (events/registry/pipeline.py)
    PIPELINE_CREATED = "pipeline.created"
    PIPELINE_NEW_STATUS = "pipeline.new_status"
    PIPELINE_DONE = "pipeline.done"
    OPERATION_NEW_STATUS = "operation.new_status"
    OPERATION_DONE = "operation.done"

    # cluster / platform
    CLUSTER_NODE_UPDATED = "cluster.node_updated"
    PLATFORM_HEALTH = "platform.health"

    # alerts (the monitor/alerts.py rule engine's lifecycle edges)
    ALERT_FIRING = "alert.firing"
    ALERT_RESOLVED = "alert.resolved"

    # entities (events/registry/{project,user,search,bookmark}.py)
    PROJECT_CREATED = "project.created"
    PROJECT_DELETED = "project.deleted"
    PROJECT_SHARED = "project.shared"
    PROJECT_UNSHARED = "project.unshared"
    USER_CREATED = "user.created"
    USER_DELETED = "user.deleted"
    SEARCH_CREATED = "search.created"
    SEARCH_DELETED = "search.deleted"
    BOOKMARK_ADDED = "bookmark.added"
    BOOKMARK_REMOVED = "bookmark.removed"

    # CI (reference api/ci/ + ci/service.py)
    CI_SET = "ci.set"
    CI_DELETED = "ci.deleted"
    CI_TRIGGERED = "ci.triggered"

    # chart views (reference events/registry/chart_view.py)
    CHART_VIEW_CREATED = "chart_view.created"
    CHART_VIEW_DELETED = "chart_view.deleted"


def created_event_for_kind(kind: str):
    """(event_type, id_key) announcing a freshly created run of ``kind`` —
    the single mapping behind orchestrator.submit and the CI trigger, so
    a new kind can't get created-event wiring in one and not the other."""
    table = {
        "experiment": (EventTypes.EXPERIMENT_CREATED, "run_id"),
        "job": (EventTypes.EXPERIMENT_CREATED, "run_id"),
        "build": (EventTypes.EXPERIMENT_CREATED, "run_id"),
        "group": (EventTypes.GROUP_CREATED, "group_id"),
        "pipeline": (EventTypes.PIPELINE_CREATED, "pipeline_id"),
    }
    return table.get(kind, (EventTypes.EXPERIMENT_CREATED, "run_id"))


@dataclass
class Event:
    """A recorded platform event (stored in the registry's activity table)."""

    event_type: str
    context: Dict[str, Any] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)

    @property
    def subject(self) -> str:
        return self.event_type.split(".", 1)[0]

    @property
    def action(self) -> str:
        return self.event_type.split(".", 1)[1]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "event_type": self.event_type,
            "context": self.context,
            "created_at": self.created_at,
        }
