from polyaxon_tpu.events.registry import Event, EventTypes

__all__ = ["Event", "EventTypes"]
