from polyaxon_tpu.events.registry import Event, EventTypes, created_event_for_kind

__all__ = ["Event", "EventTypes", "created_event_for_kind"]
