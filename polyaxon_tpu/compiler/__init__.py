from polyaxon_tpu.compiler.service import GangPlan, compile_gang_plan, compile_spec

__all__ = ["GangPlan", "compile_gang_plan", "compile_spec"]
