from polyaxon_tpu.compiler.service import compile_spec

__all__ = ["compile_spec"]
