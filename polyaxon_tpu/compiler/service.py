"""Spec compiler: raw values -> validated specification -> executable plan.

Parity: reference ``polyaxon/compiler/service.py:9-20`` (``compile(kind,
values) -> BaseSpecification`` dispatching to per-kind managers).  The
TPU-native compiler goes one step further than the reference: beyond
validating the document, it emits a ``GangPlan`` — the concrete process
topology (host count, devices/host, mesh axes, coordinator port assignment
strategy, per-process env) the spawner executes.  This subsumes the
reference's cluster_def/TF_CONFIG assembly (``polypod/tensorflow.py:160-203``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Union

from polyaxon_tpu.exceptions import CompilerError
from polyaxon_tpu.schemas.polyaxonfile import PolyaxonFile
from polyaxon_tpu.schemas.specifications import BaseSpecification


@dataclass(frozen=True)
class GangPlan:
    """Everything the spawner needs to launch one gang."""

    num_hosts: int
    devices_per_host: int
    mesh_axes: Dict[str, int]
    strategy: str
    strategy_options: Dict[str, Any] = field(default_factory=dict)
    accelerator: str = "cpu"
    env_vars: Dict[str, str] = field(default_factory=dict)
    max_restarts: int = 0
    backoff_seconds: float = 1.0
    #: Service kinds only (notebook/tensorboard): the port the service must
    #: bind. None = not a service; 0 = allocate at dispatch time.
    service_port: Optional[int] = None
    #: Cross-slice (DCN) mesh axes, subset of ``mesh_axes``; empty for
    #: single-slice gangs. The worker builds a hybrid device mesh from the
    #: split so DCN axes never land on ICI-hungry dimensions.
    dcn_axes: Dict[str, int] = field(default_factory=dict)

    @property
    def world_size(self) -> int:
        return self.num_hosts

    @property
    def num_devices(self) -> int:
        return self.num_hosts * self.devices_per_host

    @property
    def num_slices(self) -> int:
        n = 1
        for size in self.dcn_axes.values():
            n *= int(size)
        return n


def compile_spec(
    values: Union[str, Dict[str, Any], BaseSpecification],
    kind: Optional[str] = None,
) -> BaseSpecification:
    """Validate raw values into a typed specification.

    ``kind`` (when given) must match the document's kind — the reference made
    the same check in its per-kind managers.
    """
    if isinstance(values, BaseSpecification):
        spec = values
    else:
        spec = PolyaxonFile.load(values).specification
    if kind is not None and spec.kind != kind:
        raise CompilerError(f"Spec kind {spec.kind!r} does not match requested {kind!r}")
    return spec


def compile_gang_plan(spec: BaseSpecification) -> GangPlan:
    """Emit the concrete gang topology for a runnable spec."""
    topo = spec.environment.topology
    try:
        ici_axes = topo.resolved_mesh()
        dcn_axes = topo.resolved_dcn()
    except ValueError as e:
        raise CompilerError(str(e)) from e
    # The combined logical mesh (templates consume it); DCN axes lead so the
    # hybrid mesh builder places them across slices.
    mesh_axes = {**dcn_axes, **ici_axes}
    # Service kinds carry a port in the plan (reference: the notebook/
    # tensorboard deployments' containerPort + service objects,
    # ``polypod/tensorboard.py:32``); 0 defers allocation to dispatch.
    # A user-declared `port` (the `cmd: ... {{port}}` shape) pins it too —
    # otherwise the advertised service_url would name a port the workload
    # never binds.
    service_port = getattr(spec, "port", None)
    if service_port == 0 and spec.declarations.get("port"):
        service_port = int(spec.declarations["port"])
    return GangPlan(
        num_hosts=int(topo.num_hosts) * int(topo.num_slices),
        devices_per_host=topo.devices_per_host,
        mesh_axes=mesh_axes,
        strategy=topo.strategy,
        strategy_options=dict(topo.strategy_options),
        accelerator=topo.accelerator,
        env_vars=dict(spec.environment.env_vars),
        max_restarts=spec.environment.restart_policy.max_restarts,
        backoff_seconds=spec.environment.restart_policy.backoff_seconds,
        service_port=service_port,
        dcn_axes=dcn_axes,
    )
