"""Ring attention: causal attention with the sequence sharded over a mesh axis.

Long-context sequence parallelism (SURVEY §5 names it as a required gap —
the reference has no analogue).  K/V blocks rotate around the mesh axis via
``lax.ppermute`` (each hop rides one ICI link) while every device keeps its
query shard resident; softmax is accumulated online (flash-style running
max/denominator), so the full [T, T] score matrix never materializes and
per-device HBM stays O(T_local).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax


def _ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, axis_name: str
) -> jax.Array:
    """Per-shard body. q/k/v: [B, T_local, H, d], contiguous seq shards."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, Tl, H, d = q.shape
    scale = d**-0.5
    q32 = q.astype(jnp.float32)

    q_pos = idx * Tl + jnp.arange(Tl)  # global positions of local queries
    m = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Tl), jnp.float32)
    o = jnp.zeros((B, Tl, H, d), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o, l, m, k, v = carry
        # After i hops along perm j->j+1, this device holds block (idx - i).
        src = (idx - i) % n
        k_pos = src * Tl + jnp.arange(Tl)
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, k.astype(jnp.float32)) * scale
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # Fully-masked-so-far rows keep m == -inf; guard the NaN-producing
        # exp(-inf - -inf) paths.
        alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_new))
        p = jnp.where(
            jnp.isneginf(m_new)[..., None], 0.0, jnp.exp(s - m_new[..., None])
        )
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        )
        k, v = lax.ppermute((k, v), axis_name, perm)
        return o, l, m_new, k, v

    o, l, m, k, v = lax.fori_loop(0, n, body, (o, l, m, k, v))
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    seq_axis: str,
    batch_axes: Union[str, Tuple[str, ...], None] = None,
    impl: str = "auto",
    block_q: int = 1024,
    block_k: int = 1024,
) -> jax.Array:
    """Global-view entry: q/k/v [B, T, H, d] with T sharded on ``seq_axis``.

    ``impl`` selects the per-shard body: ``"flash"`` runs the pallas flash
    kernel per ring block (O(T_local) memory — scores never leave VMEM;
    interpret mode off-TPU), ``"dense"`` the jnp blockwise body, ``"auto"``
    flash on TPU and dense elsewhere.
    """
    from jax.sharding import PartitionSpec as P

    from polyaxon_tpu.parallel.flash import _on_tpu
    from polyaxon_tpu.parallel.shmap import shard_map

    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"query heads ({q.shape[2]}) must be divisible by KV heads "
            f"({k.shape[2]}) for grouped-query attention"
        )
    if impl == "auto":
        impl = "flash" if _on_tpu() else "dense"
    if impl == "flash":
        from polyaxon_tpu.parallel.flash import ring_flash_attention

        d = q.shape[-1]
        cfg = (seq_axis, d**-0.5, block_q, block_k, not _on_tpu())
        body = partial(ring_flash_attention, cfg)
    elif impl == "dense":
        # The dense blockwise body is plain MHA; broadcast GQA KV heads to
        # the query heads up front (the flash body instead broadcasts
        # per hop so the ppermute payload stays Hkv-sized).
        group = q.shape[2] // k.shape[2]
        if group > 1:
            k = jnp.repeat(k, group, axis=2)
            v = jnp.repeat(v, group, axis=2)
        body = partial(_ring_attention, axis_name=seq_axis)
    else:
        raise ValueError(f"Unknown ring attention impl {impl!r}")

    spec = P(batch_axes, seq_axis, None, None)
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
